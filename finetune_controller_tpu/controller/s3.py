"""S3 object store — SigV4 over aiohttp behind the ``ObjectStore`` seam.

The reference is S3-native end to end: aioboto3 in the API process
(``app/utils/S3Handler.py:12-25``) and ``amazon/aws-cli`` init/sidecar
containers moving the heavy bytes (``PyTorchJobDeployer.py:74,142``).  This
engine restores that parity for deployments migrating off the reference —
same ``finetune_jobs/{user}/{job}/{dataset|artifacts}`` layout
(``S3Handler.py:46-71``) — without an SDK: request signing is ~80 lines of
stdlib SigV4 (RFC-style canonical request + HMAC chain), transport is the
same aiohttp session pattern as the GCS engine, and the endpoint is
injectable so the whole surface runs hermetically against an in-process fake
that *re-verifies every signature* (``tests/test_s3.py``) — the reference's
S3 path has zero tests.

Auth: an injectable async credentials provider; the default reads
``AWS_ACCESS_KEY_ID`` / ``AWS_SECRET_ACCESS_KEY`` / ``AWS_SESSION_TOKEN``
(the same env contract the reference's k8s Secret populates,
``app/core/config.py:59-90``).

Uploads: single signed PUT up to ``multipart_threshold``; S3 multipart
(Create/UploadPart/Complete) above it, so multi-GB checkpoint shards don't
buffer in memory.  Unknown-length streams spool through a temp file first —
S3 requires a Content-Length per request (aws-chunked streaming signatures
are deliberately out of scope).
"""

from __future__ import annotations

import asyncio
import base64
import datetime
import hashlib
import hmac
import logging
import os
import tempfile
import urllib.parse
import xml.etree.ElementTree as ET
from pathlib import Path
from typing import Any, AsyncIterator, Awaitable, Callable

from .objectstore import HttpObjectStore, build_uri, parse_uri

logger = logging.getLogger(__name__)

EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()
UNSIGNED = "UNSIGNED-PAYLOAD"

#: (access_key, secret_key, session_token or None)
CredsFn = Callable[[], Awaitable[tuple[str, str, str | None]]]


async def env_credentials() -> tuple[str, str, str | None]:
    try:
        return (
            os.environ["AWS_ACCESS_KEY_ID"],
            os.environ["AWS_SECRET_ACCESS_KEY"],
            os.environ.get("AWS_SESSION_TOKEN"),
        )
    except KeyError as e:
        raise RuntimeError(
            "S3 backend needs AWS_ACCESS_KEY_ID/AWS_SECRET_ACCESS_KEY in the "
            "environment (or an injected credentials provider)"
        ) from e


def _uri_encode(s: str, *, encode_slash: bool) -> str:
    """AWS canonical URI/query encoding: unreserved chars per RFC 3986 only."""
    safe = "-._~" + ("" if encode_slash else "/")
    return urllib.parse.quote(s, safe=safe)


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sigv4_headers(
    method: str,
    host: str,
    path: str,
    query: list[tuple[str, str]],
    *,
    payload_hash: str,
    access_key: str,
    secret_key: str,
    session_token: str | None = None,
    region: str = "us-east-1",
    service: str = "s3",
    amz_date: str | None = None,
    extra_headers: dict[str, str] | None = None,
    include_content_sha: bool = True,
) -> dict[str, str]:
    """Compute the signed header set for one request (AWS SigV4).

    Pure function of its inputs (``amz_date`` injectable) so tests can pin
    the official AWS known-answer vectors and the in-process fake server can
    re-derive and verify every signature.
    """
    now = amz_date or datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y%m%dT%H%M%SZ"
    )
    datestamp = now[:8]
    headers = {
        "host": host,
        "x-amz-date": now,
        **{k.lower(): v for k, v in (extra_headers or {}).items()},
    }
    if include_content_sha:
        # S3 requires the payload hash as a signed header; other services
        # (e.g. the AWS docs' iam known-answer vector) omit it
        headers["x-amz-content-sha256"] = payload_hash
    if session_token:
        headers["x-amz-security-token"] = session_token
    signed_names = sorted(headers)
    canonical_headers = "".join(
        f"{k}:{' '.join(headers[k].split())}\n" for k in signed_names
    )
    canonical_query = "&".join(
        f"{_uri_encode(k, encode_slash=True)}={_uri_encode(v, encode_slash=True)}"
        for k, v in sorted(query)
    )
    canonical_request = "\n".join(
        [
            method,
            _uri_encode(path, encode_slash=False) or "/",
            canonical_query,
            canonical_headers,
            ";".join(signed_names),
            payload_hash,
        ]
    )
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join(
        [
            "AWS4-HMAC-SHA256",
            now,
            scope,
            hashlib.sha256(canonical_request.encode()).hexdigest(),
        ]
    )
    key = _hmac(
        _hmac(_hmac(_hmac(f"AWS4{secret_key}".encode(), datestamp), region), service),
        "aws4_request",
    )
    signature = hmac.new(key, string_to_sign.encode(), hashlib.sha256).hexdigest()
    headers["authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed_names)}, Signature={signature}"
    )
    return headers


def _xml_find_all(root: ET.Element, tag: str) -> list[ET.Element]:
    """Namespace-agnostic child lookup (S3 responses carry a default ns)."""
    return [el for el in root.iter() if el.tag.split("}")[-1] == tag]


def _xml_text(el: ET.Element, tag: str, default: str = "") -> str:
    for child in el:
        if child.tag.split("}")[-1] == tag:
            return child.text or default
    return default


class S3ObjectStore(HttpObjectStore):
    """S3 REST-API object store (reference: ``S3Handler``, redesigned).

    Path-style addressing (``{endpoint}/{bucket}/{key}``) so it works against
    AWS, MinIO-style gateways, and the in-process test fake alike.
    Session/retry/download-to-file/fan-out plumbing comes from
    :class:`HttpObjectStore`; this class owns only signing and the S3 wire
    protocol.
    """

    def __init__(
        self,
        *,
        endpoint: str = "https://s3.amazonaws.com",
        region: str = "us-east-1",
        creds_fn: CredsFn | None = None,
        bucket_prefix: str = "",
        chunk_size: int = 1 << 20,
        multipart_threshold: int = 64 << 20,
        part_size: int = 32 << 20,
    ):
        super().__init__()
        self.endpoint = endpoint.rstrip("/")
        self.region = region
        self._creds_fn = creds_fn or env_credentials
        #: optional real-bucket prefix, same convention as the GCS engine
        self.bucket_prefix = bucket_prefix
        self.chunk_size = chunk_size
        self.multipart_threshold = multipart_threshold
        self.part_size = part_size
        self._host = urllib.parse.urlparse(self.endpoint).netloc

    # -- plumbing ------------------------------------------------------------

    def _path(self, uri: str) -> str:
        bucket, key = parse_uri(uri)
        return f"/{self.bucket_prefix}{bucket}/{key}" if key else (
            f"/{self.bucket_prefix}{bucket}"
        )

    async def _open(
        self,
        method: str,
        path: str,
        *,
        query: list[tuple[str, str]] | None = None,
        data: bytes | None = None,
        payload_hash: str | None = None,
        extra_headers: dict[str, str] | None = None,
    ):
        """Sign + send ONE attempt; returns the aiohttp response context
        manager (signature is stamped fresh per call)."""
        query = query or []
        if payload_hash is None:
            payload_hash = (
                hashlib.sha256(data).hexdigest() if data else EMPTY_SHA256
            )
        access_key, secret_key, token = await self._creds_fn()
        headers = sigv4_headers(
            method,
            self._host,
            path,
            query,
            payload_hash=payload_hash,
            access_key=access_key,
            secret_key=secret_key,
            session_token=token,
            region=self.region,
            extra_headers=extra_headers,
        )
        url = f"{self.endpoint}{_uri_encode(path, encode_slash=False)}"
        if query:
            # the wire query must be byte-identical to the signed canonical
            # query (same _uri_encode, same sort): AWS proper decodes '+' as
            # space so urlencode would pass there, but MinIO-style gateways
            # may canonicalize it literally → SignatureDoesNotMatch on keys
            # containing spaces
            url += "?" + "&".join(
                f"{_uri_encode(k, encode_slash=True)}="
                f"{_uri_encode(v, encode_slash=True)}"
                for k, v in sorted(query)
            )
        session = await self.session()
        return session.request(method, url, data=data, headers=headers)

    async def _call(
        self,
        method: str,
        path: str,
        *,
        query: list[tuple[str, str]] | None = None,
        data: bytes | None = None,
        payload_hash: str | None = None,
        extra_headers: dict[str, str] | None = None,
    ) -> tuple[int, bytes, dict[str, str]]:
        """One retried request (re-signed per attempt — x-amz-date moves)."""
        return await self.request_bytes(lambda: self._open(
            method, path, query=query, data=data,
            payload_hash=payload_hash, extra_headers=extra_headers,
        ))

    # -- ObjectStore interface -----------------------------------------------

    async def put_bytes(self, uri: str, data: bytes) -> None:
        status, body, _ = await self._call("PUT", self._path(uri), data=data)
        if status >= 300:
            raise IOError(f"S3 put failed ({status}): {body[:200]!r}")

    async def put_file(self, uri: str, path: Path | str) -> None:
        p = Path(path)
        size = p.stat().st_size
        if size <= self.multipart_threshold:
            await self.put_bytes(uri, await asyncio.to_thread(p.read_bytes))
            return
        await self._multipart_upload(uri, p, size)

    async def _multipart_upload(self, uri: str, p: Path, size: int) -> None:
        path = self._path(uri)
        status, body, _ = await self._call("POST", path, query=[("uploads", "")])
        if status >= 300:
            raise IOError(f"S3 create-multipart failed ({status})")
        upload_id = _xml_text(ET.fromstring(body), "UploadId")
        if not upload_id:
            raise IOError("S3 create-multipart returned no UploadId")
        etags: list[str] = []
        try:
            with p.open("rb") as f:
                part = 1
                while True:
                    chunk = await asyncio.to_thread(f.read, self.part_size)
                    if not chunk:
                        break
                    status, _body, headers = await self._call(
                        "PUT",
                        path,
                        query=[("partNumber", str(part)), ("uploadId", upload_id)],
                        data=chunk,
                    )
                    if status >= 300:
                        raise IOError(f"S3 upload-part {part} failed ({status})")
                    etags.append(headers.get("ETag", ""))
                    part += 1
            complete = "".join(
                f"<Part><PartNumber>{i + 1}</PartNumber><ETag>{etag}</ETag></Part>"
                for i, etag in enumerate(etags)
            )
            payload = (
                f"<CompleteMultipartUpload>{complete}</CompleteMultipartUpload>"
            ).encode()
            status, _body, _ = await self._call(
                "POST", path, query=[("uploadId", upload_id)], data=payload
            )
            if status >= 300:
                raise IOError(f"S3 complete-multipart failed ({status})")
        except BaseException:
            # best-effort abort so half-uploaded parts don't bill forever
            try:
                await self._call("DELETE", path, query=[("uploadId", upload_id)])
            except Exception:
                # the original upload failure is what propagates; the abort
                # failure must not mask it, but it shouldn't vanish either
                logger.warning("multipart abort failed for %s", path,
                               exc_info=True)
            raise

    async def put_stream(self, uri: str, chunks: AsyncIterator[bytes]) -> int:
        """S3 needs a Content-Length per request, so unknown-length streams
        spool to a temp file, then take the single-PUT or multipart path."""
        total = 0
        with tempfile.NamedTemporaryFile(delete=False) as tmp:
            try:
                async for chunk in chunks:
                    total += len(chunk)
                    await asyncio.to_thread(tmp.write, chunk)
                tmp.flush()
                await self.put_file(uri, tmp.name)
            finally:
                os.unlink(tmp.name)
        return total

    async def get_bytes(self, uri: str) -> bytes:
        status, body, _ = await self._call("GET", self._path(uri))
        if status == 404:
            raise FileNotFoundError(uri)
        if status >= 300:
            raise IOError(f"S3 get failed ({status})")
        return body

    async def get_chunks(
        self, uri: str, chunk_size: int = 1 << 20
    ) -> AsyncIterator[bytes]:
        # single-attempt stream (mid-stream retry cannot resume safely);
        # the inherited get_file retries the whole transfer around this
        async with await self._open("GET", self._path(uri)) as resp:
            if resp.status == 404:
                raise FileNotFoundError(uri)
            if resp.status >= 300:
                raise IOError(f"S3 get failed ({resp.status})")
            async for chunk in resp.content.iter_chunked(chunk_size):
                yield chunk

    async def exists(self, uri: str) -> bool:
        status, _, _ = await self._call("HEAD", self._path(uri))
        if status == 200:
            return True
        if status == 404:
            return False
        # 403/5xx/301 (wrong-region redirect) must not read as "absent":
        # copy_prefix branches on this answer (exact-key vs prefix semantics)
        raise IOError(f"S3 head failed ({status}) for {uri}")

    async def size(self, uri: str) -> int | None:
        status, _, headers = await self._call("HEAD", self._path(uri))
        if status == 404:
            raise FileNotFoundError(uri)
        if status >= 300:
            raise IOError(f"S3 head failed ({status}) for {uri}")
        length = headers.get("Content-Length")
        return int(length) if length is not None else None

    async def list_prefix(self, prefix_uri: str) -> list[dict[str, Any]]:
        bucket, key = parse_uri(prefix_uri)
        path = f"/{self.bucket_prefix}{bucket}"
        out: list[dict[str, Any]] = []
        token: str | None = None
        while True:
            query = [("list-type", "2"), ("prefix", key)]
            if token:
                query.append(("continuation-token", token))
            status, body, _ = await self._call("GET", path, query=query)
            if status >= 300:
                raise IOError(f"S3 list failed ({status})")
            root = ET.fromstring(body)
            for item in _xml_find_all(root, "Contents"):
                out.append(
                    {
                        "uri": build_uri(bucket, _xml_text(item, "Key")),
                        "size": int(_xml_text(item, "Size", "0")),
                        "mtime": self.parse_iso_mtime(
                            _xml_text(item, "LastModified")
                        ),
                    }
                )
            token = None
            if _xml_text(root, "IsTruncated") == "true":
                token = _xml_text(root, "NextContinuationToken") or None
            if not token:
                return out

    #: DeleteObjects accepts at most 1000 keys per request (AWS API limit)
    _DELETE_BATCH = 1000

    async def delete_prefix(self, prefix_uri: str) -> int:
        """Batch deletion via the ``DeleteObjects`` API — a checkpoint tree
        with hundreds of shards goes down in ⌈n/1000⌉ requests instead of n
        (the reference fans out per-key coroutines, ``S3Handler.py:216-235``;
        the batch API beats even that)."""
        from xml.sax.saxutils import escape

        objs = await self.list_prefix(prefix_uri)
        bucket, _ = parse_uri(prefix_uri)
        bucket_path = f"/{self.bucket_prefix}{bucket}"
        n = 0
        for start in range(0, len(objs), self._DELETE_BATCH):
            batch = objs[start:start + self._DELETE_BATCH]
            keys = [parse_uri(o["uri"])[1] for o in batch]
            payload = (
                "<Delete><Quiet>true</Quiet>"
                + "".join(f"<Object><Key>{escape(k)}</Key></Object>" for k in keys)
                + "</Delete>"
            ).encode()
            md5 = base64.b64encode(hashlib.md5(payload).digest()).decode()
            status, body, _ = await self._call(
                "POST", bucket_path, query=[("delete", "")], data=payload,
                extra_headers={"content-md5": md5},
            )
            if status >= 300:
                raise IOError(f"S3 batch delete failed ({status})")
            errors = _xml_find_all(ET.fromstring(body), "Error")
            if errors:
                first = errors[0]
                raise IOError(
                    "S3 batch delete reported "
                    f"{len(errors)} errors, first: "
                    f"{_xml_text(first, 'Key')}: {_xml_text(first, 'Message')}"
                )
            n += len(keys)
        return n

    async def copy_prefix(self, src_uri: str, dst_uri: str) -> int:
        """Server-side copy via ``x-amz-copy-source`` (reference:
        ``S3Handler.py:375-439`` — head the key; on miss treat as prefix),
        fanned out concurrently (reference gathers too, ``S3Handler.py:422``)."""
        if await self.exists(src_uri):
            objs = [{"uri": src_uri}]
            exact = True
        else:
            objs = await self.list_prefix(src_uri)
            exact = False
        _, src_key = parse_uri(src_uri)
        dst_bucket, dst_key = parse_uri(dst_uri)

        async def copy_one(o) -> int:
            _, key = parse_uri(o["uri"])
            rel = "" if exact else key[len(src_key):].lstrip("/")
            target_key = dst_key if exact or not rel else f"{dst_key}/{rel}"
            source = _uri_encode(self._path(o["uri"]), encode_slash=False)
            status, _body, _ = await self._call(
                "PUT",
                self._path(build_uri(dst_bucket, target_key)),
                extra_headers={"x-amz-copy-source": source},
            )
            if status >= 300:
                raise IOError(f"S3 copy failed ({status}) for {o['uri']}")
            return 1

        return sum(await self.map_concurrently(copy_one, objs))
