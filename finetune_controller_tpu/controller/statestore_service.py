"""Shared state service: the control plane's EXTERNAL store.

The reference scales its API to N replicas because every replica and the
monitor talk to one external MongoDB (``app/database/db.py:51``); our default
sqlite-WAL engine shares state only between processes on ONE node. This
module closes that gap without adding a database dependency: a small aiohttp
daemon (:func:`build_state_app`, entrypoint
``python -m finetune_controller_tpu.controller.statestore_main``) hosts the
real :class:`~.statestore.StateStore` (sqlite engine) and exposes its DOMAIN
methods as JSON RPCs, and :class:`RemoteStateStore` implements the same
interface over HTTP — so ``state_backend=remote`` turns the API×N + monitor
layout into a true HA control plane, and rate limits enforced through
``rate_limit_acquire`` become cluster-scope.

The RPC surface is the domain API, not the collection primitives: domain
calls take JSON-serializable arguments, while collection operations take
Python predicates that cannot cross a wire. Auth is a static bearer token
(``FTC_STATE_TOKEN``) — this is an in-cluster service, not a user surface.
"""

from __future__ import annotations

import asyncio
import hmac
import logging
from typing import Any, Awaitable, Callable

from .schemas import (
    DatabaseStatus,
    DatasetRecord,
    JobRecord,
    MetricsDocument,
    PaginatedTableResponse,
)
from .statestore import StateStore

logger = logging.getLogger(__name__)

_RPC: dict[str, Callable[[StateStore, dict], Awaitable[Any]]] = {}


def _rpc(name: str):
    def deco(fn):
        _RPC[name] = fn
        return fn

    return deco


def _dump(model) -> Any:
    return model.model_dump(mode="json") if model is not None else None


@_rpc("create_job")
async def _create_job(store, p):
    await store.create_job(JobRecord(**p["job"]))


@_rpc("get_job")
async def _get_job(store, p):
    return _dump(await store.get_job(p["job_id"]))


@_rpc("get_jobs_by_ids")
async def _get_jobs_by_ids(store, p):
    jobs = await store.get_jobs_by_ids(p["job_ids"])
    return {k: _dump(v) for k, v in jobs.items()}


@_rpc("get_active_jobs")
async def _get_active_jobs(store, p):
    return [_dump(j) for j in await store.get_active_jobs()]


@_rpc("get_jobs_by_status")
async def _get_jobs_by_status(store, p):
    jobs = await store.get_jobs_by_status(DatabaseStatus(p["status"]))
    return [_dump(j) for j in jobs]


@_rpc("update_job_status")
async def _update_job_status(store, p):
    return await store.update_job_status(
        p["job_id"], DatabaseStatus(p["status"]),
        metadata=p.get("metadata"), **(p.get("fields") or {}),
    )


@_rpc("transition_job_status")
async def _transition_job_status(store, p):
    return await store.transition_job_status(
        p["job_id"], DatabaseStatus(p["expect"]), DatabaseStatus(p["status"]),
        metadata=p.get("metadata"), **(p.get("fields") or {}),
    )


@_rpc("update_job_promotion")
async def _update_job_promotion(store, p):
    return await store.update_job_promotion(
        p["job_id"], p["promotion_status"], p.get("promotion_uri")
    )


@_rpc("begin_promotion")
async def _begin_promotion(store, p):
    return await store.begin_promotion(
        p["job_id"], p["promotion_status"], p["promotion_uri"],
        expect_from=p.get("expect_from"),
    )


@_rpc("transition_job_promotion")
async def _transition_job_promotion(store, p):
    return await store.transition_job_promotion(
        p["job_id"], p["expect"], p["promotion_status"],
        p.get("promotion_uri"),
    )


@_rpc("update_job_fields")
async def _update_job_fields(store, p):
    return await store.update_job_fields(p["job_id"], **(p.get("fields") or {}))


@_rpc("append_job_event")
async def _append_job_event(store, p):
    return await store.append_job_event(p["job_id"], p["event"])


@_rpc("append_job_events")
async def _append_job_events(store, p):
    return await store.append_job_events(p["job_id"], p.get("events") or [])


@_rpc("merge_job_metadata")
async def _merge_job_metadata(store, p):
    return await store.merge_job_metadata(p["job_id"], p.get("patch") or {})


@_rpc("find_jobs_with_promotion_in")
async def _find_jobs_with_promotion_in(store, p):
    return [_dump(j) for j in await store.find_jobs_with_promotion_in(p["states"])]


@_rpc("get_user_jobs")
async def _get_user_jobs(store, p):
    res = await store.get_user_jobs(
        p.get("user_id"),
        page=p.get("page", 1),
        page_size=p.get("page_size", 20),
        status=DatabaseStatus(p["status"]) if p.get("status") else None,
        search=p.get("search"),
        sort_by=p.get("sort_by", "submitted_at"),
        descending=p.get("descending", True),
    )
    return _dump(res)


@_rpc("purge_job")
async def _purge_job(store, p):
    return await store.purge_job(p["job_id"])


@_rpc("delete_job")
async def _delete_job(store, p):
    return await store.delete_job(p["job_id"])


@_rpc("upsert_metrics")
async def _upsert_metrics(store, p):
    await store.upsert_metrics(MetricsDocument(**p["metrics"]))


@_rpc("get_metrics")
async def _get_metrics(store, p):
    return _dump(await store.get_metrics(p["job_id"]))


@_rpc("insert_dataset")
async def _insert_dataset(store, p):
    await store.insert_dataset(DatasetRecord(**p["dataset"]))


@_rpc("get_dataset")
async def _get_dataset(store, p):
    return _dump(await store.get_dataset(p["dataset_id"]))


@_rpc("get_user_datasets")
async def _get_user_datasets(store, p):
    return [_dump(d) for d in await store.get_user_datasets(p["user_id"])]


@_rpc("add_dataset_job_ref")
async def _add_dataset_job_ref(store, p):
    return await store.add_dataset_job_ref(p["dataset_id"], p["job_id"])


@_rpc("delete_dataset")
async def _delete_dataset(store, p):
    return await store.delete_dataset(p["dataset_id"])


@_rpc("rate_limit_acquire")
async def _rate_limit_acquire(store, p):
    return await store.rate_limit_acquire(
        p["key"], p["limit"], p.get("window_s", 60.0)
    )


def build_state_app(store: StateStore, token: str = ""):
    """aiohttp application serving the state RPCs (+ ``/healthz``)."""
    from aiohttp import web

    async def rpc_handler(request: web.Request) -> web.Response:
        # constant-time comparison: a plain != short-circuits on the first
        # differing byte, leaking token prefixes to an in-cluster attacker
        # who can measure latency. Compare as bytes — compare_digest on str
        # raises TypeError for non-ASCII input, which would turn a garbage
        # Authorization header into a 500 instead of a 401.
        presented = request.headers.get("Authorization", "")
        if token and not hmac.compare_digest(
            presented.encode("utf-8", "surrogateescape"),
            f"Bearer {token}".encode(),
        ):
            return web.json_response({"error": "unauthorized"}, status=401)
        method = request.match_info["method"]
        handler = _RPC.get(method)
        if handler is None:
            return web.json_response(
                {"error": f"unknown method {method!r}"}, status=404
            )
        try:
            payload = await request.json() if request.can_read_body else {}
        except ValueError:
            return web.json_response({"error": "bad json"}, status=400)
        try:
            result = await handler(store, payload)
        except (KeyError, ValueError, TypeError) as exc:
            return web.json_response(
                {"error": f"{type(exc).__name__}: {exc}"}, status=400
            )
        except Exception:
            logger.exception("state rpc %s failed", method)
            return web.json_response({"error": "internal"}, status=500)
        return web.json_response({"result": result})

    async def healthz(request: web.Request) -> web.Response:
        return web.json_response({"ok": True})

    # metrics documents for long jobs exceed aiohttp's default 1 MiB body
    # cap — same override the API server uses (server.py)
    app = web.Application(client_max_size=1 << 30)
    app.router.add_post("/rpc/{method}", rpc_handler)
    app.router.add_get("/healthz", healthz)
    return app


class RemoteStateStore:
    """``StateStore``-compatible client for the shared state service.

    Drop-in for every control-plane consumer (server, monitor, promotion,
    task builder) — same domain methods, same pydantic return types. Writes
    are single-attempt (a retried mutation could double-apply); reads retry
    once on transient transport errors.
    """

    def __init__(self, url: str, *, token: str = ""):
        if not url:
            raise ValueError(
                "state_backend=remote needs state_service_url (the shared "
                "state service endpoint)"
            )
        self.url = url.rstrip("/")
        self._token = token
        self._session = None
        self._connected = False

    async def _http(self):
        import aiohttp

        if self._session is None or self._session.closed:
            headers = (
                {"Authorization": f"Bearer {self._token}"} if self._token else {}
            )
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=60, sock_connect=10),
                headers=headers,
            )
        return self._session

    async def connect(self) -> None:
        session = await self._http()
        async with session.get(f"{self.url}/healthz") as resp:
            if resp.status != 200:
                raise IOError(
                    f"state service unhealthy ({resp.status}) at {self.url}"
                )
        self._connected = True

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()
        self._connected = False

    async def _call(self, method: str, retry_reads: bool = False, **payload):
        import aiohttp

        session = await self._http()
        attempts = 2 if retry_reads else 1
        for attempt in range(attempts):
            try:
                async with session.post(
                    f"{self.url}/rpc/{method}", json=payload
                ) as resp:
                    body = await resp.json()
                    if resp.status >= 500 and attempt < attempts - 1:
                        continue
                    if resp.status >= 300:
                        raise IOError(
                            f"state rpc {method} failed ({resp.status}): "
                            f"{body.get('error')}"
                        )
                    return body.get("result")
            except (aiohttp.ClientError, asyncio.TimeoutError):
                if attempt >= attempts - 1:
                    raise
        raise AssertionError("unreachable")

    # -- domain surface (mirrors StateStore) ---------------------------------

    async def create_job(self, job: JobRecord) -> None:
        await self._call("create_job", job=job.model_dump(mode="json"))

    async def get_job(self, job_id: str) -> JobRecord | None:
        doc = await self._call("get_job", retry_reads=True, job_id=job_id)
        return JobRecord(**doc) if doc else None

    async def get_jobs_by_ids(self, job_ids: list[str]) -> dict[str, JobRecord]:
        docs = await self._call(
            "get_jobs_by_ids", retry_reads=True, job_ids=list(job_ids)
        )
        return {k: JobRecord(**v) for k, v in docs.items()}

    async def get_active_jobs(self) -> list[JobRecord]:
        docs = await self._call("get_active_jobs", retry_reads=True)
        return [JobRecord(**d) for d in docs]

    async def get_jobs_by_status(self, status) -> list[JobRecord]:
        docs = await self._call(
            "get_jobs_by_status", retry_reads=True,
            status=DatabaseStatus(status).value,
        )
        return [JobRecord(**d) for d in docs]

    async def update_job_status(
        self,
        job_id: str,
        status: DatabaseStatus,
        *,
        metadata: dict[str, Any] | None = None,
        **fields: Any,
    ) -> bool:
        return await self._call(
            "update_job_status", job_id=job_id,
            status=DatabaseStatus(status).value, metadata=metadata,
            fields=fields,
        )

    async def transition_job_status(
        self,
        job_id: str,
        expect,
        status,
        *,
        metadata: dict[str, Any] | None = None,
        **fields: Any,
    ) -> bool:
        return await self._call(
            "transition_job_status", job_id=job_id,
            expect=DatabaseStatus(expect).value,
            status=DatabaseStatus(status).value,
            metadata=metadata, fields=fields,
        )

    async def update_job_promotion(
        self, job_id, promotion_status, promotion_uri=None
    ) -> bool:
        from .schemas import PromotionStatus

        return await self._call(
            "update_job_promotion", job_id=job_id,
            promotion_status=PromotionStatus(promotion_status).value,
            promotion_uri=promotion_uri,
        )

    async def begin_promotion(
        self, job_id, promotion_status, promotion_uri, expect_from=None
    ) -> bool:
        from .schemas import PromotionStatus

        return await self._call(
            "begin_promotion", job_id=job_id,
            promotion_status=PromotionStatus(promotion_status).value,
            promotion_uri=promotion_uri,
            expect_from=(
                None if expect_from is None
                else [PromotionStatus(s).value for s in expect_from]
            ),
        )

    async def transition_job_promotion(
        self, job_id, expect, promotion_status, promotion_uri=None
    ) -> bool:
        from .schemas import PromotionStatus

        return await self._call(
            "transition_job_promotion", job_id=job_id,
            expect=[PromotionStatus(s).value for s in expect],
            promotion_status=PromotionStatus(promotion_status).value,
            promotion_uri=promotion_uri,
        )

    async def update_job_fields(self, job_id: str, **fields: Any) -> bool:
        return await self._call(
            "update_job_fields", job_id=job_id, fields=fields
        )

    async def append_job_event(self, job_id: str, event: dict[str, Any]) -> bool:
        return await self._call("append_job_event", job_id=job_id, event=event)

    async def append_job_events(
        self, job_id: str, events: list[dict[str, Any]]
    ) -> int:
        if not events:
            return 0
        return await self._call(
            "append_job_events", job_id=job_id, events=events
        )

    async def merge_job_metadata(self, job_id: str, patch: dict[str, Any]) -> bool:
        return await self._call("merge_job_metadata", job_id=job_id, patch=patch)

    async def find_jobs_with_promotion_in(self, states) -> list[JobRecord]:
        from .schemas import PromotionStatus

        docs = await self._call(
            "find_jobs_with_promotion_in", retry_reads=True,
            states=[PromotionStatus(s).value for s in states],
        )
        return [JobRecord(**d) for d in docs]

    async def get_user_jobs(
        self,
        user_id: str | None,
        *,
        page: int = 1,
        page_size: int = 20,
        status: DatabaseStatus | None = None,
        search: str | None = None,
        sort_by: str = "submitted_at",
        descending: bool = True,
    ) -> PaginatedTableResponse:
        res = await self._call(
            "get_user_jobs", retry_reads=True, user_id=user_id, page=page,
            page_size=page_size,
            status=DatabaseStatus(status).value if status else None,
            search=search, sort_by=sort_by, descending=descending,
        )
        return PaginatedTableResponse(**res)

    async def purge_job(self, job_id: str) -> bool:
        return await self._call("purge_job", job_id=job_id)

    async def delete_job(self, job_id: str) -> bool:
        return await self._call("delete_job", job_id=job_id)

    async def upsert_metrics(self, metrics: MetricsDocument) -> None:
        await self._call(
            "upsert_metrics", metrics=metrics.model_dump(mode="json")
        )

    async def get_metrics(self, job_id: str) -> MetricsDocument | None:
        doc = await self._call("get_metrics", retry_reads=True, job_id=job_id)
        return MetricsDocument(**doc) if doc else None

    async def insert_dataset(self, dataset: DatasetRecord) -> None:
        await self._call(
            "insert_dataset", dataset=dataset.model_dump(mode="json")
        )

    async def get_dataset(self, dataset_id: str) -> DatasetRecord | None:
        doc = await self._call(
            "get_dataset", retry_reads=True, dataset_id=dataset_id
        )
        return DatasetRecord(**doc) if doc else None

    async def get_user_datasets(self, user_id: str) -> list[DatasetRecord]:
        docs = await self._call(
            "get_user_datasets", retry_reads=True, user_id=user_id
        )
        return [DatasetRecord(**d) for d in docs]

    async def add_dataset_job_ref(self, dataset_id: str, job_id: str) -> bool:
        return await self._call(
            "add_dataset_job_ref", dataset_id=dataset_id, job_id=job_id
        )

    async def delete_dataset(self, dataset_id: str) -> bool:
        return await self._call("delete_dataset", dataset_id=dataset_id)

    async def rate_limit_acquire(
        self, key: str, limit: int, window_s: float = 60.0
    ) -> bool:
        return await self._call(
            "rate_limit_acquire", key=key, limit=limit, window_s=window_s
        )
