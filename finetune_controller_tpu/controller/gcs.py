"""GCS object store — the cloud backend behind the ``ObjectStore`` seam.

The reference moves real bytes through S3 with aioboto3 plus ``aws-cli``
init/sidecar containers (``app/utils/S3Handler.py:12,25``,
``PyTorchJobDeployer.py:74,142``). The TPU build's natural bucket store is
GCS (it is what GKE TPU node pools authenticate to out of the box), talked to
directly over aiohttp against the JSON API — no SDK dependency, and the
endpoint is injectable so tests run against an in-process fake (SURVEY.md §4
test strategy; the reference could not test its S3 path at all).

Auth is a pluggable async token provider. The default chain:

1. ``GOOGLE_OAUTH_ACCESS_TOKEN`` env var (dev / CI);
2. service-account JSON at ``GOOGLE_APPLICATION_CREDENTIALS`` — a self-signed
   RS256 JWT exchanged at the token URI (no gcloud needed);
3. the GCE/GKE metadata server (workload identity — the in-cluster path).

URIs stay in the framework's ``obj://bucket/key`` convention; the bucket maps
1:1 onto a GCS bucket.
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
import os
import time
import urllib.parse
from pathlib import Path
from typing import Any, AsyncIterator, Awaitable, Callable

from .objectstore import ObjectStore, build_uri, parse_uri

logger = logging.getLogger(__name__)

TokenFn = Callable[[], Awaitable[str]]

_METADATA_TOKEN_URL = (
    "http://metadata.google.internal/computeMetadata/v1/instance/"
    "service-accounts/default/token"
)
_SCOPE = "https://www.googleapis.com/auth/devstorage.read_write"


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


async def _token_from_service_account(path: str) -> tuple[str, float]:
    """Self-signed JWT → access token (RFC 7523 flow, no SDK)."""
    import aiohttp
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import padding

    info = json.loads(Path(path).read_text())
    now = time.time()
    claims = {
        "iss": info["client_email"],
        "scope": _SCOPE,
        "aud": info["token_uri"],
        "iat": int(now),
        "exp": int(now) + 3600,
    }
    header = _b64url(json.dumps({"alg": "RS256", "typ": "JWT"}).encode())
    payload = _b64url(json.dumps(claims).encode())
    key = serialization.load_pem_private_key(
        info["private_key"].encode(), password=None
    )
    sig = key.sign(
        f"{header}.{payload}".encode(), padding.PKCS1v15(), hashes.SHA256()
    )
    assertion = f"{header}.{payload}.{_b64url(sig)}"
    async with aiohttp.ClientSession() as session:
        async with session.post(
            info["token_uri"],
            data={
                "grant_type": "urn:ietf:params:oauth:grant-type:jwt-bearer",
                "assertion": assertion,
            },
        ) as resp:
            resp.raise_for_status()
            body = await resp.json()
    return body["access_token"], now + float(body.get("expires_in", 3600))


async def _token_from_metadata_server() -> tuple[str, float]:
    import aiohttp

    now = time.time()
    async with aiohttp.ClientSession() as session:
        async with session.get(
            _METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"}
        ) as resp:
            resp.raise_for_status()
            body = await resp.json()
    return body["access_token"], now + float(body.get("expires_in", 3600))


class DefaultTokenProvider:
    """env var → service-account JSON → metadata server, with expiry cache."""

    def __init__(self):
        self._token = ""
        self._expires = 0.0
        self._lock = asyncio.Lock()

    async def __call__(self) -> str:
        env_tok = os.environ.get("GOOGLE_OAUTH_ACCESS_TOKEN")
        if env_tok:
            return env_tok
        async with self._lock:
            if self._token and time.time() < self._expires - 60:
                return self._token
            sa_path = os.environ.get("GOOGLE_APPLICATION_CREDENTIALS")
            if sa_path and Path(sa_path).is_file():
                self._token, self._expires = await _token_from_service_account(sa_path)
            else:
                self._token, self._expires = await _token_from_metadata_server()
            return self._token


class GCSObjectStore(ObjectStore):
    """GCS JSON-API object store (reference: ``S3Handler``, redesigned)."""

    def __init__(
        self,
        *,
        endpoint: str = "https://storage.googleapis.com",
        token_fn: TokenFn | None = None,
        bucket_prefix: str = "",
        chunk_size: int = 1 << 20,
    ):
        self.endpoint = endpoint.rstrip("/")
        self._token_fn = token_fn or DefaultTokenProvider()
        #: optional real-bucket prefix so one GCS project can host several
        #: logical buckets (``obj://datasets/...`` → ``{prefix}datasets``)
        self.bucket_prefix = bucket_prefix
        self.chunk_size = chunk_size
        self._session = None

    # -- plumbing ------------------------------------------------------------

    def _gcs_bucket(self, bucket: str) -> str:
        return f"{self.bucket_prefix}{bucket}"

    async def _headers(self) -> dict[str, str]:
        token = await self._token_fn()
        return {"Authorization": f"Bearer {token}"}

    async def session(self):
        import aiohttp

        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=None, sock_connect=30)
            )
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    def _object_url(self, uri: str, *, media: bool) -> str:
        bucket, key = parse_uri(uri)
        quoted = urllib.parse.quote(key, safe="")
        url = (
            f"{self.endpoint}/storage/v1/b/{self._gcs_bucket(bucket)}/o/{quoted}"
        )
        return f"{url}?alt=media" if media else url

    @staticmethod
    def _mtime(item: dict[str, Any]) -> float:
        updated = item.get("updated", "")
        try:
            import datetime

            return datetime.datetime.fromisoformat(
                updated.replace("Z", "+00:00")
            ).timestamp()
        except ValueError:
            return 0.0

    # -- ObjectStore interface -----------------------------------------------

    async def put_bytes(self, uri: str, data: bytes) -> None:
        bucket, key = parse_uri(uri)
        session = await self.session()
        url = (
            f"{self.endpoint}/upload/storage/v1/b/{self._gcs_bucket(bucket)}/o"
            f"?uploadType=media&name={urllib.parse.quote(key, safe='')}"
        )
        async with session.post(url, data=data, headers=await self._headers()) as resp:
            if resp.status >= 300:
                raise IOError(f"GCS upload failed ({resp.status}): {await resp.text()}")

    async def put_stream(self, uri: str, chunks: AsyncIterator[bytes]) -> int:
        total = 0

        async def counted() -> AsyncIterator[bytes]:
            nonlocal total
            async for chunk in chunks:
                total += len(chunk)
                yield chunk

        bucket, key = parse_uri(uri)
        session = await self.session()
        url = (
            f"{self.endpoint}/upload/storage/v1/b/{self._gcs_bucket(bucket)}/o"
            f"?uploadType=media&name={urllib.parse.quote(key, safe='')}"
        )
        async with session.post(
            url, data=counted(), headers=await self._headers()
        ) as resp:
            if resp.status >= 300:
                raise IOError(f"GCS upload failed ({resp.status}): {await resp.text()}")
        return total

    async def put_file(self, uri: str, path: Path | str) -> None:
        p = Path(path)

        async def chunks() -> AsyncIterator[bytes]:
            with p.open("rb") as f:
                while True:
                    chunk = await asyncio.to_thread(f.read, self.chunk_size)
                    if not chunk:
                        return
                    yield chunk

        await self.put_stream(uri, chunks())

    async def get_bytes(self, uri: str) -> bytes:
        session = await self.session()
        async with session.get(
            self._object_url(uri, media=True), headers=await self._headers()
        ) as resp:
            if resp.status == 404:
                raise FileNotFoundError(uri)
            if resp.status >= 300:
                raise IOError(f"GCS get failed ({resp.status})")
            return await resp.read()

    async def get_chunks(self, uri: str, chunk_size: int = 1 << 20) -> AsyncIterator[bytes]:
        session = await self.session()
        async with session.get(
            self._object_url(uri, media=True), headers=await self._headers()
        ) as resp:
            if resp.status == 404:
                raise FileNotFoundError(uri)
            if resp.status >= 300:
                raise IOError(f"GCS get failed ({resp.status})")
            async for chunk in resp.content.iter_chunked(chunk_size):
                yield chunk

    async def get_file(self, uri: str, dest: Path | str) -> int:
        dest_p = Path(dest)
        dest_p.parent.mkdir(parents=True, exist_ok=True)
        tmp = dest_p.with_name(dest_p.name + ".tmp")
        total = 0
        with tmp.open("wb") as f:
            async for chunk in self.get_chunks(uri, self.chunk_size):
                total += len(chunk)
                await asyncio.to_thread(f.write, chunk)
        tmp.replace(dest_p)
        return total

    async def exists(self, uri: str) -> bool:
        session = await self.session()
        async with session.get(
            self._object_url(uri, media=False), headers=await self._headers()
        ) as resp:
            return resp.status == 200

    async def list_prefix(self, prefix_uri: str) -> list[dict[str, Any]]:
        bucket, key = parse_uri(prefix_uri)
        session = await self.session()
        base = f"{self.endpoint}/storage/v1/b/{self._gcs_bucket(bucket)}/o"
        out: list[dict[str, Any]] = []
        page: str | None = None
        while True:
            params = {"prefix": key}
            if page:
                params["pageToken"] = page
            async with session.get(
                base, params=params, headers=await self._headers()
            ) as resp:
                if resp.status >= 300:
                    raise IOError(f"GCS list failed ({resp.status})")
                body = await resp.json()
            for item in body.get("items", []):
                out.append(
                    {
                        "uri": build_uri(bucket, item["name"]),
                        "size": int(item.get("size", 0)),
                        "mtime": self._mtime(item),
                    }
                )
            page = body.get("nextPageToken")
            if not page:
                return out

    async def delete_prefix(self, prefix_uri: str) -> int:
        objs = await self.list_prefix(prefix_uri)
        session = await self.session()
        n = 0
        for o in objs:
            async with session.delete(
                self._object_url(o["uri"], media=False), headers=await self._headers()
            ) as resp:
                if resp.status in (200, 204, 404):
                    n += 1
                else:
                    raise IOError(f"GCS delete failed ({resp.status}) for {o['uri']}")
        return n

    async def copy_prefix(self, src_uri: str, dst_uri: str) -> int:
        """Server-side copy per object (reference: ``S3Handler.py:375-439`` —
        head the key; on miss treat as prefix)."""
        session = await self.session()
        if await self.exists(src_uri):
            objs = [{"uri": src_uri}]
            exact = True
        else:
            objs = await self.list_prefix(src_uri)
            exact = False
        _, src_key = parse_uri(src_uri)
        dst_bucket, dst_key = parse_uri(dst_uri)
        n = 0
        for o in objs:
            src_b, key = parse_uri(o["uri"])
            rel = "" if exact else key[len(src_key):].lstrip("/")
            target_key = dst_key if exact else f"{dst_key}/{rel}" if rel else dst_key
            url = (
                f"{self.endpoint}/storage/v1/b/{self._gcs_bucket(src_b)}/o/"
                f"{urllib.parse.quote(key, safe='')}/copyTo/b/"
                f"{self._gcs_bucket(dst_bucket)}/o/"
                f"{urllib.parse.quote(target_key, safe='')}"
            )
            async with session.post(url, headers=await self._headers()) as resp:
                if resp.status >= 300:
                    raise IOError(f"GCS copy failed ({resp.status}) for {o['uri']}")
            n += 1
        return n
