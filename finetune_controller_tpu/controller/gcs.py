"""GCS object store — the cloud backend behind the ``ObjectStore`` seam.

The reference moves real bytes through S3 with aioboto3 plus ``aws-cli``
init/sidecar containers (``app/utils/S3Handler.py:12,25``,
``PyTorchJobDeployer.py:74,142``). The TPU build's natural bucket store is
GCS (it is what GKE TPU node pools authenticate to out of the box), talked to
directly over aiohttp against the JSON API — no SDK dependency, and the
endpoint is injectable so tests run against an in-process fake (SURVEY.md §4
test strategy; the reference could not test its S3 path at all).

Auth is a pluggable async token provider. The default chain:

1. ``GOOGLE_OAUTH_ACCESS_TOKEN`` env var (dev / CI);
2. service-account JSON at ``GOOGLE_APPLICATION_CREDENTIALS`` — a self-signed
   RS256 JWT exchanged at the token URI (no gcloud needed);
3. the GCE/GKE metadata server (workload identity — the in-cluster path).

URIs stay in the framework's ``obj://bucket/key`` convention; the bucket maps
1:1 onto a GCS bucket.
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
import os
import time
import urllib.parse
from pathlib import Path
from typing import Any, AsyncIterator, Awaitable, Callable

from .objectstore import HttpObjectStore, build_uri, parse_uri

logger = logging.getLogger(__name__)

TokenFn = Callable[[], Awaitable[str]]

_METADATA_TOKEN_URL = (
    "http://metadata.google.internal/computeMetadata/v1/instance/"
    "service-accounts/default/token"
)
_SCOPE = "https://www.googleapis.com/auth/devstorage.read_write"


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


async def _token_from_service_account(path: str) -> tuple[str, float]:
    """Self-signed JWT → access token (RFC 7523 flow, no SDK)."""
    import aiohttp
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import padding

    info = json.loads(await asyncio.to_thread(Path(path).read_text))
    now = time.time()
    claims = {
        "iss": info["client_email"],
        "scope": _SCOPE,
        "aud": info["token_uri"],
        "iat": int(now),
        "exp": int(now) + 3600,
    }
    header = _b64url(json.dumps({"alg": "RS256", "typ": "JWT"}).encode())
    payload = _b64url(json.dumps(claims).encode())
    key = serialization.load_pem_private_key(
        info["private_key"].encode(), password=None
    )
    sig = key.sign(
        f"{header}.{payload}".encode(), padding.PKCS1v15(), hashes.SHA256()
    )
    assertion = f"{header}.{payload}.{_b64url(sig)}"
    async with aiohttp.ClientSession() as session:
        async with session.post(
            info["token_uri"],
            data={
                "grant_type": "urn:ietf:params:oauth:grant-type:jwt-bearer",
                "assertion": assertion,
            },
        ) as resp:
            resp.raise_for_status()
            body = await resp.json()
    return body["access_token"], now + float(body.get("expires_in", 3600))


async def _token_from_metadata_server() -> tuple[str, float]:
    import aiohttp

    now = time.time()
    async with aiohttp.ClientSession() as session:
        async with session.get(
            _METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"}
        ) as resp:
            resp.raise_for_status()
            body = await resp.json()
    return body["access_token"], now + float(body.get("expires_in", 3600))


class DefaultTokenProvider:
    """env var → service-account JSON → metadata server, with expiry cache."""

    def __init__(self):
        self._token = ""
        self._expires = 0.0
        self._lock = asyncio.Lock()

    async def __call__(self) -> str:
        env_tok = os.environ.get("GOOGLE_OAUTH_ACCESS_TOKEN")
        if env_tok:
            return env_tok
        async with self._lock:
            if self._token and time.time() < self._expires - 60:
                return self._token
            sa_path = os.environ.get("GOOGLE_APPLICATION_CREDENTIALS")
            if sa_path and Path(sa_path).is_file():
                self._token, self._expires = await _token_from_service_account(sa_path)
            else:
                self._token, self._expires = await _token_from_metadata_server()
            return self._token


class GCSObjectStore(HttpObjectStore):
    """GCS JSON-API object store (reference: ``S3Handler``, redesigned).

    Session/retry/download-to-file/fan-out plumbing comes from
    :class:`HttpObjectStore`; this class owns only the GCS wire protocol.
    """

    def __init__(
        self,
        *,
        endpoint: str = "https://storage.googleapis.com",
        token_fn: TokenFn | None = None,
        bucket_prefix: str = "",
        chunk_size: int = 1 << 20,
    ):
        super().__init__()
        self.endpoint = endpoint.rstrip("/")
        self._token_fn = token_fn or DefaultTokenProvider()
        #: optional real-bucket prefix so one GCS project can host several
        #: logical buckets (``obj://datasets/...`` → ``{prefix}datasets``)
        self.bucket_prefix = bucket_prefix
        self.chunk_size = chunk_size

    # -- plumbing ------------------------------------------------------------

    def _gcs_bucket(self, bucket: str) -> str:
        return f"{self.bucket_prefix}{bucket}"

    async def _headers(self) -> dict[str, str]:
        token = await self._token_fn()
        return {"Authorization": f"Bearer {token}"}

    def _object_url(self, uri: str, *, media: bool) -> str:
        bucket, key = parse_uri(uri)
        quoted = urllib.parse.quote(key, safe="")
        url = (
            f"{self.endpoint}/storage/v1/b/{self._gcs_bucket(bucket)}/o/{quoted}"
        )
        return f"{url}?alt=media" if media else url

    async def _call(
        self, method: str, url: str, *, data: bytes | None = None,
        params: dict[str, str] | None = None,
    ) -> tuple[int, bytes]:
        """One retried JSON-API request (token re-fetched per attempt so a
        retry spanning a token expiry still authenticates)."""

        async def build():
            session = await self.session()
            return session.request(
                method, url, data=data, params=params,
                headers=await self._headers(),
            )

        status, body, _ = await self.request_bytes(build)
        return status, body

    # -- ObjectStore interface -----------------------------------------------

    def _upload_url(self, uri: str) -> str:
        bucket, key = parse_uri(uri)
        return (
            f"{self.endpoint}/upload/storage/v1/b/{self._gcs_bucket(bucket)}/o"
            f"?uploadType=media&name={urllib.parse.quote(key, safe='')}"
        )

    async def put_bytes(self, uri: str, data: bytes) -> None:
        status, body = await self._call("POST", self._upload_url(uri), data=data)
        if status >= 300:
            raise IOError(f"GCS upload failed ({status}): {body[:200]!r}")

    async def put_stream(self, uri: str, chunks: AsyncIterator[bytes]) -> int:
        """Single-attempt: an async-iterator body cannot be replayed, so a
        transient failure surfaces to the caller (uploads with a replayable
        source should go through :meth:`put_file`/:meth:`put_bytes`)."""
        total = 0

        async def counted() -> AsyncIterator[bytes]:
            nonlocal total
            async for chunk in chunks:
                total += len(chunk)
                yield chunk

        session = await self.session()
        async with session.post(
            self._upload_url(uri), data=counted(), headers=await self._headers()
        ) as resp:
            if resp.status >= 300:
                raise IOError(f"GCS upload failed ({resp.status}): {await resp.text()}")
        return total

    async def put_file(self, uri: str, path: Path | str) -> None:
        p = Path(path)

        async def build():
            async def chunks() -> AsyncIterator[bytes]:
                with p.open("rb") as f:
                    while True:
                        chunk = await asyncio.to_thread(f.read, self.chunk_size)
                        if not chunk:
                            return
                        yield chunk

            session = await self.session()
            return session.post(
                self._upload_url(uri), data=chunks(),
                headers=await self._headers(),
            )

        # the chunk generator is rebuilt per attempt, so this upload IS
        # retryable, unlike a caller-supplied stream
        status, body, _ = await self.request_bytes(build)
        if status >= 300:
            raise IOError(f"GCS upload failed ({status}): {body[:200]!r}")

    async def get_bytes(self, uri: str) -> bytes:
        status, body = await self._call("GET", self._object_url(uri, media=True))
        if status == 404:
            raise FileNotFoundError(uri)
        if status >= 300:
            raise IOError(f"GCS get failed ({status})")
        return body

    async def get_chunks(self, uri: str, chunk_size: int = 1 << 20) -> AsyncIterator[bytes]:
        session = await self.session()
        async with session.get(
            self._object_url(uri, media=True), headers=await self._headers()
        ) as resp:
            if resp.status == 404:
                raise FileNotFoundError(uri)
            if resp.status >= 300:
                raise IOError(f"GCS get failed ({resp.status})")
            async for chunk in resp.content.iter_chunked(chunk_size):
                yield chunk

    async def exists(self, uri: str) -> bool:
        status, _ = await self._call("GET", self._object_url(uri, media=False))
        if status == 200:
            return True
        if status == 404:
            return False
        # a transient error must not read as "absent": copy_prefix branches
        # on this answer (exact-key vs prefix semantics)
        raise IOError(f"GCS head failed ({status}) for {uri}")

    async def size(self, uri: str) -> int | None:
        status, body = await self._call("GET", self._object_url(uri, media=False))
        if status == 404:
            raise FileNotFoundError(uri)
        if status >= 300:
            raise IOError(f"GCS head failed ({status}) for {uri}")
        try:
            return int(json.loads(body).get("size"))
        except (ValueError, TypeError):
            return None

    async def list_prefix(self, prefix_uri: str) -> list[dict[str, Any]]:
        bucket, key = parse_uri(prefix_uri)
        base = f"{self.endpoint}/storage/v1/b/{self._gcs_bucket(bucket)}/o"
        out: list[dict[str, Any]] = []
        page: str | None = None
        while True:
            params = {"prefix": key}
            if page:
                params["pageToken"] = page
            status, body = await self._call("GET", base, params=params)
            if status >= 300:
                raise IOError(f"GCS list failed ({status})")
            doc = json.loads(body)
            for item in doc.get("items", []):
                out.append(
                    {
                        "uri": build_uri(bucket, item["name"]),
                        "size": int(item.get("size", 0)),
                        "mtime": self.parse_iso_mtime(item.get("updated", "")),
                    }
                )
            page = doc.get("nextPageToken")
            if not page:
                return out

    async def delete_prefix(self, prefix_uri: str) -> int:
        objs = await self.list_prefix(prefix_uri)

        async def delete_one(o) -> int:
            status, _ = await self._call(
                "DELETE", self._object_url(o["uri"], media=False)
            )
            if status in (200, 204, 404):
                return 1
            raise IOError(f"GCS delete failed ({status}) for {o['uri']}")

        return sum(await self.map_concurrently(delete_one, objs))

    async def copy_prefix(self, src_uri: str, dst_uri: str) -> int:
        """Server-side copy per object (reference: ``S3Handler.py:375-439`` —
        head the key; on miss treat as prefix), fanned out concurrently."""
        if await self.exists(src_uri):
            objs = [{"uri": src_uri}]
            exact = True
        else:
            objs = await self.list_prefix(src_uri)
            exact = False
        _, src_key = parse_uri(src_uri)
        dst_bucket, dst_key = parse_uri(dst_uri)

        async def copy_one(o) -> int:
            src_b, key = parse_uri(o["uri"])
            rel = "" if exact else key[len(src_key):].lstrip("/")
            target_key = dst_key if exact else f"{dst_key}/{rel}" if rel else dst_key
            url = (
                f"{self.endpoint}/storage/v1/b/{self._gcs_bucket(src_b)}/o/"
                f"{urllib.parse.quote(key, safe='')}/copyTo/b/"
                f"{self._gcs_bucket(dst_bucket)}/o/"
                f"{urllib.parse.quote(target_key, safe='')}"
            )
            status, _ = await self._call("POST", url)
            if status >= 300:
                raise IOError(f"GCS copy failed ({status}) for {o['uri']}")
            return 1

        return sum(await self.map_concurrently(copy_one, objs))
