"""Serve capacity as a first-class, preemptible scheduler tenant.

Training already competes for chips through the fair-share scheduler; this
module makes serving compete the same way instead of squatting outside the
quota math (docs/scheduling.md §Serve tenant, docs/serving.md §Autoscale):

* every fleet replica is one scheduler :class:`Workload` tagged
  ``owner="serve"`` in a (by default low-priority) serve queue — training
  tenants can preempt it, and its chips count against a real queue's share;
* the **autoscaler** (:class:`ServeScalePolicy`) watches router/fleet stats:
  sustained queue-depth pressure grows the fleet one replica at a time (each
  grow is a scheduler submit — it only materialises when admitted), and a
  sustained idle window shrinks it back toward the floor, returning chips to
  training;
* **shrink goes through drain, never kill**: a scale-down (or a training
  tenant preempting a serve workload) drains the replica — in-flight lanes
  finish — and only then releases the workload, so the chips a training job
  reclaims were freed gracefully and are admittable on the very next
  scheduler tick.

The tenant is deliberately pull-based: :meth:`ServeTenant.tick` is called
from the fleet's health cadence (or a test), reads the scheduler's decisions
(``take_preemptions(owner="serve")``, ``is_admitted``) and converges the
fleet toward them.  With ``drive_admission=True`` (standalone use, tests)
the tenant runs ``try_admit`` itself; when sharing a backend's scheduler the
backend's own tick does the admitting and the tenant just polls.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
from typing import Any

logger = logging.getLogger(__name__)

#: default tenant queue serve replicas land in (auto-registers at weight 1.0
#: unless named in FTC_SCHED_QUEUES)
SERVE_QUEUE = "serve"

#: default queue for remote rlhf rollout actor workloads (``owner="rollout"``
#: — the disaggregated data plane's serve-fleet tenants,
#: docs/preference.md §Disaggregated rollouts)
ROLLOUT_QUEUE = "rollout"


class ServeScalePolicy:
    """Queue-depth pressure → target replica count, with hysteresis.

    Pressure = queued requests per healthy replica at or above
    ``scale_up_queue_depth`` for ``sustain_ticks`` consecutive ticks → +1
    replica.  A fully idle fleet (no queue, no busy slots) for
    ``idle_ticks`` consecutive ticks → -1 replica.  Both counters reset on
    any contrary observation, so a single traffic blip neither grows nor
    shrinks the fleet — scale moves cost real chips and real drains.
    """

    def __init__(
        self,
        *,
        min_replicas: int = 1,
        max_replicas: int = 4,
        scale_up_queue_depth: int = 8,
        sustain_ticks: int = 2,
        idle_ticks: int = 3,
    ):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{min_replicas}..{max_replicas}"
            )
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.scale_up_queue_depth = scale_up_queue_depth
        self.sustain_ticks = max(1, sustain_ticks)
        self.idle_ticks = max(1, idle_ticks)
        self._pressure = 0
        self._idle = 0

    def decide(
        self, *, healthy: int, queue_depth: int, slots_busy: int
    ) -> int:
        """Target replica count given the current fleet observation."""
        current = max(healthy, 1)
        per_replica = queue_depth / current
        if per_replica >= self.scale_up_queue_depth:
            self._pressure += 1
            self._idle = 0
        elif queue_depth == 0 and slots_busy == 0:
            self._idle += 1
            self._pressure = 0
        else:
            self._pressure = 0
            self._idle = 0
        target = healthy
        if self._pressure >= self.sustain_ticks:
            target = healthy + 1
            self._pressure = 0
        elif self._idle >= self.idle_ticks:
            target = healthy - 1
            self._idle = 0
        return min(self.max_replicas, max(self.min_replicas, target))


@dataclasses.dataclass
class _ReplicaWorkload:
    workload_id: str
    #: fleet replica id once the admitted workload materialised (None =
    #: still pending admission, or spawn in flight)
    replica_id: str | None = None


class ServeTenant:
    """Binds one :class:`~finetune_controller_tpu.serve.fleet.ReplicaFleet`
    to a :class:`~finetune_controller_tpu.sched.FairShareScheduler`."""

    def __init__(
        self,
        scheduler,
        fleet,
        *,
        flavor: str,
        queue: str = SERVE_QUEUE,
        priority: object = "low",
        policy: ServeScalePolicy | None = None,
        drive_admission: bool = False,
        queue_depth_fn=None,
        owner: str = "serve",
    ):
        self.scheduler = scheduler
        self.fleet = fleet
        self.flavor = flavor
        self.queue = queue
        self.priority = priority
        #: scheduler workload tag — ``take_preemptions(owner=...)`` routes
        #: reclaim decisions to the tenant that owns them; the rollout
        #: tenant reuses this class's machinery under ``owner="rollout"``
        self.owner = owner
        self.policy = policy or ServeScalePolicy()
        #: run ``try_admit`` inside :meth:`tick` (standalone scheduler);
        #: False when a backend's own tick drives admission
        self.drive_admission = drive_admission
        #: optional override for the observed queue depth (a router exposes
        #: fleet-wide depth; default reads the fleet's aggregate stats)
        self._queue_depth_fn = queue_depth_fn
        self._workloads: dict[str, _ReplicaWorkload] = {}
        self._wl_seq = itertools.count()
        # counters (GET /admin/serve, docs/serving.md §Autoscale)
        self.scale_ups_total = 0
        self.scale_downs_total = 0
        self.preempted_total = 0

    # ---- bookkeeping -------------------------------------------------------

    def _bound(self) -> int:
        """Replica workloads submitted (pending or admitted)."""
        return len(self._workloads)

    def _observe(self) -> dict[str, int]:
        stats = self.fleet.stats()
        depth = (
            self._queue_depth_fn() if self._queue_depth_fn is not None
            else stats["queue_depth"]
        )
        return {
            "healthy": stats["replicas_healthy"],
            "queue_depth": int(depth),
            "slots_busy": stats["slots_busy"],
        }

    async def attach_initial(self) -> None:
        """Register workloads for replicas the fleet already runs (the fleet
        starts before the tenant; its floor capacity must still be
        accounted against the serve queue's share)."""
        for replica in self.fleet.healthy_replicas():
            wid = self._submit_workload()
            self._workloads[wid].replica_id = replica.replica_id

    def _submit_workload(self) -> str:
        wid = f"{self.owner}-{self.fleet.job_id}-w{next(self._wl_seq)}"
        self.scheduler.submit(
            wid, self.flavor, 1,
            queue=self.queue, priority=self.priority, owner=self.owner,
        )
        self._workloads[wid] = _ReplicaWorkload(workload_id=wid)
        return wid

    # ---- the reconcile tick ------------------------------------------------

    async def tick(self) -> dict[str, Any]:
        """One reconcile pass: handle preemptions, materialise admitted
        grows, converge toward the policy's target.  Returns a summary for
        logging/tests."""
        summary: dict[str, Any] = {
            "preempted": [], "spawned": [], "drained": [], "target": None,
        }
        # 1. preemptions aimed at serve workloads: drain (never kill), then
        #    release so the preemptor admits on the next scheduler pass
        take = getattr(self.scheduler, "take_preemptions", None)
        if take is not None:
            for decision in take(owner=self.owner):
                await self._drain_workload(
                    decision.job_id,
                    reason=f"preempted for {decision.preemptor_id or 'reclaim'}",
                )
                self.preempted_total += 1
                summary["preempted"].append(decision.job_id)
        # 2. admission: standalone tenants drive it; shared schedulers are
        #    ticked by their backend, and serve workloads skipped there stay
        #    admitted for us to observe
        if self.drive_admission:
            self.scheduler.try_admit()
        # a crashed replica restarts under a NEW id (fleet health loop):
        # rebind its workload to an unbound healthy replica so the chips
        # accounting follows the restart instead of double-spawning
        bound_ids = {wl.replica_id for wl in self._workloads.values()}
        for wl in self._workloads.values():
            if wl.replica_id is not None \
                    and wl.replica_id not in self.fleet.replicas:
                replacement = next(
                    (r.replica_id for r in self.fleet.healthy_replicas()
                     if r.replica_id not in bound_ids), None,
                )
                if replacement is not None:
                    wl.replica_id = replacement
                    bound_ids.add(replacement)
        for wl in list(self._workloads.values()):
            if wl.replica_id is None \
                    and self.scheduler.is_admitted(wl.workload_id):
                replica = await self.fleet.spawn_replica()
                wl.replica_id = replica.replica_id
                summary["spawned"].append(replica.replica_id)
        # keep the fleet's restart ceiling in step with what the scheduler
        # actually granted
        self.fleet.target_replicas = max(1, sum(
            1 for wl in self._workloads.values() if wl.replica_id is not None
        ))
        # 3. autoscale toward the policy target
        obs = self._observe()
        target = self.policy.decide(**obs)
        summary["target"] = target
        if target > self._bound():
            self._submit_workload()
            self.scale_ups_total += 1
            logger.info(
                "serve autoscale: +1 replica for %s (queue_depth=%d over %d "
                "healthy)", self.fleet.job_id, obs["queue_depth"],
                obs["healthy"],
            )
        elif target < self._bound():
            victim = self._pick_shrink_victim()
            if victim is not None:
                await self._drain_workload(victim, reason="idle scale-down")
                self.scale_downs_total += 1
                summary["drained"].append(victim)
        return summary

    def _pick_shrink_victim(self) -> str | None:
        """Prefer a workload still pending admission (free to cancel), else
        the newest materialised replica."""
        for wl in self._workloads.values():
            if wl.replica_id is None:
                return wl.workload_id
        for wl in self._workloads.values():
            # dead binding (replica crashed away, no rebind candidate):
            # the chips are already idle — releasing this one costs nothing
            if wl.replica_id not in self.fleet.replicas:
                return wl.workload_id
        live = [
            (wl, self.fleet.replicas[wl.replica_id])
            for wl in self._workloads.values()
        ]
        live = [(wl, r) for wl, r in live if r.healthy]
        if not live:
            return None
        return max(live, key=lambda p: p[1].started_at)[0].workload_id

    async def _drain_workload(self, workload_id: str, *, reason: str) -> None:
        """Drain the workload's replica (if materialised), then release the
        chips — the order that makes a reclaim graceful AND prompt."""
        wl = self._workloads.pop(workload_id, None)
        if wl is not None and wl.replica_id is not None:
            await self.fleet.drain_replica(wl.replica_id, reason=reason)
        # forget (not release): a drained serve workload never resubmits at
        # a new size, so any reservation must die with it
        getattr(self.scheduler, "forget", self.scheduler.release)(workload_id)

    async def close(self) -> None:
        for wid in list(self._workloads):
            wl = self._workloads.pop(wid)
            getattr(self.scheduler, "forget", self.scheduler.release)(
                wl.workload_id
            )

    def stats(self) -> dict[str, Any]:
        def pid_of(replica_id: str | None) -> int | None:
            """Real process placement for transport fleets: the admitted
            workload's chips are held by THIS worker pid (docs/serving.md
            §Cross-process transport) — '-' for in-process replicas."""
            replica = (
                self.fleet.replicas.get(replica_id)
                if replica_id is not None else None
            )
            if replica is None or not replica.remote:
                return None
            return replica.batcher.pid

        return {
            "workloads": {
                wid: wl.replica_id for wid, wl in self._workloads.items()
            },
            "worker_pids": {
                wid: pid_of(wl.replica_id)
                for wid, wl in self._workloads.items()
            },
            "transport": self.fleet.transport_mode,
            "queue": self.queue,
            "flavor": self.flavor,
            "scale_ups_total": self.scale_ups_total,
            "scale_downs_total": self.scale_downs_total,
            "preempted_total": self.preempted_total,
        }


class RolloutTenant:
    """Remote rlhf rollout actors as ``owner="rollout"`` scheduler tenants.

    The :class:`~finetune_controller_tpu.prefs.rollout_plane.RolloutPlane`
    owns worker LIFECYCLE (spawn, respawn, policy push); this tenant owns
    only their chips accounting: one workload per rollout worker in the
    rollout queue, preemptible like serve capacity.  No autoscale policy —
    the worker count is the job spec's ``rollout_workers`` — so the tick is
    just preemption intake: a reclaimed workload's worker id is handed back
    for the plane to stop (its learner keeps stepping on buffered pairs;
    respawn happens when the scheduler re-admits).
    """

    def __init__(self, scheduler, job_id: str, *, flavor: str,
                 queue: str = ROLLOUT_QUEUE, priority: object = "low"):
        self.scheduler = scheduler
        self.job_id = job_id
        self.flavor = flavor
        self.queue = queue
        self.priority = priority
        #: workload id → worker id, one per remote rollout actor
        self._workloads: dict[str, str] = {}
        self.preempted_total = 0

    def submit(self, worker_id: str) -> str:
        wid = f"rollout-{self.job_id}-{worker_id}"
        self.scheduler.submit(
            wid, self.flavor, 1,
            queue=self.queue, priority=self.priority, owner="rollout",
        )
        self._workloads[wid] = worker_id
        return wid

    def is_admitted(self, worker_id: str) -> bool:
        return self.scheduler.is_admitted(
            f"rollout-{self.job_id}-{worker_id}"
        )

    def tick(self) -> dict[str, Any]:
        """Preemption intake: worker ids whose chips the scheduler reclaimed
        this tick, plus the currently-admitted set."""
        preempted: list[str] = []
        take = getattr(self.scheduler, "take_preemptions", None)
        if take is not None:
            for decision in take(owner="rollout"):
                worker = self._workloads.get(decision.job_id)
                if worker is not None:
                    preempted.append(worker)
                    self.preempted_total += 1
                getattr(self.scheduler, "forget", self.scheduler.release)(
                    decision.job_id
                )
                self._workloads.pop(decision.job_id, None)
        admitted = [
            worker for wid, worker in self._workloads.items()
            if self.scheduler.is_admitted(wid)
        ]
        return {"preempted": preempted, "admitted": admitted}

    def close(self) -> None:
        for wid in list(self._workloads):
            getattr(self.scheduler, "forget", self.scheduler.release)(wid)
            self._workloads.pop(wid, None)

    def stats(self) -> dict[str, Any]:
        return {
            "workloads": dict(self._workloads),
            "queue": self.queue,
            "flavor": self.flavor,
            "preempted_total": self.preempted_total,
        }
