"""Tenant queues, priority classes, and the scheduler's Workload record.

The reference delegates all of this to Kueue's ClusterQueue/LocalQueue CRs
(SURVEY.md §2.2); here a queue is a named tenant with a *weight* — its
entitlement to the cluster relative to its siblings — and every workload
carries a *priority class* that orders admission and gates preemption
(Kueue's ``WorkloadPriorityClass``).

The Workload sequence number is **per-scheduler** (each scheduler owns an
``itertools.count``): the seed's module-global counter leaked ordering
across scheduler instances, which made queue positions test-order-dependent
(ISSUE 5 satellite).
"""

from __future__ import annotations

import dataclasses

#: the tenant queue a submission lands in when it names none
DEFAULT_QUEUE = "default"

#: named priority classes (Kueue WorkloadPriorityClass equivalents).  Higher
#: admits first; a workload can only preempt strictly-lower-priority victims
#: (see preemption.py for the fairness-triggered same-priority case).
PRIORITY_CLASSES: dict[str, int] = {
    "low": 0,
    "normal": 50,
    "high": 100,
}

DEFAULT_PRIORITY = "normal"


def parse_priority(value: object) -> int:
    """Resolve a priority class name or integer to its numeric value.

    Accepts the named classes (``low``/``normal``/``high``), ints, and
    int-shaped strings (an escape hatch for finer-grained orderings).
    Raises ``ValueError`` on anything else — surfaced at submit time as a
    400, never inside the admission loop.
    """
    if isinstance(value, bool):  # bool is an int subclass; reject explicitly
        raise ValueError(f"priority must be a class name or integer, got {value!r}")
    if isinstance(value, int):
        return value
    if isinstance(value, str):
        key = value.strip().lower()
        if key in PRIORITY_CLASSES:
            return PRIORITY_CLASSES[key]
        try:
            return int(key)
        except ValueError:
            raise ValueError(
                f"unknown priority {value!r}; one of "
                f"{sorted(PRIORITY_CLASSES)} or an integer"
            ) from None
    raise ValueError(f"priority must be a class name or integer, got {value!r}")


def priority_name(value: int) -> str:
    """Best-effort display name for a numeric priority."""
    for name, num in PRIORITY_CLASSES.items():
        if num == value:
            return name
    return str(value)


@dataclasses.dataclass
class QueueConfig:
    """One tenant queue (Kueue ClusterQueue equivalent, minus the CRD)."""

    name: str
    #: relative entitlement: a queue's nominal share of every flavor's quota
    #: is ``quota * weight / sum(weights of queues with demand)``
    weight: float = 1.0

    def __post_init__(self) -> None:
        if self.weight <= 0:
            raise ValueError(f"queue {self.name!r} weight must be > 0")


class QueueSet:
    """The configured tenant queues.

    Unknown queue names resolve to an equal-share default (weight 1.0)
    WITHOUT being stored — tenant onboarding must not require a config
    push, and an unknown name failing the submission would be a worse
    failure mode than an equal-share default.  Not storing them is load-
    bearing: queue names are user-supplied, so registration on first use
    would let any submitter grow controller memory (and /metrics label
    cardinality) without bound by minting unique names.
    """

    def __init__(self, queues: list[QueueConfig] | dict[str, float] | None = None):
        self._queues: dict[str, QueueConfig] = {}
        if isinstance(queues, dict):
            queues = [QueueConfig(name=n, weight=w) for n, w in queues.items()]
        for q in queues or []:
            self._queues[q.name] = q
        self._queues.setdefault(DEFAULT_QUEUE, QueueConfig(name=DEFAULT_QUEUE))

    def get(self, name: str) -> QueueConfig:
        q = self._queues.get(name)
        return q if q is not None else QueueConfig(name=name)

    def weight(self, name: str) -> float:
        return self.get(name).weight

    def names(self) -> list[str]:
        """CONFIGURED queue names only (ad-hoc queues are not stored)."""
        return sorted(self._queues)

    def total_weight(self, names: set[str] | None = None) -> float:
        """Sum of weights over ``names`` (default: every configured queue)."""
        if names is None:
            return sum(q.weight for q in self._queues.values())
        return sum(self.get(n).weight for n in names)


@dataclasses.dataclass
class Workload:
    """One queued/admitted job (Kueue ``Workload`` CR equivalent).

    ``seq`` is assigned by the owning scheduler from its per-instance
    counter — never from a module global (the satellite fix).
    """

    job_id: str
    flavor: str
    chips: int
    queue: str = DEFAULT_QUEUE
    priority: int = PRIORITY_CLASSES[DEFAULT_PRIORITY]
    seq: int = 0
    admitted: bool = False
    #: which plane owns this workload's lifecycle: "train" (the backend
    #: starts/stops a trainer process for it) or "serve" (a
    #: ``sched/serve_tenant.py`` replica — the backend must NOT try to start
    #: a process for it, and its preemption decisions route to the serve
    #: tenant, which drains the replica instead of SIGTERMing anything)
    owner: str = "train"
    #: victim of an in-flight preemption/resize: SIGTERM sent, chips still
    #: held until the process exits and the backend releases the workload
    preempting: bool = False
    #: clock reading at submit (scheduler-injected clock; sim uses virtual time)
    submitted_at: float = 0.0
    admitted_at: float | None = None
    #: slice count this workload currently runs at (chips = num_slices *
    #: chips_per_slice); changes across shrink/grow resubmits
    num_slices: int = 1
    #: slice count the job originally asked for — the grow pass restores a
    #: shrunk workload toward this when chips free (docs/elasticity.md)
    requested_slices: int = 1
    #: smallest slice count this workload can RUN at.  1 for ordinary elastic
    #: jobs; equal to ``requested_slices`` for atomic gangs (the RLHF
    #: actor+learner pair, docs/preference.md) — the shrink planner and
    #: elastic admission never go below it, so a gang is only ever admitted
    #: whole or fully preempted
    min_slices: int = 1
    #: slice count an in-flight resize will resubmit this workload at
    #: (None = full eviction or no resize pending)
    resize_to: int | None = None

    @property
    def chips_per_slice(self) -> int:
        return self.chips // max(1, self.num_slices)

    @property
    def shrunk(self) -> bool:
        return self.admitted and self.num_slices < self.requested_slices

    def freed_chips(self) -> int:
        """Chips this (preempting) workload hands to its preemptor when it
        exits: everything on a full eviction, the shed slices on a shrink
        (the rest is reserved for the workload's own resubmit)."""
        if self.resize_to is None:
            return self.chips
        return self.chips - self.resize_to * self.chips_per_slice
