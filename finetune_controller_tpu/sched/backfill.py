"""Reservation-protected backfill.

When the head-of-line workload on a flavor cannot fit, every currently-free
chip plus every chip of its in-flight preemption victims is *reserved* for
it — the anti-starvation guarantee the seed's best-effort FIFO lacked (a
blocked large job watched small jobs stream past it forever).

Backfill then answers: how many chips may later-ranked workloads use
**without delaying that reservation**?  Without runtime estimates the only
safe answer is the *excess* over the head's total need:

    capacity = free + incoming - head_need

where ``incoming`` counts the chips of preemption victims already SIGTERMed
on the head's behalf (they exit within seconds, so the head's start is
imminent and provably unaffected by backfill in the excess).  When no
preemption is possible, ``incoming`` is 0 and ``free < head_need`` by
construction, so the capacity is negative and nothing slips past the head —
strict-FIFO-with-reservation, i.e. no starvation.
"""

from __future__ import annotations


def backfill_capacity(free: int, incoming: int, head_need: int) -> int:
    """Chips available to backfill candidates behind a blocked head.

    ``free``: unused chips on the flavor right now; ``incoming``: chips of
    in-flight preemption victims earmarked for the head; ``head_need``: the
    head workload's full chip request.  Never negative.
    """
    return max(0, free + incoming - head_need)
