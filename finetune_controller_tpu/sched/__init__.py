"""Multi-tenant fair-share scheduling (docs/scheduling.md).

The in-repo replacement for the best-effort-FIFO :class:`GangScheduler`
(``controller/backends/scheduler.py``): named tenant queues with weights,
priority classes on every workload, weighted dominant-resource fair sharing
over the per-flavor chip quotas in the :class:`DeviceCatalog`, cohort
borrowing, checkpoint-aware preemption through the resilience loop, and
reservation-protected backfill.

Modules:

- :mod:`.queues` — tenant queues, priority classes, the Workload record;
- :mod:`.fairshare` — the :class:`FairShareScheduler` itself plus the
  weighted-DRF share math and the Jain fairness index;
- :mod:`.preemption` — the resize-before-evict planner (shrink to fair
  share first, full eviction as the fallback; docs/elasticity.md) and the
  victim ordering (lowest priority, most-over-share, youngest first);
- :mod:`.backfill` — the reservation-protected backfill gate;
- :mod:`.serve_tenant` — serve replicas as preemptible ``owner="serve"``
  workloads with queue-depth autoscaling; shrink and preemption go through
  graceful drain (docs/serving.md §Fleet);
- :mod:`.sim` — a seeded, clock-injected cluster simulator so fairness /
  starvation / preemption / progress-loss properties are provable in fast
  deterministic tests (and ``BENCH_MODE=sched`` comparisons against the
  FIFO and evict-only baselines).
"""

from .backfill import backfill_capacity
from .fairshare import FairShareScheduler, jain_index
from .preemption import ResizeDecision, plan_preemption, select_victims
from .queues import (
    DEFAULT_QUEUE,
    PRIORITY_CLASSES,
    QueueConfig,
    QueueSet,
    Workload,
    parse_priority,
)
from .serve_tenant import SERVE_QUEUE, ServeScalePolicy, ServeTenant

__all__ = [
    "DEFAULT_QUEUE",
    "PRIORITY_CLASSES",
    "SERVE_QUEUE",
    "FairShareScheduler",
    "QueueConfig",
    "QueueSet",
    "ServeScalePolicy",
    "ServeTenant",
    "Workload",
    "ResizeDecision",
    "backfill_capacity",
    "jain_index",
    "parse_priority",
    "plan_preemption",
    "select_victims",
]
