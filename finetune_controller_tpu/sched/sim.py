"""Seeded, clock-injected cluster simulator for scheduler properties.

Real preemption tests cost minutes of wall clock (subprocesses, training,
checkpoints); the scheduler's *policy* properties — fairness, starvation
freedom, quota safety, preempt→resume latency — are pure control-flow and
deserve millisecond-scale deterministic proofs.  This module replays a
workload trace against any scheduler with the GangScheduler surface
(``submit``/``try_admit``/``release`` + optionally ``take_preemptions``)
on a virtual clock:

- a **preempted** job models the resilience loop: it keeps its chips for
  ``preempt_exit_s`` (SIGTERM → checkpoint → exit), loses progress since its
  last checkpoint (``checkpoint_every_s`` granularity), waits out
  ``requeue_delay_s`` (the retry backoff), then resubmits and later resumes;
- per-queue **chip-seconds** are integrated over the contention window
  (>= 2 tenants with arrived-but-unfinished demand) so Jain's fairness
  index is computed on entitlement-normalised allocations;
- every event is totally ordered (time, then a tie-break counter), so a
  seeded trace replays bit-identically — the property tests and
  ``BENCH_MODE=sched`` both lean on this.
"""

from __future__ import annotations

import dataclasses
import heapq
import random
from typing import Callable

from ..controller.devices import DeviceCatalog, DeviceFlavor, FlavorQuota
from .fairshare import jain_index
from .queues import DEFAULT_QUEUE


@dataclasses.dataclass
class SimJob:
    """One trace entry: a job with a known (virtual) runtime."""

    job_id: str
    flavor: str
    duration_s: float
    arrival_s: float = 0.0
    queue: str = DEFAULT_QUEUE
    priority: object = "normal"
    num_slices: int = 1
    #: checkpoint cadence: a preemption rounds completed work down to this
    checkpoint_every_s: float = 30.0


@dataclasses.dataclass
class JobOutcome:
    job_id: str
    queue: str
    chips: int  # at the REQUESTED size (the small-job filter keys off this)
    arrival_s: float
    first_admit_s: float | None = None
    finish_s: float | None = None
    preempted_at: list[float] = dataclasses.field(default_factory=list)
    resumed_at: list[float] = dataclasses.field(default_factory=list)
    #: slice-count trajectory across resizes (for debugging/assertions)
    sizes: list[int] = dataclasses.field(default_factory=list)

    @property
    def queue_wait_s(self) -> float | None:
        if self.first_admit_s is None:
            return None
        return self.first_admit_s - self.arrival_s


@dataclasses.dataclass
class SimReport:
    makespan_s: float
    outcomes: dict[str, JobOutcome]
    preemptions: int
    resizes: int
    preempt_resume_latencies_s: list[float]
    #: per-queue chip-seconds integrated while >= 2 queues had live demand
    contention_chip_seconds: dict[str, float]
    jain_fairness: float
    #: chip-seconds of completed work discarded at preemption/resize exits
    #: (progress since the victim's last periodic checkpoint; 0 under the
    #: save-on-SIGTERM model — see ``ClusterSim.preempt_saves``)
    replay_lost_chip_seconds: float
    #: chip-seconds spent inside exit graces (SIGTERM → checkpoint → exit):
    #: the chips are held but produce no progress — every extra restart a
    #: policy causes pays this, which is what keeps resize churn honest
    exit_overhead_chip_seconds: float
    #: chip-seconds of capacity that sat idle while some job wanted chips it
    #: did not have (pending, or running shrunk below its request) — under
    #: eviction this is dominated by anti-starvation reservations holding
    #: partial capacity for a big readmit; resize keeps those chips training
    idle_demand_chip_seconds: float

    @property
    def progress_lost_chip_seconds(self) -> float:
        """The ISSUE 7 gated metric: chip-seconds of progress the cluster
        lost to capacity churn — work discarded to checkpoint replay, exit-
        grace overhead, and demanded-but-idle capacity.  Resize must beat
        full eviction on this."""
        return (
            self.replay_lost_chip_seconds
            + self.exit_overhead_chip_seconds
            + self.idle_demand_chip_seconds
        )

    def waits(self, *, max_chips: int | None = None) -> list[float]:
        """Queue waits (s), optionally only for jobs at most ``max_chips``."""
        return [
            o.queue_wait_s
            for o in self.outcomes.values()
            if o.queue_wait_s is not None
            and (max_chips is None or o.chips <= max_chips)
        ]


def percentile(xs: list[float], p: float) -> float:
    """Nearest-rank percentile, dependency-free (the sim must not need numpy)."""
    if not xs:
        return 0.0
    xs = sorted(xs)
    k = max(0, min(len(xs) - 1, int(round(p / 100.0 * (len(xs) - 1)))))
    return xs[k]


class ClusterSim:
    """Event-driven replay of a trace against one scheduler instance."""

    def __init__(
        self,
        catalog: DeviceCatalog,
        scheduler_factory: Callable,
        *,
        preempt_exit_s: float = 1.0,
        requeue_delay_s: float = 2.0,
        queue_weights: dict[str, float] | None = None,
        preempt_saves: bool = True,
        tick_interval_s: float = 5.0,
    ):
        self.catalog = catalog
        self.now = 0.0
        #: factory receives the sim clock; FIFO factories may ignore it
        self.scheduler = scheduler_factory(lambda: self.now)
        self.preempt_exit_s = preempt_exit_s
        self.requeue_delay_s = requeue_delay_s
        #: entitlements used to NORMALISE the Jain index.  Explicit so both
        #: legs of an A/B (FIFO vs fair-share) are judged against the SAME
        #: entitlements — a weight-blind scheduler must not get its fairness
        #: scored against flat weights while the other leg uses the trace's.
        self.queue_weights = queue_weights
        #: True models the PR-3 SIGTERM contract: the victim CHECKPOINTS AT
        #: ITS CURRENT STEP before exiting (save-on-preempt, proven
        #: step-continuous in tests/test_sched_e2e.py), so a scheduler-driven
        #: exit replays nothing — its cost is the exit grace itself plus the
        #: requeue window.  False is the legacy pessimistic model (progress
        #: rounds down to the last periodic checkpoint — the SIGKILL-
        #: escalation/crash shape).
        self.preempt_saves = preempt_saves
        #: periodic reconcile cadence (the monitor's ``scheduler_tick``):
        #: without it the grow pass would only run on job arrival/exit edges
        #: and a drained queue could leave shrunk jobs small forever
        self.tick_interval_s = tick_interval_s

    def run(self, jobs: list[SimJob], *, horizon_s: float = 10_000_000.0) -> SimReport:
        jobs_by_id = {j.job_id: j for j in jobs}
        if len(jobs_by_id) != len(jobs):
            raise ValueError("duplicate job_id in trace")
        outcomes = {
            j.job_id: JobOutcome(
                job_id=j.job_id, queue=j.queue, arrival_s=j.arrival_s,
                chips=self._chips(j),
            )
            for j in jobs
        }
        #: remaining work in CHIP-SECONDS: a job's duration is defined at its
        #: requested size, so work = duration * requested_chips; running at
        #: c chips finishes the remainder in remaining/c seconds (the linear
        #: scaling a data-parallel trainer actually gets)
        remaining_cs = {j.job_id: j.duration_s * self._chips(j) for j in jobs}
        #: slice count each job runs (or will resubmit) at; shrinks/grows
        #: rewrite it when the decision is taken
        cur_slices = {j.job_id: max(1, j.num_slices) for j in jobs}
        #: chips the live attempt actually occupies (for integration)
        cur_chips: dict[str, int] = {}
        started_at: dict[str, float] = {}
        #: per-job attempt generation; bumped on every (re)start AND on
        #: preemption so stale finish events are recognisably dead
        attempt: dict[str, int] = {j.job_id: 0 for j in jobs}
        #: arrived-but-unfinished job ids per queue (live demand)
        live_by_queue: dict[str, set[str]] = {j.queue: set() for j in jobs}

        heap: list[tuple[float, int, str, str, int]] = []
        tie = 0

        def push(t: float, kind: str, job_id: str, att: int = 0) -> None:
            nonlocal tie
            heapq.heappush(heap, (t, tie, kind, job_id, att))
            tie += 1

        for j in jobs:
            push(j.arrival_s, "arrive", j.job_id)

        running_chips: dict[str, float] = {}  # per queue
        contention_cs: dict[str, float] = {}
        contended_queues: set[str] = set()
        last_t = 0.0
        preempt_latencies: list[float] = []
        first_arrival = min((j.arrival_s for j in jobs), default=0.0)
        makespan_end = first_arrival
        replay_lost = 0.0
        exit_overhead = 0.0
        idle_demand = 0.0
        resizes = 0
        evictions = 0
        total_quota = sum(
            self.catalog.quota_for(f.name) for f in self.catalog.flavors
        )
        req_chips = {j.job_id: self._chips(j) for j in jobs}

        def integrate(to_t: float) -> None:
            nonlocal last_t, idle_demand
            dt = to_t - last_t
            if dt > 0:
                live = {q for q, ids in live_by_queue.items() if ids}
                # Jain window: >= 2 queues with live demand (PR-5 semantics)
                if len(live) >= 2:
                    contended_queues.update(live)
                    for q in live:
                        r = running_chips.get(q, 0.0)
                        contention_cs[q] = contention_cs.get(q, 0.0) + r * dt
                # idle-under-demand: some live job wants chips it does not
                # have (pending, or running below its requested size) while
                # capacity sits free — counted up to the unmet amount
                unmet = sum(
                    max(0, req_chips[jid] - cur_chips.get(jid, 0))
                    for ids in live_by_queue.values() for jid in ids
                )
                if unmet > 0:
                    idle = max(0.0, total_quota - sum(running_chips.values()))
                    idle_demand += min(idle, float(unmet)) * dt
            last_t = to_t

        def on_decisions() -> None:
            nonlocal resizes, evictions
            take = getattr(self.scheduler, "take_preemptions", None)
            if take is None:
                return
            for d in take():
                victim_id, to_slices = self._decision(d)
                o = outcomes[victim_id]
                o.preempted_at.append(self.now)
                if to_slices:
                    resizes += 1
                    cur_slices[victim_id] = to_slices
                else:
                    evictions += 1
                # bump the generation so the victim's in-flight finish is
                # dead; the exit event carries the new generation
                attempt[victim_id] += 1
                push(self.now + self.preempt_exit_s, "exit", victim_id,
                     attempt[victim_id])

        def schedule() -> None:
            for w in self.scheduler.try_admit():
                j = jobs_by_id[w.job_id]
                o = outcomes[w.job_id]
                if o.first_admit_s is None:
                    o.first_admit_s = self.now
                if len(o.resumed_at) < len(o.preempted_at):
                    o.resumed_at.append(self.now)
                    preempt_latencies.append(self.now - o.preempted_at[-1])
                started_at[w.job_id] = self.now
                attempt[w.job_id] += 1
                cur_chips[w.job_id] = w.chips
                # the FIFO scheduler's minimal Workload has no slice count
                o.sizes.append(getattr(w, "num_slices", 1))
                running_chips[j.queue] = (
                    running_chips.get(j.queue, 0.0) + w.chips
                )
                push(self.now + remaining_cs[w.job_id] / max(w.chips, 1),
                     "finish", w.job_id, attempt[w.job_id])
            on_decisions()

        # the monitor's periodic reconcile: without ticks, a drained queue
        # would leave the grow pass (and reservation TTLs) waiting for the
        # next job edge that may never come.  Only schedulers that resize
        # need it — FIFO/evict replays stay identical to PR 5 event-for-event.
        ticking = bool(getattr(self.scheduler, "resize", False))
        if ticking and jobs:
            push(first_arrival + self.tick_interval_s, "tick", jobs[0].job_id)

        while heap:
            t, _, kind, job_id, att = heapq.heappop(heap)
            if t > horizon_s:
                raise RuntimeError(
                    f"simulation passed the horizon ({horizon_s}s) with "
                    f"unfinished jobs — likely a starved or thrashing schedule"
                )
            integrate(t)
            self.now = t
            if kind == "tick":
                if any(o.finish_s is None for o in outcomes.values()):
                    push(t + self.tick_interval_s, "tick", job_id)
                    schedule()
                continue
            j = jobs_by_id[job_id]
            o = outcomes[job_id]
            if kind == "arrive":
                live_by_queue[j.queue].add(job_id)
                self.scheduler.submit(
                    job_id, j.flavor, j.num_slices,
                    queue=j.queue, priority=j.priority,
                )
            elif kind == "resubmit":
                self._resubmit(j, cur_slices[job_id])
            elif kind == "finish":
                if att != attempt[job_id]:
                    continue  # stale: this attempt was preempted
                self.scheduler.release(job_id)
                running_chips[j.queue] = (
                    running_chips.get(j.queue, 0.0) - cur_chips.pop(job_id, 0)
                )
                remaining_cs[job_id] = 0.0
                live_by_queue[j.queue].discard(job_id)
                o.finish_s = t
                makespan_end = max(makespan_end, t)
            elif kind == "exit":
                # the victim's process exited: progress rounds down to the
                # last checkpoint BEFORE the SIGTERM, chips free, and the job
                # requeues after its retry backoff (a resized victim at its
                # new size — the reservation inside the scheduler holds its
                # chips through this window)
                if att != attempt[job_id]:
                    continue
                chips = cur_chips.pop(job_id, 0)
                run_s = max(0.0, o.preempted_at[-1] - started_at[job_id])
                if self.preempt_saves:
                    # PR-3 SIGTERM contract: checkpoint AT the current step,
                    # then exit — nothing replays
                    saved_s = run_s
                else:
                    ckpt = max(j.checkpoint_every_s, 1e-9)
                    saved_s = min(run_s, (run_s // ckpt) * ckpt)
                remaining_cs[job_id] = max(
                    0.0, remaining_cs[job_id] - saved_s * chips
                )
                replay_lost += (run_s - saved_s) * chips
                # the exit grace holds the chips while saving/tearing down
                exit_overhead += max(0.0, t - o.preempted_at[-1]) * chips
                self.scheduler.release(job_id)
                running_chips[j.queue] = (
                    running_chips.get(j.queue, 0.0) - chips
                )
                push(t + self.requeue_delay_s, "resubmit", job_id)
            schedule()

        alloc = [
            contention_cs.get(q, 0.0) / max(self._queue_weight(q), 1e-9)
            for q in sorted(contended_queues)
        ]
        return SimReport(
            makespan_s=makespan_end - first_arrival,
            outcomes=outcomes,
            preemptions=evictions + resizes,
            resizes=resizes,
            preempt_resume_latencies_s=preempt_latencies,
            contention_chip_seconds=contention_cs,
            jain_fairness=jain_index(alloc),
            replay_lost_chip_seconds=replay_lost,
            exit_overhead_chip_seconds=exit_overhead,
            idle_demand_chip_seconds=idle_demand,
        )

    # -- internals -----------------------------------------------------------

    def _chips(self, j: SimJob) -> int:
        flavor = self.catalog.get_worker(j.flavor)
        return flavor.total_chips * max(1, j.num_slices)

    @staticmethod
    def _decision(d) -> tuple[str, int]:
        """Normalise a scheduler decision to ``(victim_id, to_slices)`` —
        accepts both ResizeDecision objects and legacy (victim, preemptor)
        pairs (to_slices 0 = full eviction)."""
        to = getattr(d, "to_slices", None)
        if to is not None:
            return d.job_id, int(to)
        victim_id, _preemptor = d
        return victim_id, 0

    def _resubmit(self, j: SimJob, slices: int) -> None:
        kwargs = dict(queue=j.queue, priority=j.priority)
        if slices != max(1, j.num_slices):
            # only resized resubmits pass requested_slices (the FIFO
            # scheduler never resizes, so it never sees the kwarg)
            kwargs["requested_slices"] = max(1, j.num_slices)
        self.scheduler.submit(j.job_id, j.flavor, slices, **kwargs)

    def _queue_weight(self, queue: str) -> float:
        if self.queue_weights is not None:
            return self.queue_weights.get(queue, 1.0)
        queues = getattr(self.scheduler, "queues", None)
        return queues.weight(queue) if queues is not None else 1.0


# ---------------------------------------------------------------------------
# Canonical trace + catalog for tests and BENCH_MODE=sched
# ---------------------------------------------------------------------------


def sim_catalog(chips: int = 8, flavor: str = "sim-chip") -> DeviceCatalog:
    """A one-flavor virtual cluster: 1 chip per slice, ``chips`` quota."""
    return DeviceCatalog(
        flavors=[DeviceFlavor(
            name=flavor, generation="cpu", hosts=1, chips_per_host=1,
            runtime="cpu", queue="sim-queue",
        )],
        quotas=[FlavorQuota(flavor=flavor, nominal_chips=chips)],
        default_flavor=flavor,
    )


def synthetic_trace(
    seed: int = 0,
    *,
    flavor: str = "sim-chip",
    n_big: int = 4,
    n_small: int = 24,
) -> list[SimJob]:
    """The head-of-line-blocking trace: long low-priority multi-chip batch
    jobs saturate the cluster early, then a stream of short 1-chip jobs from
    two higher-entitlement tenants arrives.  FIFO strands the small jobs
    behind the saturated quota for the batch jobs' full runtime; fair-share
    preempts (checkpoint-aware) and lets them flow."""
    rng = random.Random(seed)
    jobs: list[SimJob] = []
    for i in range(n_big):
        jobs.append(SimJob(
            job_id=f"batch-{i}", flavor=flavor, num_slices=4,
            duration_s=rng.uniform(500.0, 700.0),
            arrival_s=rng.uniform(0.0, 2.0),
            queue="batch", priority="low", checkpoint_every_s=60.0,
        ))
    for i in range(n_small):
        q, prio = (("prod", "high") if i % 2 == 0 else ("research", "normal"))
        jobs.append(SimJob(
            job_id=f"small-{i}", flavor=flavor, num_slices=1,
            duration_s=rng.uniform(20.0, 45.0),
            arrival_s=10.0 + i * rng.uniform(2.0, 6.0),
            queue=q, priority=prio, checkpoint_every_s=30.0,
        ))
    return jobs


#: queue weights for the canonical trace (prod is the paying tenant)
TRACE_QUEUES = {"batch": 1.0, "research": 2.0, "prod": 4.0}


def elastic_trace(
    seed: int = 0,
    *,
    flavor: str = "sim-chip",
    xl_slices: int = 8,
    n_small: int = 16,
) -> list[SimJob]:
    """The capacity-reclaim trace — the scenario resize exists for (ISSUE 7
    motivation: "losing chips means a job either waits for the original
    topology or loses all progress").

    A whole-cluster XL batch job saturates the quota; then a high-priority
    4-slice reclaim (the quota-reclaim / maintenance shape) and a stream of
    1-chip tenant jobs arrive.  Under full eviction the XL job cannot run
    again until ALL of its chips are simultaneously free, so its
    anti-starvation reservation idles every chip that frees before the last
    arrival drains; under resize it degrades onto the leftovers and grows
    back.  ``BENCH_MODE=sched`` gates resize-vs-evict progress loss here.
    """
    rng = random.Random(seed)
    jobs: list[SimJob] = [
        SimJob(
            job_id="xl-0", flavor=flavor, num_slices=xl_slices,
            duration_s=600.0, arrival_s=0.0,
            queue="batch", priority="low", checkpoint_every_s=60.0,
        ),
        SimJob(
            job_id="reclaim-0", flavor=flavor, num_slices=4,
            duration_s=rng.uniform(150.0, 200.0), arrival_s=20.0,
            queue="prod", priority="high", checkpoint_every_s=60.0,
        ),
    ]
    for i in range(n_small):
        q, prio = (("prod", "high") if i % 2 == 0 else ("research", "normal"))
        jobs.append(SimJob(
            job_id=f"small-{i}", flavor=flavor, num_slices=1,
            duration_s=rng.uniform(20.0, 45.0),
            arrival_s=10.0 + i * rng.uniform(4.0, 10.0),
            queue=q, priority=prio, checkpoint_every_s=30.0,
        ))
    return jobs
