"""Weighted dominant-resource fair sharing + the FairShareScheduler.

The drop-in replacement for the best-effort-FIFO ``GangScheduler``
(``controller/backends/scheduler.py``): same surface (``submit`` /
``try_admit`` / ``release`` / ``pending`` / ``position`` / ``usage``) so the
local backend and the admin routes keep working, plus the multi-tenant
machinery the ROADMAP's "heavy traffic" north star needs:

- **ordering**: pending workloads rank by priority class (desc), then their
  queue's *weighted dominant share* (asc — the DRF rule: serve the tenant
  farthest below its entitlement first), then submission sequence;
- **borrowing**: nominal shares divide each flavor's quota among the queues
  with *demand* on it — an idle queue is simply absent from the denominator,
  so its quota is lendable and reclaimable (via preemption) the moment it
  wakes up;
- **preemption → resize**: a blocked higher-priority or under-share head
  plans shrinks-then-evictions (``preemption.plan_preemption``,
  docs/elasticity.md), the backend SIGTERMs the victims through the
  resilience loop, and the freed chips are *reserved* for the preemptor
  (and a shrinking victim's surviving slices for its own resubmit) — no
  admission race;
- **elastic admission + grow**: a blocked multi-slice head with no
  preemption path starts shrunk within its fair share instead of idling a
  reservation, and shrunk workloads grow back once the flavor has been
  tenant-quiet for ``grow_delay_s``;
- **backfill**: later-ranked workloads admit only into capacity provably in
  excess of the head's reservation (``backfill.backfill_capacity``).

Everything is synchronous and in-memory (trivially testable, like the seed
scheduler); the clock is injected so the simulator (``sched/sim.py``) can
drive it on virtual time.
"""

from __future__ import annotations

import collections
import itertools
import logging
import time
from typing import Iterable

from ..controller.devices import DeviceCatalog
from .backfill import backfill_capacity
from .preemption import ResizeDecision, plan_preemption
from .queues import (
    DEFAULT_PRIORITY,
    DEFAULT_QUEUE,
    QueueConfig,
    QueueSet,
    Workload,
    parse_priority,
)

logger = logging.getLogger(__name__)


def jain_index(values: Iterable[float]) -> float:
    """Jain's fairness index: 1.0 = perfectly even, 1/n = maximally unfair.

    Computed over *entitlement-normalised* allocations (caller divides each
    tenant's allocation by its weight first).
    """
    xs = [float(v) for v in values]
    if not xs:
        return 1.0
    total = sum(xs)
    sq = sum(x * x for x in xs)
    if sq == 0:
        return 1.0  # nobody got anything: degenerate but not "unfair"
    return (total * total) / (len(xs) * sq)


class FairShareScheduler:
    """Quota-based admission with weighted fair sharing and preemption."""

    def __init__(
        self,
        catalog: DeviceCatalog,
        queues: list[QueueConfig] | dict[str, float] | None = None,
        *,
        clock=time.monotonic,
        resize: bool = True,
        grow_delay_s: float = 60.0,
        reservation_ttl_s: float = 300.0,
    ):
        self._catalog = catalog
        self.queues = QueueSet(queues)
        self._clock = clock
        #: resize-instead-of-evict (docs/elasticity.md): shrink multi-slice
        #: victims to their fair share before full evictions, and grow them
        #: back when chips free.  False degrades to the PR-5 evict-only
        #: behavior (FTC_SCHED_RESIZE=false).
        self.resize = resize
        #: the flavor must be TENANT-QUIET this long — no demand from any
        #: queue other than the shrunk workloads' own — before the grow
        #: pass restarts one at a larger size (and the workload itself must
        #: have run this long since admission).  Growing costs a restart,
        #: so growing into a momentary gap between tenant arrivals would
        #: thrash (shrink, grow, shrink again) and claw back chips the
        #: contending tenants are entitled to.
        self.grow_delay_s = grow_delay_s
        #: flavor -> clock reading when it last became tenant-quiet
        #: (absent = other-tenant demand present); the grow pass's lull timer
        self._quiet_since: dict[str, float] = {}
        #: resize reservations expire after this long: if the resubmit never
        #: arrives (job cancelled mid-resize, controller crash), the chips
        #: must not stay fenced off forever
        self.reservation_ttl_s = reservation_ttl_s
        self._workloads: dict[str, Workload] = {}
        #: per-scheduler sequence (the satellite fix: the seed's module-global
        #: counter made queue positions depend on unrelated instances)
        self._seq = itertools.count()
        #: preemptor job_id -> victim job_ids still exiting on its behalf
        self._claims: dict[str, list[str]] = {}
        #: decisions selected but not yet delivered to the backend
        self._pending_preemptions: list[ResizeDecision] = []
        #: job_id -> (flavor, chips, deadline): chips fenced off for a
        #: resized workload's own resubmit — a shrink frees only the shed
        #: slices to the preemptor; the rest must survive the exit/requeue
        #: window or the victim would strand behind whoever grabbed them
        self._resize_reservations: dict[str, tuple[str, int, float]] = {}
        # observability
        self.preemptions_total = 0
        self.preemptions_by_queue: dict[str, int] = {}
        self.resizes_total = 0
        self.shrinks_total = 0
        self.grows_total = 0
        #: workloads started below their requested size (elastic admission);
        #: in resize_history these are the "shrink" entries with no preemptor
        self.admitted_shrunk_total = 0
        self.resizes_by_queue: dict[str, int] = {}
        #: recent resize decisions (GET /admin/scheduler, ftc-ctl queue)
        self.resize_history: collections.deque = collections.deque(maxlen=50)

    # -- submission / release ------------------------------------------------

    def submit(
        self,
        job_id: str,
        flavor_name: str,
        num_slices: int = 1,
        *,
        queue: str | None = None,
        priority: object | None = None,
        requested_slices: int | None = None,
        min_slices: int = 1,
        owner: str = "train",
    ) -> Workload:
        """Register a suspended workload under a tenant queue + priority.

        ``owner`` tags which plane runs the workload ("train" = the backend
        spawns a trainer; "serve" = a serve-tenant replica) — admission and
        preemption delivery filter on it so each plane only ever handles its
        own workloads (docs/scheduling.md §Serve tenant).

        ``requested_slices`` (>= ``num_slices``) is the topology the job
        originally asked for; a resized resubmit runs at ``num_slices`` and
        the grow pass restores it toward ``requested_slices`` when chips
        free.  Defaults to ``num_slices`` (a job at its full size).

        ``min_slices`` floors every shrink: an atomic gang (RLHF
        actor+learner, ``spec.atomic_gang``) submits with
        ``min_slices == num_slices`` and is then only ever admitted whole
        or fully evicted — never resized.
        """
        if job_id in self._workloads:
            raise ValueError(f"workload {job_id!r} already queued")
        flavor = self._catalog.get_worker(flavor_name)
        num_slices = max(1, num_slices)
        requested = max(num_slices, requested_slices or num_slices)
        need = flavor.total_chips * num_slices
        quota = self._catalog.quota_for(flavor.name)
        if need > quota:
            # an inadmissible head would hold its flavor's reservation
            # forever (strict anti-starvation means nothing passes it) —
            # refuse at submit, where it surfaces as a 400, not a wedge
            raise ValueError(
                f"workload {job_id!r} needs {need} chips of {flavor.name!r} "
                f"but the quota is {quota}: it can never be admitted"
            )
        w = Workload(
            job_id=job_id,
            flavor=flavor.name,
            chips=need,
            queue=queue or DEFAULT_QUEUE,
            priority=parse_priority(
                priority if priority is not None else DEFAULT_PRIORITY
            ),
            seq=next(self._seq),
            submitted_at=self._clock(),
            num_slices=num_slices,
            requested_slices=requested,
            min_slices=max(1, min(min_slices, num_slices)),
            owner=owner,
        )
        self._workloads[job_id] = w
        return w

    def release(self, job_id: str) -> None:
        """Free a workload's quota (finished, deleted, or preempted-and-exited).

        A resize reservation deliberately SURVIVES release: the victim's
        exit is exactly when its chips must stay fenced for the resubmit.
        Reservations die on admission, on :meth:`forget`, or at their TTL.
        """
        self._workloads.pop(job_id, None)
        self._claims.pop(job_id, None)  # it was a preemptor: drop its claim
        for victims in self._claims.values():
            if job_id in victims:
                victims.remove(job_id)

    def forget(self, job_id: str) -> None:
        """Release + drop any resize reservation — the job is gone for good
        (cancelled/terminal), not coming back at a new size."""
        self.release(job_id)
        self._resize_reservations.pop(job_id, None)

    # -- share math ----------------------------------------------------------

    def _used_chips(self, flavor: str) -> int:
        return sum(
            w.chips for w in self._workloads.values()
            if w.admitted and w.flavor == flavor
        )

    def _queue_used(self, queue: str, flavor: str) -> int:
        return sum(
            w.chips for w in self._workloads.values()
            if w.admitted and w.flavor == flavor and w.queue == queue
        )

    def _active_queues(self, flavor: str) -> set[str]:
        """Queues with demand (pending or admitted) on a flavor — the cohort
        sharing that flavor's quota.  Idle queues are absent, which is
        exactly what makes their share lendable."""
        return {
            w.queue for w in self._workloads.values() if w.flavor == flavor
        }

    def nominal_share(self, queue: str, flavor: str) -> float:
        """``quota * weight / sum(weights of the flavor's active cohort)``."""
        active = self._active_queues(flavor)
        if queue not in active:
            active = active | {queue}
        total_w = self.queues.total_weight(active)
        if total_w <= 0:
            return 0.0
        quota = self._catalog.quota_for(flavor)
        return quota * self.queues.weight(queue) / total_w

    def _over_share(self, flavor: str) -> dict[str, float]:
        """Per-queue chips above nominal share on a flavor (<=0 = within)."""
        return {
            q: self._queue_used(q, flavor) - self.nominal_share(q, flavor)
            for q in self._active_queues(flavor)
        }

    def weighted_dominant_share(self, queue: str) -> float:
        """DRF: the queue's largest per-flavor usage fraction, normalised by
        its weight.  Low = under-served, admitted first."""
        dom = 0.0
        for f in self._catalog.flavors:
            quota = self._catalog.quota_for(f.name)
            if quota <= 0:
                continue
            dom = max(dom, self._queue_used(queue, f.name) / quota)
        return dom / self.queues.weight(queue)

    # -- admission -----------------------------------------------------------

    def _rank_key(self, w: Workload, wds: dict[str, float]):
        return (-w.priority, wds[w.queue], w.seq)

    def _incoming_chips(self, preemptor: Workload) -> int:
        """Chips of in-flight victims SIGTERMed on this preemptor's behalf —
        still admitted (held) but guaranteed to free within the resilience
        loop's exit grace.  A shrinking victim contributes only its shed
        slices; the rest is its own resubmit's reservation."""
        return sum(
            self._workloads[v].freed_chips()
            for v in self._claims.get(preemptor.job_id, ())
            if v in self._workloads and self._workloads[v].preempting
        )

    def _reserve(self, job_id: str, flavor: str, chips: int) -> None:
        self._resize_reservations[job_id] = (
            flavor, chips, self._clock() + self.reservation_ttl_s
        )

    def _reserved_chips(self, flavor: str, *, exclude: str | None = None) -> int:
        """Unexpired resize-reservation chips on a flavor, excluding one
        job's own reservation (a workload may always consume its own).

        A reservation whose job is still ADMITTED (a victim that has not
        exited yet, or a grow target still running at its old size) only
        counts for the chips BEYOND what the job currently holds —
        ``_used_chips`` already covers the held part, and double-counting it
        would drive free capacity negative and trigger spurious extra
        preemptions for a head whose shortfall is in fact covered."""
        now = self._clock()
        total = 0
        for job_id in list(self._resize_reservations):
            f, chips, deadline = self._resize_reservations[job_id]
            if deadline < now:
                logger.warning(
                    "resize reservation for %s (%d chips of %s) expired "
                    "unconsumed; releasing", job_id, chips, f,
                )
                del self._resize_reservations[job_id]
                continue
            if f != flavor or job_id == exclude:
                continue
            live = self._workloads.get(job_id)
            if live is not None and live.admitted:
                total += max(0, chips - live.chips)
            else:
                total += chips
        return total

    def _own_reservation(self, w: Workload) -> int:
        res = self._resize_reservations.get(w.job_id)
        if res is None or res[0] != w.flavor:
            return 0
        return res[1]

    def try_admit(self) -> list[Workload]:
        """Admit every pending workload the fair-share policy allows.

        Returns the newly admitted workloads (the backend starts them).
        Preemption/resize victims selected during the pass are queued for
        :meth:`take_preemptions` — the backend SIGTERMs them and their chips
        stay reserved for the blocked head (and, on a shrink, for the
        victim's own resubmit) until they exit.  A final grow pass restores
        shrunk workloads toward their requested size from leftover capacity.
        """
        now = self._clock()
        wds = {
            q: self.weighted_dominant_share(q)
            for q in {w.queue for w in self._workloads.values()}
        }
        pend = sorted(
            (w for w in self._workloads.values() if not w.admitted),
            key=lambda w: self._rank_key(w, wds),
        )
        free: dict[str, int] = {}
        admitted: list[Workload] = []
        head_blocked: dict[str, Workload] = {}
        for w in pend:
            f = w.flavor
            if f not in free:
                # free = physically unused minus OTHER jobs' resize
                # reservations; a workload's own reservation is added back
                # per-candidate below
                free[f] = (
                    self._catalog.quota_for(f)
                    - self._used_chips(f)
                    - self._reserved_chips(f)
                )
            own = self._own_reservation(w)
            head = head_blocked.get(f)
            if head is not None:
                # behind a blocked head: only provably-excess chips admit,
                # and only chips that are PHYSICALLY free right now — the
                # capacity formula counts in-flight victim chips the head
                # will consume, which nobody else may start on.  A
                # candidate's OWN resize reservation is exempt from the
                # head's claim (those chips were fenced for exactly this
                # resubmit), so it adds to the excess, not to the pool the
                # head may take.
                cap = own + backfill_capacity(
                    free[f], self._incoming_chips(head), head.chips
                )
                if 0 < w.chips <= min(cap, free[f] + own):
                    self._admit(w, now, admitted, free)
                continue
            avail = free[f] + own
            if w.chips <= avail:
                self._admit(w, now, admitted, free)
                continue
            if self._maybe_preempt(w, avail):
                # victims are exiting (or already incoming) on this head's
                # behalf: it stays pending with its chips reserved
                head_blocked[f] = w
                continue
            # ELASTIC ADMISSION (docs/elasticity.md): no preemption can
            # cover the shortfall — rather than park as a blocked head whose
            # anti-starvation reservation idles every chip that frees, a
            # multi-slice workload starts SHRUNK on what is free right now;
            # the grow pass restores it when capacity returns.  Checkpoints
            # are topology-portable, so a resumed job lands here too.
            # Fair-share cap: the shrunk admission must keep the queue
            # STRICTLY within its nominal share (floored to slice
            # granularity) — uncapped, a deep queue would absorb every idle
            # chip during contention and crowd the tenants the share math
            # protects.  A queue whose whole share is already in use (or
            # whose share rounds below one slice) parks as a blocked head
            # exactly as before.
            cps = w.chips_per_slice
            if self.resize and w.num_slices > 1 and cps > 0 and avail >= cps:
                share_room = (
                    self.nominal_share(w.queue, f)
                    - self._queue_used(w.queue, f)
                )
                share_slices = int(max(0.0, share_room) // cps)
                fit = min(w.num_slices - 1, avail // cps, share_slices)
                # an atomic gang (min_slices == num_slices) never admits
                # partially — a gang missing its actor (or learner) slice
                # cannot make progress at all
                if fit >= max(1, w.min_slices):
                    d = ResizeDecision(
                        job_id=w.job_id, preemptor_id=None,
                        from_slices=w.num_slices, to_slices=fit,
                    )
                    w.num_slices = fit
                    w.chips = fit * cps
                    self._record_resize(d, w)
                    self.admitted_shrunk_total += 1
                    logger.info(
                        "elastic admission: %s starts at %d/%d slices "
                        "(%d chips of %s free)",
                        w.job_id, fit, w.requested_slices, avail, w.flavor,
                    )
                    self._admit(w, now, admitted, free)
                    continue
            head_blocked[f] = w
        if self.resize:
            self._grow_pass(now, free, head_blocked)
        return admitted

    def _admit(self, w: Workload, now: float, admitted: list[Workload],
               free: dict[str, int]) -> None:
        w.admitted = True
        w.admitted_at = now
        own = 0
        if w.job_id in self._resize_reservations:
            own = self._own_reservation(w)
            del self._resize_reservations[w.job_id]  # consumed
        free[w.flavor] -= max(0, w.chips - own)
        self._claims.pop(w.job_id, None)  # reservation consumed
        admitted.append(w)
        logger.info(
            "admitted %s (%d chips of %s, queue=%s prio=%d, slices=%d/%d)",
            w.job_id, w.chips, w.flavor, w.queue, w.priority,
            w.num_slices, w.requested_slices,
        )

    def _maybe_preempt(self, w: Workload, free_chips: int) -> bool:
        """Plan shrinks/evictions covering the head's shortfall (beyond
        chips already incoming from earlier preemptions) and reserve them
        for it (docs/elasticity.md: resize-instead-of-evict).  Returns True
        when the head's full size is covered (victims exiting or already
        incoming) — i.e. it should stay pending rather than admit shrunk."""
        shortfall = w.chips - free_chips - self._incoming_chips(w)
        if shortfall <= 0:
            return True
        over = self._over_share(w.flavor)
        # RECLAIM-ONLY fairness trigger: a queue may fairness-preempt (same
        # priority, victim queue over share) only when it stays within its
        # own nominal share after admission.  A borrower preempting would
        # oscillate: post-swap the roles reverse and the displaced queue
        # preempts right back — reclaim-only makes the swap a fixed point.
        under = (
            self._queue_used(w.queue, w.flavor) + w.chips
            <= self.nominal_share(w.queue, w.flavor) + 1e-9
        )
        candidates = [
            c for c in self._workloads.values()
            if c.admitted and c.flavor == w.flavor
        ]
        plans = plan_preemption(
            w, candidates, shortfall,
            over_share=over, preemptor_under_share=under, resize=self.resize,
        )
        if not plans:
            return False
        claim = self._claims.setdefault(w.job_id, [])
        for d in plans:
            v = self._workloads[d.job_id]
            v.preempting = True
            v.resize_to = d.to_slices or None
            claim.append(v.job_id)
            self._pending_preemptions.append(d)
            if d.kind == "evict":
                self.preemptions_total += 1
                self.preemptions_by_queue[v.queue] = (
                    self.preemptions_by_queue.get(v.queue, 0) + 1
                )
            else:
                # the shrunk victim's surviving slices are fenced for its
                # own resubmit — without this, whoever admits first during
                # the exit/backoff window strands the victim
                self._record_resize(d, v)
                self._reserve(
                    v.job_id, v.flavor, d.to_slices * v.chips_per_slice
                )
            logger.info(
                "%s %s (queue=%s prio=%d, %d chips, slices %d->%s) for %s "
                "(queue=%s prio=%d)",
                d.kind, v.job_id, v.queue, v.priority, v.chips,
                d.from_slices, d.to_slices or "none",
                w.job_id, w.queue, w.priority,
            )
        return True

    def _record_resize(self, d: ResizeDecision, v: Workload) -> None:
        self.resizes_total += 1
        if d.kind == "shrink":
            self.shrinks_total += 1
        else:
            self.grows_total += 1
        self.resizes_by_queue[v.queue] = self.resizes_by_queue.get(v.queue, 0) + 1
        self.resize_history.append({
            "job_id": d.job_id,
            "kind": d.kind,
            "from_slices": d.from_slices,
            "to_slices": d.to_slices,
            "preemptor": d.preemptor_id,
            "queue": v.queue,
            "at": self._clock(),
        })

    def _grow_pass(self, now: float, free: dict[str, int],
                   head_blocked: dict[str, Workload]) -> None:
        """Restore shrunk workloads toward their requested size from chips
        nobody pending could use.  Runs only for flavors with NO blocked
        head (a blocked head's reservation owns the leftovers) and only for
        workloads that have run at least ``grow_delay_s`` since admission —
        growing costs a checkpoint restart, so it must not thrash."""
        shrunk = [
            w for w in self._workloads.values()
            if w.shrunk and not w.preempting
        ]
        # quiet timer per flavor: the flavor must be free of OTHER tenants'
        # demand for a sustained window before a grow restart is worth
        # paying — update it for every flavor a shrunk workload lives on,
        # even when the grow below is skipped
        shrunk_queues: dict[str, set] = {}
        for w in shrunk:
            shrunk_queues.setdefault(w.flavor, set()).add(w.queue)
        for f, queues in shrunk_queues.items():
            if f not in free:
                free[f] = (
                    self._catalog.quota_for(f)
                    - self._used_chips(f)
                    - self._reserved_chips(f)
                )
            others = any(
                x.flavor == f and x.queue not in queues
                for x in self._workloads.values()
            )
            if others or f in head_blocked:
                self._quiet_since.pop(f, None)
            else:
                self._quiet_since.setdefault(f, now)
        # most-shrunk-first, then oldest: the workload farthest below its
        # request has waited hardest for its chips back
        shrunk.sort(key=lambda w: (
            -(w.requested_slices - w.num_slices), w.admitted_at or 0.0, w.seq
        ))
        for w in shrunk:
            f = w.flavor
            if f in head_blocked:
                continue
            lull_start = self._quiet_since.get(f)
            if lull_start is None or now - lull_start < self.grow_delay_s:
                continue
            if now - (w.admitted_at or 0.0) < self.grow_delay_s:
                continue
            cps = w.chips_per_slice
            if cps <= 0:
                continue
            delta = min(w.requested_slices - w.num_slices, free[f] // cps)
            if delta < 1:
                continue
            to = w.num_slices + delta
            d = ResizeDecision(
                job_id=w.job_id, preemptor_id=None,
                from_slices=w.num_slices, to_slices=to,
            )
            w.preempting = True
            w.resize_to = to
            # fence the grown size: current chips free at exit, the delta
            # comes out of free now
            self._reserve(w.job_id, f, to * cps)
            free[f] -= delta * cps
            self._pending_preemptions.append(d)
            self._record_resize(d, w)
            logger.info(
                "grow %s (queue=%s) slices %d->%d (%d free chips of %s)",
                w.job_id, w.queue, d.from_slices, d.to_slices,
                free[f] + delta * cps, f,
            )

    def take_preemptions(self, owner: str | None = None) -> list[ResizeDecision]:
        """Drain the :class:`ResizeDecision`s selected since the last call —
        the backend SIGTERMs each victim; the resilience loop (checkpoint →
        RETRYING → resume, at ``to_slices`` when the decision is a resize)
        does the rest.

        ``owner`` filters by the victim workload's owner tag, leaving the
        rest pending: the training backend drains ``owner="train"`` (SIGTERM
        → retry supervisor), the serve tenant drains ``owner="serve"``
        (graceful replica drain — never a kill).  ``None`` keeps the legacy
        take-everything behavior for single-plane callers.
        """
        if owner is None:
            out, self._pending_preemptions = self._pending_preemptions, []
            return out
        out, keep = [], []
        for d in self._pending_preemptions:
            victim = self._workloads.get(d.job_id)
            victim_owner = victim.owner if victim is not None else "train"
            (out if victim_owner == owner else keep).append(d)
        self._pending_preemptions = keep
        return out

    # -- introspection (GangScheduler-compatible + the tenant view) ----------

    def pending(self) -> list[str]:
        """Pending job ids in *admission rank* order (priority, share, seq) —
        the order they would actually admit, which is what a queue-position
        display must show."""
        wds = {
            q: self.weighted_dominant_share(q)
            for q in {w.queue for w in self._workloads.values()}
        }
        return [
            w.job_id
            for w in sorted(
                (w for w in self._workloads.values() if not w.admitted),
                key=lambda w: self._rank_key(w, wds),
            )
        ]

    def position(self, job_id: str) -> int | None:
        pend = self.pending()
        return pend.index(job_id) + 1 if job_id in pend else None

    def is_admitted(self, job_id: str) -> bool:
        w = self._workloads.get(job_id)
        return bool(w and w.admitted)

    def workload(self, job_id: str) -> Workload | None:
        return self._workloads.get(job_id)

    def usage(self) -> dict[str, dict[str, int]]:
        """Per-flavor quota usage — the GangScheduler admin/debug shape."""
        out: dict[str, dict[str, int]] = {}
        for f in self._catalog.flavors:
            out[f.name] = {
                "used_chips": self._used_chips(f.name),
                "nominal_chips": self._catalog.quota_for(f.name),
                "pending": sum(
                    1 for w in self._workloads.values()
                    if not w.admitted and w.flavor == f.name
                ),
            }
        return out

    def snapshot(self) -> dict:
        """The tenant-facing view (``GET /admin/scheduler``, ``ftc-ctl
        queue``): per-queue usage, weighted share, borrowed chips, depth,
        and pending positions, plus cluster-wide counters."""
        pend_order = self.pending()
        queues: dict[str, dict] = {}
        # configured queues + queues with LIVE workloads only: ad-hoc queue
        # names are user-supplied, and emitting a /metrics series per name
        # ever seen would be an unbounded-cardinality leak
        names = set(self.queues.names()) | {
            w.queue for w in self._workloads.values()
        }
        for q in sorted(names):
            used = {
                f.name: self._queue_used(q, f.name)
                for f in self._catalog.flavors
                if self._queue_used(q, f.name)
            }
            borrowed = 0.0
            for f in self._catalog.flavors:
                u = self._queue_used(q, f.name)
                if u:
                    borrowed += max(0.0, u - self.nominal_share(q, f.name))
            pending_jobs = [
                {"job_id": j, "position": pend_order.index(j) + 1}
                for j in pend_order
                if self._workloads[j].queue == q
            ]
            queues[q] = {
                "weight": self.queues.weight(q),
                "running": sum(
                    1 for w in self._workloads.values()
                    if w.admitted and w.queue == q
                ),
                "depth": len(pending_jobs),
                "used_chips": used,
                "used_chips_total": sum(used.values()),
                "dominant_share": round(self.weighted_dominant_share(q), 4),
                "borrowed_chips": round(borrowed, 2),
                "preemptions": self.preemptions_by_queue.get(q, 0),
                "resizes": self.resizes_by_queue.get(q, 0),
                "pending": pending_jobs,
            }
        shrunk = {
            w.job_id: {
                "queue": w.queue,
                "num_slices": w.num_slices,
                "requested_slices": w.requested_slices,
            }
            for w in self._workloads.values() if w.shrunk
        }
        return {
            "policy": "fairshare",
            "resize_enabled": self.resize,
            "queues": queues,
            "flavors": self.usage(),
            "preemptions_total": self.preemptions_total,
            "resizes_total": self.resizes_total,
            "shrinks_total": self.shrinks_total,
            "grows_total": self.grows_total,
            "resize_history": list(self.resize_history),
            "shrunk_workloads": shrunk,
            "reservations": {
                p: list(v) for p, v in self._claims.items() if v
            },
            "resize_reservations": {
                j: {"flavor": f, "chips": c}
                for j, (f, c, _) in self._resize_reservations.items()
            },
        }
