"""Weighted dominant-resource fair sharing + the FairShareScheduler.

The drop-in replacement for the best-effort-FIFO ``GangScheduler``
(``controller/backends/scheduler.py``): same surface (``submit`` /
``try_admit`` / ``release`` / ``pending`` / ``position`` / ``usage``) so the
local backend and the admin routes keep working, plus the multi-tenant
machinery the ROADMAP's "heavy traffic" north star needs:

- **ordering**: pending workloads rank by priority class (desc), then their
  queue's *weighted dominant share* (asc — the DRF rule: serve the tenant
  farthest below its entitlement first), then submission sequence;
- **borrowing**: nominal shares divide each flavor's quota among the queues
  with *demand* on it — an idle queue is simply absent from the denominator,
  so its quota is lendable and reclaimable (via preemption) the moment it
  wakes up;
- **preemption**: a blocked higher-priority or under-share head picks
  victims (``preemption.select_victims``), the backend SIGTERMs them through
  the resilience loop, and the freed chips are *reserved* for the preemptor
  — no admission race;
- **backfill**: later-ranked workloads admit only into capacity provably in
  excess of the head's reservation (``backfill.backfill_capacity``).

Everything is synchronous and in-memory (trivially testable, like the seed
scheduler); the clock is injected so the simulator (``sched/sim.py``) can
drive it on virtual time.
"""

from __future__ import annotations

import itertools
import logging
import time
from typing import Iterable

from ..controller.devices import DeviceCatalog
from .backfill import backfill_capacity
from .preemption import select_victims
from .queues import (
    DEFAULT_PRIORITY,
    DEFAULT_QUEUE,
    QueueConfig,
    QueueSet,
    Workload,
    parse_priority,
)

logger = logging.getLogger(__name__)


def jain_index(values: Iterable[float]) -> float:
    """Jain's fairness index: 1.0 = perfectly even, 1/n = maximally unfair.

    Computed over *entitlement-normalised* allocations (caller divides each
    tenant's allocation by its weight first).
    """
    xs = [float(v) for v in values]
    if not xs:
        return 1.0
    total = sum(xs)
    sq = sum(x * x for x in xs)
    if sq == 0:
        return 1.0  # nobody got anything: degenerate but not "unfair"
    return (total * total) / (len(xs) * sq)


class FairShareScheduler:
    """Quota-based admission with weighted fair sharing and preemption."""

    def __init__(
        self,
        catalog: DeviceCatalog,
        queues: list[QueueConfig] | dict[str, float] | None = None,
        *,
        clock=time.monotonic,
    ):
        self._catalog = catalog
        self.queues = QueueSet(queues)
        self._clock = clock
        self._workloads: dict[str, Workload] = {}
        #: per-scheduler sequence (the satellite fix: the seed's module-global
        #: counter made queue positions depend on unrelated instances)
        self._seq = itertools.count()
        #: preemptor job_id -> victim job_ids still exiting on its behalf
        self._claims: dict[str, list[str]] = {}
        #: (victim, preemptor) pairs selected but not yet delivered to the backend
        self._pending_preemptions: list[tuple[str, str]] = []
        # observability
        self.preemptions_total = 0
        self.preemptions_by_queue: dict[str, int] = {}

    # -- submission / release ------------------------------------------------

    def submit(
        self,
        job_id: str,
        flavor_name: str,
        num_slices: int = 1,
        *,
        queue: str | None = None,
        priority: object | None = None,
    ) -> Workload:
        """Register a suspended workload under a tenant queue + priority."""
        if job_id in self._workloads:
            raise ValueError(f"workload {job_id!r} already queued")
        flavor = self._catalog.get_worker(flavor_name)
        need = flavor.total_chips * max(1, num_slices)
        quota = self._catalog.quota_for(flavor.name)
        if need > quota:
            # an inadmissible head would hold its flavor's reservation
            # forever (strict anti-starvation means nothing passes it) —
            # refuse at submit, where it surfaces as a 400, not a wedge
            raise ValueError(
                f"workload {job_id!r} needs {need} chips of {flavor.name!r} "
                f"but the quota is {quota}: it can never be admitted"
            )
        w = Workload(
            job_id=job_id,
            flavor=flavor.name,
            chips=need,
            queue=queue or DEFAULT_QUEUE,
            priority=parse_priority(
                priority if priority is not None else DEFAULT_PRIORITY
            ),
            seq=next(self._seq),
            submitted_at=self._clock(),
        )
        self._workloads[job_id] = w
        return w

    def release(self, job_id: str) -> None:
        """Free a workload's quota (finished, deleted, or preempted-and-exited)."""
        self._workloads.pop(job_id, None)
        self._claims.pop(job_id, None)  # it was a preemptor: drop its claim
        for victims in self._claims.values():
            if job_id in victims:
                victims.remove(job_id)

    # -- share math ----------------------------------------------------------

    def _used_chips(self, flavor: str) -> int:
        return sum(
            w.chips for w in self._workloads.values()
            if w.admitted and w.flavor == flavor
        )

    def _queue_used(self, queue: str, flavor: str) -> int:
        return sum(
            w.chips for w in self._workloads.values()
            if w.admitted and w.flavor == flavor and w.queue == queue
        )

    def _active_queues(self, flavor: str) -> set[str]:
        """Queues with demand (pending or admitted) on a flavor — the cohort
        sharing that flavor's quota.  Idle queues are absent, which is
        exactly what makes their share lendable."""
        return {
            w.queue for w in self._workloads.values() if w.flavor == flavor
        }

    def nominal_share(self, queue: str, flavor: str) -> float:
        """``quota * weight / sum(weights of the flavor's active cohort)``."""
        active = self._active_queues(flavor)
        if queue not in active:
            active = active | {queue}
        total_w = self.queues.total_weight(active)
        if total_w <= 0:
            return 0.0
        quota = self._catalog.quota_for(flavor)
        return quota * self.queues.weight(queue) / total_w

    def _over_share(self, flavor: str) -> dict[str, float]:
        """Per-queue chips above nominal share on a flavor (<=0 = within)."""
        return {
            q: self._queue_used(q, flavor) - self.nominal_share(q, flavor)
            for q in self._active_queues(flavor)
        }

    def weighted_dominant_share(self, queue: str) -> float:
        """DRF: the queue's largest per-flavor usage fraction, normalised by
        its weight.  Low = under-served, admitted first."""
        dom = 0.0
        for f in self._catalog.flavors:
            quota = self._catalog.quota_for(f.name)
            if quota <= 0:
                continue
            dom = max(dom, self._queue_used(queue, f.name) / quota)
        return dom / self.queues.weight(queue)

    # -- admission -----------------------------------------------------------

    def _rank_key(self, w: Workload, wds: dict[str, float]):
        return (-w.priority, wds[w.queue], w.seq)

    def _incoming_chips(self, preemptor: Workload) -> int:
        """Chips of in-flight victims SIGTERMed on this preemptor's behalf —
        still admitted (held) but guaranteed to free within the resilience
        loop's exit grace."""
        return sum(
            self._workloads[v].chips
            for v in self._claims.get(preemptor.job_id, ())
            if v in self._workloads and self._workloads[v].preempting
        )

    def try_admit(self) -> list[Workload]:
        """Admit every pending workload the fair-share policy allows.

        Returns the newly admitted workloads (the backend starts them).
        Preemption victims selected during the pass are queued for
        :meth:`take_preemptions` — the backend SIGTERMs them and their chips
        stay reserved for the blocked head until they exit.
        """
        now = self._clock()
        wds = {
            q: self.weighted_dominant_share(q)
            for q in {w.queue for w in self._workloads.values()}
        }
        pend = sorted(
            (w for w in self._workloads.values() if not w.admitted),
            key=lambda w: self._rank_key(w, wds),
        )
        free: dict[str, int] = {}
        admitted: list[Workload] = []
        head_blocked: dict[str, Workload] = {}
        for w in pend:
            f = w.flavor
            if f not in free:
                free[f] = self._catalog.quota_for(f) - self._used_chips(f)
            head = head_blocked.get(f)
            if head is not None:
                # behind a blocked head: only provably-excess chips admit,
                # and only chips that are PHYSICALLY free right now — the
                # capacity formula counts in-flight victim chips the head
                # will consume, which nobody else may start on
                cap = backfill_capacity(
                    free[f], self._incoming_chips(head), head.chips
                )
                if 0 < w.chips <= min(cap, free[f]):
                    self._admit(w, now, admitted, free)
                continue
            if w.chips <= free[f]:
                self._admit(w, now, admitted, free)
                continue
            head_blocked[f] = w
            self._maybe_preempt(w, free[f])
        return admitted

    def _admit(self, w: Workload, now: float, admitted: list[Workload],
               free: dict[str, int]) -> None:
        w.admitted = True
        w.admitted_at = now
        free[w.flavor] -= w.chips
        self._claims.pop(w.job_id, None)  # reservation consumed
        admitted.append(w)
        logger.info(
            "admitted %s (%d chips of %s, queue=%s prio=%d)",
            w.job_id, w.chips, w.flavor, w.queue, w.priority,
        )

    def _maybe_preempt(self, w: Workload, free_chips: int) -> None:
        """Select victims covering the head's shortfall (beyond chips already
        incoming from earlier preemptions) and reserve them for it."""
        shortfall = w.chips - free_chips - self._incoming_chips(w)
        if shortfall <= 0:
            return
        over = self._over_share(w.flavor)
        # RECLAIM-ONLY fairness trigger: a queue may fairness-preempt (same
        # priority, victim queue over share) only when it stays within its
        # own nominal share after admission.  A borrower preempting would
        # oscillate: post-swap the roles reverse and the displaced queue
        # preempts right back — reclaim-only makes the swap a fixed point.
        under = (
            self._queue_used(w.queue, w.flavor) + w.chips
            <= self.nominal_share(w.queue, w.flavor) + 1e-9
        )
        candidates = [
            c for c in self._workloads.values()
            if c.admitted and c.flavor == w.flavor
        ]
        victims = select_victims(
            w, candidates, shortfall,
            over_share=over, preemptor_under_share=under,
        )
        if not victims:
            return
        claim = self._claims.setdefault(w.job_id, [])
        for v in victims:
            v.preempting = True
            claim.append(v.job_id)
            self._pending_preemptions.append((v.job_id, w.job_id))
            self.preemptions_total += 1
            self.preemptions_by_queue[v.queue] = (
                self.preemptions_by_queue.get(v.queue, 0) + 1
            )
            logger.info(
                "preempting %s (queue=%s prio=%d, %d chips) for %s "
                "(queue=%s prio=%d)",
                v.job_id, v.queue, v.priority, v.chips,
                w.job_id, w.queue, w.priority,
            )

    def take_preemptions(self) -> list[tuple[str, str]]:
        """Drain the ``(victim, preemptor)`` pairs selected since the last
        call — the backend SIGTERMs each victim; the resilience loop
        (checkpoint → RETRYING → resume) does the rest."""
        out, self._pending_preemptions = self._pending_preemptions, []
        return out

    # -- introspection (GangScheduler-compatible + the tenant view) ----------

    def pending(self) -> list[str]:
        """Pending job ids in *admission rank* order (priority, share, seq) —
        the order they would actually admit, which is what a queue-position
        display must show."""
        wds = {
            q: self.weighted_dominant_share(q)
            for q in {w.queue for w in self._workloads.values()}
        }
        return [
            w.job_id
            for w in sorted(
                (w for w in self._workloads.values() if not w.admitted),
                key=lambda w: self._rank_key(w, wds),
            )
        ]

    def position(self, job_id: str) -> int | None:
        pend = self.pending()
        return pend.index(job_id) + 1 if job_id in pend else None

    def is_admitted(self, job_id: str) -> bool:
        w = self._workloads.get(job_id)
        return bool(w and w.admitted)

    def workload(self, job_id: str) -> Workload | None:
        return self._workloads.get(job_id)

    def usage(self) -> dict[str, dict[str, int]]:
        """Per-flavor quota usage — the GangScheduler admin/debug shape."""
        out: dict[str, dict[str, int]] = {}
        for f in self._catalog.flavors:
            out[f.name] = {
                "used_chips": self._used_chips(f.name),
                "nominal_chips": self._catalog.quota_for(f.name),
                "pending": sum(
                    1 for w in self._workloads.values()
                    if not w.admitted and w.flavor == f.name
                ),
            }
        return out

    def snapshot(self) -> dict:
        """The tenant-facing view (``GET /admin/scheduler``, ``ftc-ctl
        queue``): per-queue usage, weighted share, borrowed chips, depth,
        and pending positions, plus cluster-wide counters."""
        pend_order = self.pending()
        queues: dict[str, dict] = {}
        # configured queues + queues with LIVE workloads only: ad-hoc queue
        # names are user-supplied, and emitting a /metrics series per name
        # ever seen would be an unbounded-cardinality leak
        names = set(self.queues.names()) | {
            w.queue for w in self._workloads.values()
        }
        for q in sorted(names):
            used = {
                f.name: self._queue_used(q, f.name)
                for f in self._catalog.flavors
                if self._queue_used(q, f.name)
            }
            borrowed = 0.0
            for f in self._catalog.flavors:
                u = self._queue_used(q, f.name)
                if u:
                    borrowed += max(0.0, u - self.nominal_share(q, f.name))
            pending_jobs = [
                {"job_id": j, "position": pend_order.index(j) + 1}
                for j in pend_order
                if self._workloads[j].queue == q
            ]
            queues[q] = {
                "weight": self.queues.weight(q),
                "running": sum(
                    1 for w in self._workloads.values()
                    if w.admitted and w.queue == q
                ),
                "depth": len(pending_jobs),
                "used_chips": used,
                "used_chips_total": sum(used.values()),
                "dominant_share": round(self.weighted_dominant_share(q), 4),
                "borrowed_chips": round(borrowed, 2),
                "preemptions": self.preemptions_by_queue.get(q, 0),
                "pending": pending_jobs,
            }
        return {
            "policy": "fairshare",
            "queues": queues,
            "flavors": self.usage(),
            "preemptions_total": self.preemptions_total,
            "reservations": {
                p: list(v) for p, v in self._claims.items() if v
            },
        }
