"""Victim selection for checkpoint-aware preemption.

Preemption is *cheap* here because the resilience subsystem (PR 3) already
turned SIGTERM into "checkpoint, exit 143, classify as preemption, requeue
with backoff, resume from the committed checkpoint" — so evicting a workload
costs it at most ``checkpoint_every`` steps of progress, not the whole run.

Who may be preempted (both triggers from ISSUE 5):

- a **higher-priority** workload that cannot fit may evict strictly-lower-
  priority victims regardless of queue shares;
- an **under-share** workload may evict same-priority victims whose queue is
  *over* its nominal share — the fair-share reclaim.  The caller only sets
  ``preemptor_under_share`` when the preemptor's queue stays within its
  nominal share *after* admission (reclaim-only): a borrower preempting
  would oscillate — post-swap the roles reverse and the displaced queue
  preempts right back.

Victim order (most expendable first): lowest priority, then most-over-share
queue, then youngest (highest seq) — the youngest workload has the least
sunk progress beyond its last checkpoint, and evicting it perturbs the
cluster least.  Selection is greedy and all-or-nothing: if the eligible
victims cannot cover the shortfall, nobody is killed (a partial eviction
would not admit the preemptor and would only thrash the victims).
"""

from __future__ import annotations

from typing import Iterable

from .queues import Workload


def select_victims(
    preemptor: Workload,
    candidates: Iterable[Workload],
    shortfall: int,
    *,
    over_share: dict[str, float],
    preemptor_under_share: bool,
) -> list[Workload]:
    """Pick victims freeing ``shortfall`` chips for ``preemptor``.

    ``over_share`` maps queue name -> chips above its weighted nominal share
    (<= 0 means at-or-under share); ``preemptor_under_share`` is whether the
    preemptor's queue is below its share.  Returns ``[]`` when the eligible
    set cannot cover the shortfall.
    """
    if shortfall <= 0:
        return []
    eligible: list[Workload] = []
    for w in candidates:
        if w.preempting or not w.admitted or w.job_id == preemptor.job_id:
            continue
        if w.priority < preemptor.priority:
            eligible.append(w)
        elif (
            preemptor_under_share
            and w.priority == preemptor.priority
            and over_share.get(w.queue, 0.0) > 0
        ):
            eligible.append(w)
    # lowest priority, most-over-share queue, youngest first — deterministic
    eligible.sort(
        key=lambda w: (w.priority, -over_share.get(w.queue, 0.0), -w.seq)
    )
    victims: list[Workload] = []
    freed = 0
    for w in eligible:
        if freed >= shortfall:
            break
        victims.append(w)
        freed += w.chips
    if freed < shortfall:
        return []
    return victims
