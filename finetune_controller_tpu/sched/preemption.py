"""Victim selection for checkpoint-aware preemption — resize before evict.

Preemption is *cheap* here because the resilience subsystem (PR 3) already
turned SIGTERM into "checkpoint, exit 143, classify as preemption, requeue
with backoff, resume from the committed checkpoint" — so evicting a workload
costs it at most ``checkpoint_every`` steps of progress, not the whole run.
Since checkpoints are topology-portable (``train/elastic.py``), the planner
can do one better: **shrink** a multi-slice victim instead of evicting it —
the victim checkpoints, exits, and resumes at a reduced slice count within a
monitor tick, so capacity loss degrades its throughput instead of parking
its progress (VirtualFlow's decouple-model-from-hardware move, PAPERS.md).

Who may be preempted (both triggers from ISSUE 5):

- a **higher-priority** workload that cannot fit may evict strictly-lower-
  priority victims regardless of queue shares;
- an **under-share** workload may evict same-priority victims whose queue is
  *over* its nominal share — the fair-share reclaim.  The caller only sets
  ``preemptor_under_share`` when the preemptor's queue stays within its
  nominal share *after* admission (reclaim-only): a borrower preempting
  would oscillate — post-swap the roles reverse and the displaced queue
  preempts right back.

Plan order (ISSUE 7): **shrink-to-fair-share plans before full-eviction
plans.**  Pass 1 walks the eligible victims in expendability order (lowest
priority, most-over-share queue, youngest) and shrinks each multi-slice
victim — down to its queue's nominal share when the queue is borrowing,
deeper (to the 1-slice floor) when the shortfall demands it.  Shrinking past
the immediate shortfall when the victim's queue is over share is deliberate:
the freed headroom absorbs the *next* arrivals without re-paying a
checkpoint restart per arrival.  Pass 2 escalates to full evictions (again
in expendability order, upgrading planned shrinks) only for whatever
shortfall the shrinks could not cover.  Selection stays all-or-nothing: if
the eligible set cannot cover the shortfall, nobody is touched.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Iterable

from .queues import Workload


@dataclasses.dataclass(frozen=True)
class ResizeDecision:
    """One planned action on a running workload.

    ``to_slices == 0`` is a full eviction; ``to_slices < from_slices`` a
    shrink; ``to_slices > from_slices`` a grow (emitted by the scheduler's
    grow pass, not by this planner).  ``preemptor_id`` is None for grows.
    """

    job_id: str
    preemptor_id: str | None
    from_slices: int
    to_slices: int

    @property
    def kind(self) -> str:
        if self.to_slices == 0:
            return "evict"
        return "grow" if self.to_slices > self.from_slices else "shrink"

    @property
    def pair(self) -> tuple[str, str | None]:
        """(victim, preemptor) — the PR-5 shape, for logs and tests."""
        return (self.job_id, self.preemptor_id)


def _eligible(
    preemptor: Workload,
    candidates: Iterable[Workload],
    *,
    over_share: dict[str, float],
    preemptor_under_share: bool,
) -> list[Workload]:
    out: list[Workload] = []
    for w in candidates:
        if w.preempting or not w.admitted or w.job_id == preemptor.job_id:
            continue
        if w.priority < preemptor.priority:
            out.append(w)
        elif (
            preemptor_under_share
            and w.priority == preemptor.priority
            and over_share.get(w.queue, 0.0) > 0
        ):
            out.append(w)
    # lowest priority, most-over-share queue, youngest first — deterministic
    out.sort(key=lambda w: (w.priority, -over_share.get(w.queue, 0.0), -w.seq))
    return out


def plan_preemption(
    preemptor: Workload,
    candidates: Iterable[Workload],
    shortfall: int,
    *,
    over_share: dict[str, float],
    preemptor_under_share: bool,
    resize: bool = True,
) -> list[ResizeDecision]:
    """Plan shrinks (preferred) and evictions freeing ``shortfall`` chips.

    ``over_share`` maps queue name -> chips above its weighted nominal share
    (<= 0 means at-or-under) — it doubles as the shrink-to-fair-share
    target: shedding a queue's excess lands it at its share;
    ``resize=False`` degrades to the PR-5 evict-only planner.  Returns
    ``[]`` when the eligible set cannot cover the shortfall
    (all-or-nothing).
    """
    if shortfall <= 0:
        return []
    eligible = _eligible(
        preemptor, candidates,
        over_share=over_share, preemptor_under_share=preemptor_under_share,
    )
    plans: dict[str, ResizeDecision] = {}
    freed = 0
    #: chips each victim queue still holds above its share, decremented as
    #: shrinks are planned so one pass doesn't over-shrink a queue
    excess = {q: max(0.0, v) for q, v in over_share.items()}
    if resize:
        for w in eligible:
            # a victim already at (or below) its shrink floor can only be
            # fully evicted — min_slices == num_slices is how atomic gangs
            # (RLHF actor+learner) opt out of partial shrinks entirely
            floor = max(1, w.min_slices)
            if w.num_slices <= floor:
                continue
            cps = w.chips_per_slice
            if cps <= 0:
                continue
            # slices still needed for the preemptor's shortfall
            need = max(0, math.ceil((shortfall - freed) / cps))
            # fair-share deepening: shed the victim's share of its queue's
            # borrowed chips too, so the next arrival doesn't cost another
            # checkpoint restart
            fair = int(excess.get(w.queue, 0.0) // cps)
            take = min(w.num_slices - floor, max(need, fair))
            if take <= 0:
                continue
            plans[w.job_id] = ResizeDecision(
                job_id=w.job_id,
                preemptor_id=preemptor.job_id,
                from_slices=w.num_slices,
                to_slices=w.num_slices - take,
            )
            freed += take * cps
            excess[w.queue] = excess.get(w.queue, 0.0) - take * cps
    if freed < shortfall:
        # pass 2: escalate to full evictions in the same expendability
        # order — a planned shrink upgrades to an eviction (its remaining
        # slices free too)
        for w in eligible:
            if freed >= shortfall:
                break
            prior = plans.get(w.job_id)
            already = 0
            if prior is not None:
                already = (prior.from_slices - prior.to_slices) * w.chips_per_slice
            plans[w.job_id] = ResizeDecision(
                job_id=w.job_id,
                preemptor_id=preemptor.job_id,
                from_slices=w.num_slices,
                to_slices=0,
            )
            freed += w.chips - already
    if freed < shortfall:
        return []
    return list(plans.values())


def select_victims(
    preemptor: Workload,
    candidates: Iterable[Workload],
    shortfall: int,
    *,
    over_share: dict[str, float],
    preemptor_under_share: bool,
) -> list[Workload]:
    """PR-5 compatibility shim: the evict-only planner, returning the victim
    workloads themselves (tests and external callers)."""
    by_id = {w.job_id: w for w in candidates}
    plans = plan_preemption(
        preemptor, by_id.values(), shortfall,
        over_share=over_share, preemptor_under_share=preemptor_under_share,
        resize=False,
    )
    return [by_id[p.job_id] for p in plans]
