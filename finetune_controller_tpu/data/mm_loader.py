"""Multimodal data pipeline: image-bearing jsonl → (tokens, pixels) batches.

Row schema: the text loader's schemas (``data/loader.py`` — ``text``,
``prompt``/``completion``, token lists, chat ``messages``) plus an ``image``
field referencing the picture (path relative to the dataset file, absolute
path, data URI, or bare base64 — ``data/images.py``).

Layout differs from the text packer on purpose: one SAMPLE per row (no
cross-document packing — each image belongs to exactly one conversation),
text padded/truncated to a static ``seq_len``, pixels resized to the model's
``image_size``. The model prepends the projected patch tokens, so the static
shape per step is ``n_patches + seq_len`` — one compiled program for the
whole run. Reference dataset contract: ``app/models/base/finetuning.py:37-49``.
"""

from __future__ import annotations

import json
import logging
from collections import OrderedDict
from pathlib import Path
from typing import Iterator

import numpy as np

from .images import preprocess_image
from .loader import make_encoders, parse_text_row

logger = logging.getLogger(__name__)

#: decoded-pixel LRU cap: ~336²·3·4B ≈ 1.4 MB per image → ~700 MB ceiling
_PIXEL_CACHE_MAX = 512


class PixelCache:
    """Bounded LRU for decoded pixel arrays, keyed by row index.

    A real LRU, not clear-everything-at-capacity: steady-state epochs over a
    dataset just past the cap evict only the least-recently-used entries, so
    most rows keep their decode instead of the whole dataset re-decoding
    every epoch. ``capacity <= 0`` disables caching entirely (every access
    decodes — what the input-pipeline bench uses to measure raw decode cost).
    """

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._entries: OrderedDict[int, np.ndarray] = OrderedDict()

    def get(self, key: int) -> np.ndarray | None:
        px = self._entries.get(key)
        if px is not None:
            self._entries.move_to_end(key)
        return px

    def put(self, key: int, px: np.ndarray) -> None:
        if self.capacity <= 0:
            return
        if key in self._entries:
            self._entries.move_to_end(key)
        elif len(self._entries) >= self.capacity:
            self._entries.popitem(last=False)
        self._entries[key] = px

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: int) -> bool:
        return key in self._entries


def load_mm_rows(
    path: str, tokenizer_file: str | None = None
) -> list[tuple[list[int], list[int], str]]:
    """Parse rows to (tokens, loss_flags, image_ref). Every row must carry
    an ``image`` — a text-only row in a multimodal dataset is almost always
    a mistake (its loss would silently train the decoder on a black image)."""
    encode, encode_fragment = make_encoders(tokenizer_file)
    header_cache: dict[str, list[int]] = {}
    rows: list[tuple[list[int], list[int], str]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            image = row.get("image")
            if not image:
                raise ValueError(
                    "multimodal jsonl rows must carry an 'image' field "
                    f"(path / data URI / base64). Row: {line[:120]}"
                )
            toks, flags = parse_text_row(
                row, encode, encode_fragment, header_cache, line=line
            )
            rows.append((toks, flags, str(image)))
    if not rows:
        raise ValueError(f"no rows found in {path}")
    return rows


def mm_jsonl_batches(
    path: str,
    batch_size: int,
    seq_len: int,
    image_size: int,
    tokenizer_file: str | None = None,
    seed: int = 0,
    shard_index: int = 0,
    shard_count: int = 1,
    normalize: str = "clip",
    pixel_cache_size: int | None = None,
) -> Iterator[dict]:
    """Infinite shuffled sample batches:
    ``{"tokens": (B, S) i32, "loss_mask": (B, S) f32, "pixels": (B, H, W, 3)
    f32}``. Text longer than ``seq_len`` truncates (the image prefix rides
    inside the model, so S here is text-only); shorter pads with zeros whose
    loss_mask is 0. Multi-host: strided shard of the row stream."""
    rows = load_mm_rows(path, tokenizer_file)
    base_dir = Path(path).resolve().parent
    rng = np.random.default_rng(seed)
    pixel_cache = PixelCache(
        _PIXEL_CACHE_MAX if pixel_cache_size is None else pixel_cache_size
    )
    truncated = 0
    for i, (toks, flags, _) in enumerate(rows):
        if len(toks) > seq_len:
            truncated += 1
        if not any(flags):
            # no loss-counted tokens at ALL (empty completion, empty text):
            # the row would contribute ZERO gradient every epoch — the same
            # silent failure the chat-row empty-mask check in data/loader.py
            # catches, so refuse it here too
            raise ValueError(
                f"row {i}: no loss-counted tokens (empty completion?): the "
                "sample would train on nothing every epoch"
            )
        if not any(flags[:seq_len]):
            # truncation cut away every loss position (e.g. a prompt longer
            # than seq_len): the sample would contribute ZERO gradient every
            # epoch — fail loudly rather than silently training on nothing
            raise ValueError(
                f"row {i}: all loss-counted tokens fall past seq_len "
                f"{seq_len} (prompt length {flags.index(1)}); raise seq_len "
                "or shorten the prompt"
            )
    if truncated:
        logger.warning(
            "%d/%d multimodal rows exceed seq_len %d and will truncate",
            truncated, len(rows), seq_len,
        )

    def sample(idx: int) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        toks, flags, image = rows[idx]
        toks, flags = toks[:seq_len], flags[:seq_len]
        pad = seq_len - len(toks)
        t = np.asarray(toks + [0] * pad, np.int32)
        m = np.asarray(flags + [0] * pad, np.float32)
        px = pixel_cache.get(idx)
        if px is None:
            px = preprocess_image(
                image, image_size, base_dir=base_dir, normalize=normalize
            )
            pixel_cache.put(idx, px)
        return t, m, px

    n = len(rows)
    warned = False
    while True:
        order = rng.permutation(n)[shard_index::shard_count]
        if not len(order):
            if not warned:
                logger.warning(
                    "dataset has %d rows for %d shards; shard %d falls back "
                    "to the full row set (hosts will overlap)",
                    n, shard_count, shard_index,
                )
                warned = True
            order = rng.permutation(n)
        if len(order) < batch_size:
            order = np.resize(order, batch_size)
        for i in range(0, len(order) - batch_size + 1, batch_size):
            parts = [sample(int(j)) for j in order[i:i + batch_size]]
            yield {
                "tokens": np.stack([p[0] for p in parts]),
                "loss_mask": np.stack([p[1] for p in parts]),
                "pixels": np.stack([p[2] for p in parts]),
            }
