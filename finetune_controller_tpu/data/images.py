"""Host-side image decode/resize/normalize for multimodal datasets.

Dataset rows reference images as file paths (PNG/JPEG via PIL, ``.npy``
arrays) or base64 payloads (``data:`` URIs or bare base64 of the same
formats). Output is always ``(size, size, 3) float32`` ready for the ViT
patch conv — normalized with the CLIP mean/std by default, because the
shipped LLaVA preset imports a CLIP tower pretrained under exactly that
preprocessing (reference dataset contract:
``app/models/base/finetuning.py:37-49`` — the reference only declares
content types; the actual pipeline lived in user containers).
"""

from __future__ import annotations

import base64
import binascii
import io
from pathlib import Path

import numpy as np

#: OpenAI CLIP preprocessing constants (the tower the LLaVA preset imports)
CLIP_MEAN = np.asarray([0.48145466, 0.4578275, 0.40821073], np.float32)
CLIP_STD = np.asarray([0.26862954, 0.26130258, 0.27577711], np.float32)


def _from_bytes(raw: bytes) -> np.ndarray:
    """(H, W, 3) float32 in [0, 1] from PNG/JPEG/NPY bytes."""
    if raw[:6] == b"\x93NUMPY":
        arr = np.load(io.BytesIO(raw), allow_pickle=False)
        return _as_float01(arr)
    from PIL import Image

    img = Image.open(io.BytesIO(raw)).convert("RGB")
    return np.asarray(img, np.float32) / 255.0


def _as_float01(arr: np.ndarray) -> np.ndarray:
    arr = np.asarray(arr)
    if arr.ndim == 2:
        arr = np.repeat(arr[..., None], 3, axis=-1)
    if arr.ndim != 3 or arr.shape[-1] not in (1, 3):
        raise ValueError(f"image array must be (H, W, 3), got {arr.shape}")
    if arr.shape[-1] == 1:
        arr = np.repeat(arr, 3, axis=-1)
    arr = arr.astype(np.float32)
    if arr.max() > 1.0 + 1e-6:
        arr = arr / 255.0
    return arr


def decode_image(ref: str, *, base_dir: Path | str | None = None) -> np.ndarray:
    """Resolve an ``image`` field: data URI, bare base64, or a path
    (relative paths resolve against the dataset file's directory)."""
    if ref.startswith("data:"):
        _, _, payload = ref.partition(",")
        return _from_bytes(base64.b64decode(payload))
    p = Path(ref)
    if not p.is_absolute() and base_dir is not None:
        p = Path(base_dir) / p
    if p.exists():
        if p.suffix == ".npy":
            return _as_float01(np.load(p, allow_pickle=False))
        return _from_bytes(p.read_bytes())
    if Path(ref).suffix or "\\" in ref:
        # a file suffix ("." in the last component) or a backslash cannot
        # appear in base64 — this is a missing/typo'd PATH, so don't even try
        # the fallback ("/" alone is NOT a path signal: it is in the base64
        # alphabet, and bare payloads legitimately contain it)
        raise FileNotFoundError(
            f"image ref {ref[:80]!r} is neither an existing file nor "
            "decodable base64 (its file suffix rules the base64 fallback out)"
        )
    # not a file — try bare base64 before giving up. A typo'd extensionless
    # path can be VALID base64 of garbage bytes, which then dies inside the
    # image decoder (PIL's UnidentifiedImageError is an OSError) — catch that
    # too and raise the intended error instead of an uncaught decode failure.
    try:
        return _from_bytes(base64.b64decode(ref, validate=True))
    except (binascii.Error, ValueError, OSError):
        raise FileNotFoundError(
            f"image ref {ref[:80]!r} is neither an existing file nor "
            "decodable base64"
        ) from None


def resize_image(img: np.ndarray, size: int) -> np.ndarray:
    """Bilinear resize to (size, size, 3) (PIL when available, else a
    nearest-neighbor numpy fallback — tests/containers without PIL)."""
    if img.shape[0] == size and img.shape[1] == size:
        return img
    try:
        from PIL import Image

        pil = Image.fromarray((np.clip(img, 0, 1) * 255).astype(np.uint8))
        return np.asarray(
            pil.resize((size, size), Image.BILINEAR), np.float32
        ) / 255.0
    except ImportError:
        ys = (np.arange(size) * img.shape[0] / size).astype(int)
        xs = (np.arange(size) * img.shape[1] / size).astype(int)
        return img[ys][:, xs]


def preprocess_image(
    ref: str, size: int, *,
    base_dir: Path | str | None = None,
    normalize: str = "clip",
) -> np.ndarray:
    """ref → (size, size, 3) float32, CLIP-normalized by default."""
    img = resize_image(decode_image(ref, base_dir=base_dir), size)
    if normalize == "clip":
        return (img - CLIP_MEAN) / CLIP_STD
    if normalize == "none":
        return img
    raise ValueError(f"unknown image normalize mode {normalize!r}")
