"""Host-side data pipeline: tokenized-JSONL → packed fixed-length batches.

Datasets are files in the object store (the control plane downloads them to a
local path before launch, mirroring the reference's init-container `s3 cp`
seam — reference ``app/jobs/kubeflow/PyTorchJobDeployer.py:70-91``).

Supported formats:
  * ``.jsonl`` with ``{"tokens": [...]}`` rows (pre-tokenized), or
    ``{"text": "..."}`` rows tokenized with a byte-level fallback tokenizer
    (or a HuggingFace ``tokenizers`` file when provided);
  * ``.jsonl`` SFT rows — ``{"prompt": ..., "completion": ...}`` (text) or
    ``{"prompt_tokens": [...], "completion_tokens": [...]}`` — where the loss
    counts ONLY completion tokens (the mask rides through packing);
  * ``.jsonl`` chat rows — ``{"messages": [{"role", "content"}, ...]}``
    rendered with a fixed template; loss counts assistant content only
    (see :func:`_render_chat`);
  * ``.npy`` — a flat int32 token stream.

Packing: documents are concatenated into a flat stream with per-document
``segment_ids`` so attention never crosses document boundaries, then cut into
(batch, seq_len) blocks — the TPU-friendly static-shape layout.

Multi-host: each process takes a strided shard of the block stream
(``shard_index``/``shard_count``), so no two hosts train on the same block.
"""

from __future__ import annotations

import json
import logging
from typing import Iterator, Sequence

import numpy as np

logger = logging.getLogger(__name__)


def _byte_tokenize(text: str) -> list[int]:
    return list(text.encode("utf-8"))


def _render_chat(messages, encode_fragment, header_cache: dict) -> "Document":
    """Render a chat row (``{"messages": [{"role", "content"}, ...]}``) with
    a fixed, deterministic template::

        <|role|>\\ncontent\\n

    Loss counts ONLY assistant-message content (+ its terminating newline);
    role headers and user/system turns are masked — every assistant turn in
    a multi-turn conversation contributes. Custom chat templates belong in
    preprocessing: render them to ``prompt``/``completion`` (or token) rows.

    ``encode_fragment`` must NOT add special tokens — fragments are
    concatenated, and a post-processor's per-call BOS/EOS would litter the
    stream mid-sequence. ``header_cache`` memoizes the handful of role
    headers across the whole file.
    """
    if not isinstance(messages, list) or not all(
        isinstance(m, dict) for m in messages
    ):
        raise ValueError(
            "'messages' must be a list of {'role', 'content'} objects"
        )
    toks: list[int] = []
    flags: list[int] = []
    for msg in messages:
        role = str(msg.get("role", "user"))
        header = header_cache.get(role)
        if header is None:
            header = header_cache[role] = encode_fragment(f"<|{role}|>\n")
        body = encode_fragment(str(msg.get("content", "")) + "\n")
        toks += header + body
        flags += [0] * len(header)
        flags += [1] * len(body) if role == "assistant" else [0] * len(body)
    return toks, flags


#: a document is (tokens, loss_flags) — flags mark the positions whose
#: prediction counts (1 everywhere for plain LM rows, completion-only for SFT)
Document = tuple[list[int], list[int]]


def make_encoders(tokenizer_file: str | None):
    """(encode, encode_fragment) pair: HF ``tokenizers`` file when given,
    byte-level fallback otherwise — shared by the text and multimodal
    loaders so tokenizer-handling fixes land in both."""
    tokenizer = None
    if tokenizer_file:
        from tokenizers import Tokenizer

        tokenizer = Tokenizer.from_file(tokenizer_file)

    def encode(text: str) -> list[int]:
        if tokenizer is not None:
            return tokenizer.encode(text).ids
        return _byte_tokenize(text)

    def encode_fragment(text: str) -> list[int]:
        # fragments get concatenated — a post-processor's BOS/EOS per call
        # would land mid-sequence
        if tokenizer is not None:
            return tokenizer.encode(text, add_special_tokens=False).ids
        return _byte_tokenize(text)

    return encode, encode_fragment


def load_token_documents(path: str, tokenizer_file: str | None = None) -> list[Document]:
    if path.endswith(".npy"):
        toks = np.load(path).astype(np.int32).tolist()
        return [(toks, [1] * len(toks))]
    encode, encode_fragment = make_encoders(tokenizer_file)
    header_cache: dict[str, list[int]] = {}
    docs: list[Document] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            docs.append(parse_text_row(
                json.loads(line), encode, encode_fragment, header_cache,
                line=line,
            ))
    if not docs:
        raise ValueError(f"no documents found in {path}")
    return docs


def parse_text_row(
    row: dict, encode, encode_fragment, header_cache: dict, line: str = ""
) -> Document:
    """One jsonl row → (tokens, loss_flags). Shared by the text loader and
    the multimodal loader (``data/mm_loader.py``), which reads the same text
    schemas next to an ``image`` field."""
    if "tokens" in row:
        toks = [int(t) for t in row["tokens"]]
        return toks, [1] * len(toks)
    if "text" in row:
        toks = encode(row["text"])
        return toks, [1] * len(toks)
    if "prompt_tokens" in row and "completion_tokens" in row:
        p = [int(t) for t in row["prompt_tokens"]]
        c = [int(t) for t in row["completion_tokens"]]
        return p + c, [0] * len(p) + [1] * len(c)
    if "prompt" in row and "completion" in row:
        p, c = encode(row["prompt"]), encode(row["completion"])
        return p + c, [0] * len(p) + [1] * len(c)
    if "messages" in row:
        doc = _render_chat(row["messages"], encode_fragment, header_cache)
        if not any(doc[1]):
            # an all-masked chat doc trains on NOTHING — the classic
            # wrong-role footgun ({"role": "model"}), caught per row
            # so a mixed corpus can't hide it
            raise ValueError(
                "chat row produced no assistant-content tokens (the "
                "loss mask is empty): the template counts loss only "
                f"for role == 'assistant'. Row: {line[:120]}"
            )
        return doc
    raise ValueError(
        "jsonl rows must have 'tokens', 'text', "
        "'prompt'/'completion', or 'messages' fields"
    )


def pack_documents(
    docs: Sequence[Document | Sequence[int]], seq_len: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Concatenate docs → (n_blocks, seq_len) token, segment-id, and
    loss-flag arrays. Accepts bare token lists (all positions count) or
    (tokens, flags) documents (SFT completion masking)."""
    stream: list[int] = []
    segs: list[int] = []
    flags: list[int] = []
    for i, d in enumerate(docs):
        if isinstance(d, tuple):
            toks, f = d
        else:
            toks, f = list(d), [1] * len(d)
        if len(f) != len(toks):
            raise ValueError(f"doc {i}: {len(f)} flags for {len(toks)} tokens")
        stream.extend(toks)
        flags.extend(f)
        segs.extend([i + 1] * len(toks))
    n_blocks = max(len(stream) // seq_len, 1)
    if len(stream) < seq_len:  # pad tiny datasets up to one block
        pad = seq_len - len(stream)
        stream = list(stream) + [0] * pad
        segs = list(segs) + [0] * pad
        flags = list(flags) + [0] * pad
    cut = n_blocks * seq_len
    tokens = np.asarray(stream[:cut], np.int32).reshape(n_blocks, seq_len)
    segments = np.asarray(segs[:cut], np.int32).reshape(n_blocks, seq_len)
    loss_flags = np.asarray(flags[:cut], np.float32).reshape(n_blocks, seq_len)
    return tokens, segments, loss_flags


def batches_from_tokens(
    tokens: np.ndarray,
    segments: np.ndarray | None,
    batch_size: int,
    seed: int = 0,
    shard_index: int = 0,
    shard_count: int = 1,
    loss_flags: np.ndarray | None = None,
) -> Iterator[dict]:
    """Infinite shuffled batch iterator over packed blocks."""
    n = tokens.shape[0]
    rng = np.random.default_rng(seed)

    def make_batch(idx: np.ndarray) -> dict:
        if segments is None:
            mask = (
                loss_flags[idx].astype(np.float32)
                if loss_flags is not None
                else np.ones_like(tokens[idx], np.float32)
            )
            return {"tokens": tokens[idx], "loss_mask": mask}
        seg = segments[idx]
        # Mask padding AND each document's first in-block token: predicting
        # doc i+1's first token happens from inside doc i, which
        # segment-masked attention cannot see — irreducible noise.  loss_mask
        # is indexed by *target* position (losses.next_token_loss), so the
        # zero goes on the boundary target itself.
        mask = (seg > 0).astype(np.float32)
        mask[:, 1:] *= (seg[:, 1:] == seg[:, :-1]).astype(np.float32)
        if loss_flags is not None:
            # SFT: only completion targets count
            mask *= loss_flags[idx].astype(np.float32)
        return {"tokens": tokens[idx], "loss_mask": mask, "segment_ids": seg}

    warned = False
    while True:
        order = rng.permutation(n)
        order = order[shard_index::shard_count]
        if not len(order):
            # Fewer blocks than hosts — unavoidable overlap; warn once.
            if not warned:
                logger.warning(
                    "dataset has %d blocks for %d shards; shard %d falls back "
                    "to the full block set (hosts will overlap)",
                    n, shard_count, shard_index,
                )
                warned = True
            order = rng.permutation(n)
        for i in range(0, len(order) - batch_size + 1, batch_size):
            yield make_batch(order[i : i + batch_size])
        if len(order) < batch_size:
            # Shard smaller than one batch: tile this shard's own blocks.
            yield make_batch(np.resize(order, batch_size))


def jsonl_token_batches(
    path: str,
    batch_size: int,
    seq_len: int,
    tokenizer_file: str | None = None,
    seed: int = 0,
    shard_index: int = 0,
    shard_count: int = 1,
) -> Iterator[dict]:
    tokens = segments = loss_flags = None
    if tokenizer_file is None and path.endswith(".jsonl"):
        # native C++ parse+tokenize+pack hot path (data/native_loader.py):
        # covers every byte-level row schema incl. SFT prompt/completion and
        # chat messages, with loss flags; byte-parity with the Python path,
        # gate with FTC_NATIVE=0. Anything it can't own (malformed rows,
        # non-string chat content it would have to stringify) raises and the
        # Python loader decides — including raising the user-facing error.
        from .native_loader import pack_jsonl_native

        try:
            packed = pack_jsonl_native(path, seq_len)
        except ValueError:
            packed = None  # odd schema: the Python loader decides
        if packed is not None:
            tokens, segments, loss_flags = packed
            logger.debug("native packer produced %d blocks", tokens.shape[0])
    if tokens is None:
        docs = load_token_documents(path, tokenizer_file)
        tokens, segments, loss_flags = pack_documents(docs, seq_len)
    return batches_from_tokens(
        tokens, segments, batch_size, seed=seed,
        shard_index=shard_index, shard_count=shard_count,
        loss_flags=loss_flags,
    )


