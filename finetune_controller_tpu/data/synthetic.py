"""Synthetic LM datasets — the egress-free CI and benchmark workload.

``task="increment"`` generates sequences where token[t+1] = (token[t] + 1)
mod vocab: a model that learns at all drives loss → 0 quickly, which gives
tests a crisp "training works" signal (the reference had no equivalent — its
smoke workload was a containerised MNIST it never ran in CI, SURVEY.md §4).
"""

from __future__ import annotations

from typing import Iterator

import numpy as np


def synthetic_batches(
    batch_size: int,
    seq_len: int,
    vocab_size: int,
    task: str = "increment",
    seed: int = 0,
    image_size: int = 0,
) -> Iterator[dict]:
    """``image_size > 0`` adds a ``pixels`` field (multimodal smoke data):
    the image's mean brightness picks the caption's start token, so a model
    that wires vision → text at all can beat the text-only loss floor."""
    rng = np.random.default_rng(seed)
    while True:
        if task == "increment":
            start = rng.integers(0, vocab_size, (batch_size, 1))
            offsets = np.arange(seq_len)[None, :]
            tokens = (start + offsets) % vocab_size
        elif task == "random":
            tokens = rng.integers(0, vocab_size, (batch_size, seq_len))
        else:
            raise ValueError(f"unknown synthetic task {task!r}")
        batch = {
            "tokens": tokens.astype(np.int32),
            "loss_mask": np.ones((batch_size, seq_len), np.float32),
        }
        if image_size:
            brightness = (tokens[:, 0].astype(np.float32) / vocab_size)[:, None, None, None]
            pixels = brightness + 0.1 * rng.standard_normal(
                (batch_size, image_size, image_size, 3)
            )
            batch["pixels"] = pixels.astype(np.float32)
        yield batch
