"""Synthetic LM datasets — the egress-free CI and benchmark workload.

``task="increment"`` generates sequences where token[t+1] = (token[t] + 1)
mod vocab: a model that learns at all drives loss → 0 quickly, which gives
tests a crisp "training works" signal (the reference had no equivalent — its
smoke workload was a containerised MNIST it never ran in CI, SURVEY.md §4).
"""

from __future__ import annotations

from typing import Iterator

import numpy as np


#: distinct brightness levels in the multimodal task — spaced far enough
#: apart that the per-pixel noise averages out well below the level gap
BRIGHTNESS_LEVELS = 16


def synthetic_batches(
    batch_size: int,
    seq_len: int,
    vocab_size: int,
    task: str = "increment",
    seed: int = 0,
    image_size: int = 0,
) -> Iterator[dict]:
    """Synthetic tasks:

    * ``increment`` — token[t+1] = token[t]+1 mod vocab; text-only.
    * ``random`` — iid tokens (loss should NOT beat log(vocab)).
    * ``brightness`` — multimodal wiring probe (requires ``image_size``):
      token 0 is a fixed BOS, token 1 encodes the image's mean brightness
      (one of :data:`BRIGHTNESS_LEVELS` levels), tokens 2+ increment from it.
      The brightness token is predictable ONLY through the vision path —
      ``loss_mask`` counts just that target, so the loss starts at
      log(vocab) and can only fall if pixels reach the decoder.
    """
    rng = np.random.default_rng(seed)
    if task == "brightness":
        if not image_size:
            raise ValueError("task='brightness' requires image_size > 0")
        if vocab_size < BRIGHTNESS_LEVELS:
            # vocab // levels == 0 would collapse every level onto the same
            # token — the probe would pass with no vision path at all
            raise ValueError(
                f"task='brightness' requires vocab_size >= {BRIGHTNESS_LEVELS}"
            )
    while True:
        if task == "increment":
            start = rng.integers(0, vocab_size, (batch_size, 1))
            offsets = np.arange(seq_len)[None, :]
            tokens = (start + offsets) % vocab_size
            loss_mask = np.ones((batch_size, seq_len), np.float32)
        elif task == "random":
            tokens = rng.integers(0, vocab_size, (batch_size, seq_len))
            loss_mask = np.ones((batch_size, seq_len), np.float32)
        elif task == "brightness":
            level = rng.integers(0, BRIGHTNESS_LEVELS, (batch_size, 1))
            start = level * (vocab_size // BRIGHTNESS_LEVELS)
            offsets = np.arange(seq_len)[None, :]
            tokens = (start + offsets) % vocab_size
            tokens[:, 0] = 0  # BOS carries no information about the level
            loss_mask = np.zeros((batch_size, seq_len), np.float32)
            loss_mask[:, 1] = 1.0  # only the brightness-determined target counts
        else:
            raise ValueError(f"unknown synthetic task {task!r}")
        batch = {
            "tokens": tokens.astype(np.int32),
            "loss_mask": loss_mask,
        }
        if image_size:
            if task == "brightness":
                brightness = level.astype(np.float32) / BRIGHTNESS_LEVELS
            else:
                brightness = tokens[:, :1].astype(np.float32) / vocab_size
            pixels = brightness[:, :, None, None] + 0.05 * rng.standard_normal(
                (batch_size, image_size, image_size, 3)
            )
            batch["pixels"] = pixels.astype(np.float32)
        yield batch
