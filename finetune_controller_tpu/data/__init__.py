from .synthetic import synthetic_batches
from .loader import jsonl_token_batches, batches_from_tokens
from .prefetch import PrefetchIterator, prefetch_batches

__all__ = [
    "synthetic_batches",
    "jsonl_token_batches",
    "batches_from_tokens",
    "PrefetchIterator",
    "prefetch_batches",
]
