"""ctypes bindings for the native JSONL packer (``native/packer.cc``).

The parse+tokenize+pack hot path runs in C++ (~order-of-magnitude over the
Python loop on large corpora); shuffling and batch assembly stay in
``data.loader`` (numpy, already fast). Covers every byte-level row schema —
plain LM, SFT prompt/completion (text or tokens), and chat messages, with
loss flags. Output parity with ``loader.load_token_documents`` +
``loader.pack_documents`` is enforced by tests; rows needing a real
tokenizer file keep using the Python path.
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading

import numpy as np

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_lib_failed = False


def _load() -> ctypes.CDLL | None:
    global _lib, _lib_failed
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        if os.environ.get("FTC_NATIVE", "1").lower() in ("0", "false", "no"):
            _lib_failed = True
            return None
        from ..native.build import ensure_built

        path = ensure_built()
        if path is None:
            _lib_failed = True
            return None
        lib = ctypes.CDLL(str(path))
        lib.ftc_pack_file.restype = ctypes.c_int64
        lib.ftc_pack_file.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_void_p)
        ]
        lib.ftc_copy_packed.restype = ctypes.c_int32
        lib.ftc_copy_packed.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.ftc_last_error.restype = ctypes.c_char_p
        lib.ftc_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def pack_jsonl_native(
    path: str, seq_len: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray] | None:
    """Native parse+tokenize+pack; None when the library is unavailable.

    Returns (tokens, segments, loss_flags). Raises ValueError on malformed
    datasets (same contract as the Python loader).
    """
    lib = _load()
    if lib is None:
        return None
    handle = ctypes.c_void_p()
    n_blocks = lib.ftc_pack_file(path.encode(), seq_len, ctypes.byref(handle))
    if n_blocks < 0:
        err = lib.ftc_last_error().decode(errors="replace")
        raise ValueError(f"native packer failed for {path}: {err}")
    try:
        tokens = np.empty((n_blocks, seq_len), np.int32)
        segments = np.empty((n_blocks, seq_len), np.int32)
        flags = np.empty((n_blocks, seq_len), np.int32)
        rc = lib.ftc_copy_packed(
            handle,
            tokens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            segments.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            flags.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        if rc != 0:
            raise ValueError("native packer copy failed")
        return tokens, segments, flags.astype(np.float32)
    finally:
        lib.ftc_free(handle)
