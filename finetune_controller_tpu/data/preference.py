"""Preference-pair datasets: (prompt, chosen, rejected) → padded DPO batches.

The DPO batch contract (``prefs/dpo_trainer.py``) is four (B, S) leaves::

    {"chosen_tokens", "chosen_mask", "rejected_tokens", "rejected_mask"}

where chosen/rejected share the SAME prompt prefix and each mask is 1 only
over completion *targets* (prompt and padding are 0 — the convention
``train/losses.py`` uses, so masked-logprob parity holds).  Batches are plain
dicts of numpy arrays, so they ride the existing background-prefetch path
(``data/prefetch.py``) unchanged — prefetch on/off is bit-identical (tested).

Two sources:

* :func:`synthetic_preference_batches` — the egress-free CI/benchmark
  workload: prompts are increment sequences (``data/synthetic.py``'s task),
  the chosen completion continues the increment and the rejected one breaks
  it.  Deterministic per seed; the eval stream draws from a disjoint seed
  region exactly like the SFT synthetic loader.
* :func:`preference_jsonl_batches` — real datasets: jsonl rows with
  ``{"prompt", "chosen", "rejected"}`` text (tokenized with the shared
  encoders) or pre-tokenized ``{"prompt_tokens", "chosen_tokens",
  "rejected_tokens"}`` lists.
"""

from __future__ import annotations

import json
import logging
from typing import Iterator

import numpy as np

from .loader import make_encoders

logger = logging.getLogger(__name__)


def _pad_pair(
    prompt: list[int], completion: list[int], seq_len: int
) -> tuple[np.ndarray, np.ndarray]:
    """(tokens, mask) both (seq_len,) — prompt+completion right-padded with 0;
    mask counts completion targets only.  Over-long rows keep the FULL prompt
    and truncate the completion (a truncated prompt would make chosen and
    rejected diverge before the completion even starts)."""
    if len(prompt) >= seq_len:
        prompt = prompt[: seq_len - 1]  # leave >= 1 completion slot
    completion = completion[: seq_len - len(prompt)]
    tokens = np.zeros((seq_len,), np.int32)
    mask = np.zeros((seq_len,), np.float32)
    n = len(prompt) + len(completion)
    tokens[: len(prompt)] = prompt
    tokens[len(prompt): n] = completion
    mask[len(prompt): n] = 1.0
    return tokens, mask


def _stack_pairs(
    pairs: list[tuple[list[int], list[int], list[int]]], seq_len: int
) -> dict:
    """[(prompt, chosen, rejected)] → the 4-leaf DPO batch dict."""
    ct, cm, rt, rm = [], [], [], []
    for prompt, chosen, rejected in pairs:
        t, m = _pad_pair(prompt, chosen, seq_len)
        ct.append(t); cm.append(m)
        t, m = _pad_pair(prompt, rejected, seq_len)
        rt.append(t); rm.append(m)
    return {
        "chosen_tokens": np.stack(ct),
        "chosen_mask": np.stack(cm),
        "rejected_tokens": np.stack(rt),
        "rejected_mask": np.stack(rm),
    }


# ---------------------------------------------------------------------------
# Synthetic pairs (the seeded CI / benchmark workload)
# ---------------------------------------------------------------------------


def make_increment_pair(
    rng: np.random.Generator,
    seq_len: int,
    vocab_size: int,
    prompt_fraction: float = 0.5,
) -> tuple[list[int], list[int], list[int]]:
    """One (prompt, chosen, rejected) increment pair.

    Prompt: ``start, start+1, ...`` — chosen continues the +1 stride, the
    rejected completion walks a corrupted stride (uniformly 2..7, never 1) so
    it is *systematically* wrong, not just noisy: a policy that learns the
    increment rule ranks held-out pairs correctly, which is what the
    ``dpo_accuracy`` eval gate measures.
    """
    prompt_len = max(2, int(seq_len * prompt_fraction))
    completion_len = seq_len - prompt_len
    start = int(rng.integers(0, vocab_size))
    prompt = [(start + i) % vocab_size for i in range(prompt_len)]
    nxt = prompt[-1]
    chosen = [(nxt + 1 + i) % vocab_size for i in range(completion_len)]
    stride = int(rng.integers(2, 8))
    rejected = [(nxt + stride * (i + 1)) % vocab_size
                for i in range(completion_len)]
    return prompt, chosen, rejected


def synthetic_preference_batches(
    batch_size: int,
    seq_len: int,
    vocab_size: int,
    seed: int = 0,
    prompt_fraction: float = 0.5,
) -> Iterator[dict]:
    """Infinite deterministic stream of increment preference batches.

    Same seed → bit-identical pair stream (tested round-trip); callers hold
    out an eval split by offsetting the seed, exactly like
    ``train/cli.py``'s synthetic SFT streams.
    """
    if vocab_size < 16:
        raise ValueError("preference task needs vocab_size >= 16")
    rng = np.random.default_rng(seed)
    while True:
        pairs = [
            make_increment_pair(rng, seq_len, vocab_size, prompt_fraction)
            for _ in range(batch_size)
        ]
        yield _stack_pairs(pairs, seq_len)


# ---------------------------------------------------------------------------
# JSONL pairs (real datasets)
# ---------------------------------------------------------------------------


def load_preference_rows(
    path: str, tokenizer_file: str | None = None
) -> list[tuple[list[int], list[int], list[int]]]:
    """Parse a preference jsonl into (prompt, chosen, rejected) token rows."""
    encode, _ = make_encoders(tokenizer_file)
    rows: list[tuple[list[int], list[int], list[int]]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            if {"prompt_tokens", "chosen_tokens", "rejected_tokens"} <= set(row):
                rows.append((
                    [int(t) for t in row["prompt_tokens"]],
                    [int(t) for t in row["chosen_tokens"]],
                    [int(t) for t in row["rejected_tokens"]],
                ))
                continue
            if {"prompt", "chosen", "rejected"} <= set(row):
                rows.append((
                    encode(row["prompt"]),
                    encode(row["chosen"]),
                    encode(row["rejected"]),
                ))
                continue
            raise ValueError(
                "preference jsonl rows need 'prompt'/'chosen'/'rejected' "
                "(text) or 'prompt_tokens'/'chosen_tokens'/'rejected_tokens' "
                f"fields; got keys {sorted(row)}"
            )
    if not rows:
        raise ValueError(f"no preference pairs found in {path}")
    for i, (p, c, r) in enumerate(rows):
        if not p or not c or not r:
            raise ValueError(
                f"preference row {i}: prompt/chosen/rejected must all be "
                "non-empty"
            )
    return rows


def preference_jsonl_batches(
    path: str,
    batch_size: int,
    seq_len: int,
    tokenizer_file: str | None = None,
    seed: int = 0,
    shard_index: int = 0,
    shard_count: int = 1,
) -> Iterator[dict]:
    """Infinite shuffled batch stream over a preference jsonl.

    Multi-host: each process takes a strided shard of the shuffled row order
    (the ``data/loader.py`` convention) so no two hosts train on the same
    pair in an epoch.
    """
    rows = load_preference_rows(path, tokenizer_file)
    rng = np.random.default_rng(seed)
    n = len(rows)
    warned = False
    while True:
        order = rng.permutation(n)[shard_index::shard_count]
        if not len(order):
            if not warned:
                logger.warning(
                    "preference dataset has %d pairs for %d shards; shard %d "
                    "falls back to the full set (hosts will overlap)",
                    n, shard_count, shard_index,
                )
                warned = True
            order = rng.permutation(n)
        for i in range(0, len(order) - batch_size + 1, batch_size):
            yield _stack_pairs(
                [rows[j] for j in order[i: i + batch_size]], seq_len
            )
        if len(order) < batch_size:
            # shard smaller than one batch: tile its own rows
            idx = np.resize(order, batch_size)
            yield _stack_pairs([rows[j] for j in idx], seq_len)
