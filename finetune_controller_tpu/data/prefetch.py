"""Overlapped host input pipeline: background prefetch + device transfer.

Every loader in this package (``loader``, ``mm_loader``, ``native_loader``
via ``loader``, ``synthetic``) yields host-side numpy batches from a plain
Python iterator — built synchronously on the training thread, so the device
idles for the whole host build (worst on the multimodal loader, whose
PIL decode/resize runs per batch).  :class:`PrefetchIterator` wraps any of
them with the Podracer-style overlap (arXiv:2104.06272): a bounded background
producer builds batch N+1..N+k while the device runs step N, and an optional
transfer stage ``jax.device_put``s the next batch with the training-step
sharding so the host→HBM copy overlaps compute too (``device_put`` dispatches
asynchronously; with queue depth ≥ 1 this is classic double buffering).

Contract:
  * **order-preserving** — one producer thread + a FIFO queue; batch k of the
    wrapped iterator is the k-th batch out, so checkpoint-resume
    fast-forwarding stays deterministic (tested);
  * **bounded** — at most ``depth`` finished batches wait in the queue (plus
    one being built), so host memory stays O(depth) batches;
  * **crash-transparent** — a producer exception is re-raised on the
    consumer thread as the ORIGINAL exception (no hang, no wrapper type);
  * **clean shutdown** — :meth:`close` (also on context-manager exit) stops
    the producer even when it is blocked on a full queue; the thread is a
    daemon so an unclosed iterator never wedges interpreter exit;
  * **observable** — per-batch host-build / transfer seconds (producer side)
    and consumer wait seconds are recorded; :meth:`pop_stats` drains
    windowed aggregates for metrics/bench reporting.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator

__all__ = ["PrefetchIterator", "prefetch_batches"]

#: queue sentinel: the wrapped iterator is exhausted
_DONE = object()


class _Failure:
    """Producer-side exception, carried through the queue to the consumer."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class PrefetchIterator:
    """Wrap ``batches`` with a background producer thread (depth-bounded
    queue) and an optional ``transfer`` stage applied on the producer thread
    (e.g. the trainer's ``_shard_batch`` — an async ``device_put`` with the
    step's shardings, so the copy overlaps the running step)."""

    def __init__(
        self,
        batches: Iterable[Any],
        depth: int = 2,
        transfer: Callable[[Any], Any] | None = None,
        name: str = "input-prefetch",
    ):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._inner = iter(batches)
        self._queue: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._transfer = transfer
        self._exhausted = False
        # consumer-visible timing (what the training step actually waited)
        self.last_wait_s = 0.0
        # producer-side timing for the batch most recently handed out
        self.last_build_s = 0.0
        self.last_transfer_s = 0.0
        self._agg_lock = threading.Lock()
        self._agg = {"batches": 0, "build_s": 0.0, "transfer_s": 0.0,
                     "wait_s": 0.0}
        self._thread = threading.Thread(
            target=self._produce, name=name, daemon=True
        )
        self._thread.start()

    # ---- producer ---------------------------------------------------------

    def _produce(self) -> None:
        try:
            while not self._stop.is_set():
                t0 = time.perf_counter()
                try:
                    batch = next(self._inner)
                except StopIteration:
                    self._put(_DONE)
                    return
                build_s = time.perf_counter() - t0
                t1 = time.perf_counter()
                if self._transfer is not None:
                    batch = self._transfer(batch)
                transfer_s = time.perf_counter() - t1
                if not self._put((batch, build_s, transfer_s)):
                    return  # closed while waiting for queue space
        except BaseException as exc:  # noqa: BLE001  # ftc: ignore[silent-except] -- not swallowed: carried across the thread boundary and re-raised on the consumer in __next__
            self._put(_Failure(exc))

    def _put(self, item: Any) -> bool:
        """Bounded put that stays responsive to :meth:`close` — a plain
        blocking ``put`` on a full queue would hang shutdown forever."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    # ---- consumer ---------------------------------------------------------

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        if self._exhausted:
            raise StopIteration
        if self._stop.is_set():
            # closed: the producer exited without posting _DONE and the
            # queue was drained — a blocking get() here would hang forever
            raise StopIteration
        t0 = time.perf_counter()
        item = self._queue.get()
        self.last_wait_s = time.perf_counter() - t0
        if item is _DONE:
            self._exhausted = True
            raise StopIteration
        if isinstance(item, _Failure):
            self._exhausted = True
            self.close()
            raise item.exc  # the original exception, original traceback
        batch, self.last_build_s, self.last_transfer_s = item
        with self._agg_lock:
            self._agg["batches"] += 1
            self._agg["build_s"] += self.last_build_s
            self._agg["transfer_s"] += self.last_transfer_s
            self._agg["wait_s"] += self.last_wait_s
        return batch

    def pop_stats(self) -> dict[str, float]:
        """Drain the aggregate window: totals since the last pop —
        ``batches``, producer-side ``build_s``/``transfer_s``, and
        consumer-visible ``wait_s``."""
        with self._agg_lock:
            out = dict(self._agg)
            for k in self._agg:
                self._agg[k] = 0 if k == "batches" else 0.0
        return out

    # ---- lifecycle --------------------------------------------------------

    def close(self) -> None:
        """Stop the producer and join it. Safe to call repeatedly, and from
        the consumer while the producer is blocked on a full queue."""
        self._stop.set()
        # drain so a producer stuck in _put observes the stop event promptly
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        # the producer can still be inside next(self._inner) (e.g. an image
        # decode) — bounded join; the daemon thread cannot block exit
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "PrefetchIterator":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


def prefetch_batches(
    batches: Iterable[Any],
    depth: int = 2,
    transfer: Callable[[Any], Any] | None = None,
) -> Iterator[Any]:
    """Wrap ``batches`` with background prefetch; ``depth <= 0`` is the
    escape hatch — the plain synchronous iterator comes back unchanged."""
    if depth <= 0:
        return iter(batches)
    return PrefetchIterator(batches, depth=depth, transfer=transfer)
