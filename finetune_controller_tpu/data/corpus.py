"""On-box natural-language corpus assembly (no network egress).

The fidelity proof (``finetune_controller_tpu/fidelity.py``) needs genuine
English text to pretrain a small base model — the reference's only example
trains on real MNIST digits (reference ``app/models/examples/mnist.py:13-99``),
and our equivalent north star is a fine-tune whose loss drop reflects a real
signal, not ``data/synthetic.py`` integer patterns.

The bench/test environment has no network, so the corpus is assembled from
text that ships with every CPython install: module/class/function docstrings
of a fixed stdlib list. That is real prose (sentences, headings, grammar) with
the statistics byte-level language modeling needs — a pretrained base scores
dramatically better on held-out English than a random-init model, which is
exactly the contrast the proof asserts.

Deterministic for a given CPython build (docstrings are versioned source).
"""

from __future__ import annotations

import importlib
import inspect
import re

#: fixed module list — broad, prose-heavy stdlib docs; deliberately NOT
#: "every importable module" (import side effects, platform variance)
_STDLIB_MODULES = [
    "argparse", "asyncio", "base64", "bisect", "calendar", "codecs",
    "collections", "configparser", "contextlib", "csv", "datetime",
    "difflib", "email", "enum", "fileinput", "fnmatch", "functools",
    "getpass", "gettext", "glob", "gzip", "hashlib", "heapq", "hmac",
    "html", "http", "imaplib", "inspect", "io", "ipaddress", "itertools",
    "json", "logging", "mailbox", "math", "mimetypes", "multiprocessing",
    "netrc", "ntpath", "numbers", "operator", "os", "pathlib", "pickle",
    "pickletools", "platform", "plistlib", "posixpath", "pprint",
    "profile", "queue", "random", "re", "sched", "secrets", "selectors",
    "shelve", "shlex", "shutil", "smtplib", "socket", "socketserver",
    "sqlite3", "ssl", "statistics", "string", "stringprep", "struct",
    "subprocess", "tarfile", "tempfile", "textwrap", "threading",
    "timeit", "tokenize", "traceback", "types", "typing", "unittest",
    "urllib.parse", "urllib.request", "uuid", "warnings", "wave",
    "weakref", "xml.dom", "xml.etree.ElementTree", "zipfile", "zlib",
]

_WS = re.compile(r"[ \t]+")


def _clean(doc: str) -> str:
    """Normalize a docstring toward plain prose: strip each line and collapse
    intra-line whitespace (indentation carries no language signal here).
    Length/quality filtering happens in :func:`build_corpus`."""
    lines = [_WS.sub(" ", ln.strip()) for ln in doc.strip().splitlines()]
    text = "\n".join(lines).strip()
    return text


def iter_docstrings(modules: list[str] | None = None):
    """Yield cleaned docstrings: each module's own doc plus its public
    classes', functions', and methods' docs. Import failures are skipped
    (the fixed list holds pure-stdlib names, but a trimmed container build
    must degrade to a smaller corpus, not crash)."""
    seen: set[int] = set()
    if modules is None:
        modules = _STDLIB_MODULES
    for name in modules:
        try:
            mod = importlib.import_module(name)
        except Exception:  # ftc: ignore[silent-except] -- trimmed container builds degrade to a smaller corpus by design (see docstring)
            continue
        if mod.__doc__:
            yield _clean(mod.__doc__)
        for _, member in inspect.getmembers(mod):
            if not (inspect.isclass(member) or inspect.isfunction(member)):
                continue
            if getattr(member, "__module__", None) != mod.__name__:
                continue  # re-exports would duplicate text across modules
            doc = inspect.getdoc(member)
            if doc and id(member) not in seen:
                seen.add(id(member))
                yield _clean(doc)
            if inspect.isclass(member):
                for _, meth in inspect.getmembers(member, inspect.isfunction):
                    mdoc = inspect.getdoc(meth)
                    if mdoc and id(meth) not in seen:
                        seen.add(id(meth))
                        yield _clean(mdoc)


def build_corpus(
    max_bytes: int = 400_000, *, min_doc_bytes: int = 120,
    modules: list[str] | None = None,
) -> list[str]:
    """Assemble up to ``max_bytes`` of English documents (longest sources
    first would bias toward a few modules; the fixed module order keeps the
    mix broad and deterministic)."""
    docs: list[str] = []
    total = 0
    for text in iter_docstrings(modules):
        raw = text.encode("utf-8")
        if len(raw) < min_doc_bytes:
            continue  # one-liners carry little modelable structure
        if not text.isascii():
            # byte-level vocab 256 handles any byte, but non-ASCII is rare
            # enough in docstrings to be noise rather than signal
            continue
        docs.append(text)
        total += len(raw)
        if total >= max_bytes:
            break
    if not docs:
        raise RuntimeError("no stdlib docstrings found — broken environment?")
    return docs


def write_corpus_jsonl(path, max_bytes: int = 400_000) -> int:
    """Write ``{"text": ...}`` rows for the data loader; returns corpus bytes."""
    import json

    docs = build_corpus(max_bytes)
    total = 0
    with open(path, "w") as f:
        for d in docs:
            f.write(json.dumps({"text": d}) + "\n")
            total += len(d.encode())
    return total
