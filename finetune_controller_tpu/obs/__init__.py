"""End-to-end observability: tracing, event timelines, step-phase profiling.

The answer to "where did this job's last 20 minutes go?" (docs/observability.md).
Three cooperating layers, zero dependencies beyond the stdlib:

* ``trace``  — trace ids minted at submit and threaded through every plane;
  OTel-compatible span dicts; the crash-safe trainer-side span log and the
  controller-side trace assembly (``GET /jobs/{id}/trace``);
* ``events`` — the structured lifecycle timeline appended to the job
  document (``GET /jobs/{id}/timeline``, ``ftc-ctl timeline``), plus the
  trainer-side ``events.jsonl`` that rides the artifact channel;
* ``prom``   — Prometheus *histogram* support for the ``/metrics`` exporter
  (step phases, queue wait, retry latency, serve TTFT) and the process-level
  ``ftc_build_info`` / ``ftc_uptime_seconds`` series;
* ``phase``  — the trainer's step-phase clock (input-wait / device-compute /
  checkpoint / sync), feeding the metrics CSV and the histograms.

The trainer-side pieces (``SpanRecorder``, ``EventLogWriter``, ``PhaseClock``)
are stdlib-only on purpose: they run inside pods that carry none of the
controller extras, exactly like ``resilience/heartbeat.py``.
"""

from .events import (
    EVENTS_FILENAME,
    EventLogWriter,
    make_event,
    parse_event_lines,
)
from .phase import PhaseClock
from .prom import Histogram, ObsHub
from .trace import (
    SpanRecorder,
    build_trace,
    new_span_id,
    new_trace_id,
    parse_span_lines,
    validate_trace,
)

__all__ = [
    "EVENTS_FILENAME",
    "EventLogWriter",
    "Histogram",
    "ObsHub",
    "PhaseClock",
    "SpanRecorder",
    "build_trace",
    "make_event",
    "new_span_id",
    "new_trace_id",
    "parse_event_lines",
    "parse_span_lines",
    "validate_trace",
]
