"""The trainer's step-phase clock: where does a training step's time go?

``input_ms`` (PR 1) answered one question — how long the step loop waited on
its batch.  The phase clock generalises it: every logging window is split
into **input-wait**, **checkpoint** (host gather + save), **sync**
(cross-host preemption agreement + heartbeat), **eval**, and the residual
**compute** (device step dispatch-to-completion — the window wall clock the
other phases don't claim).  Per-step averages land in the metrics CSV as
``phase_*_ms`` columns; the monitor feeds them into the
``ftc_step_phase_ms`` histogram (``obs/prom.py``).

Measurement is host-side ``perf_counter`` bracketing — a handful of calls
per step, no device syncs added (the ``BENCH_MODE=obs`` gate holds the whole
tracing layer under 2% of step time).
"""

from __future__ import annotations

import time


class PhaseClock:
    """Accumulates named phase seconds over one logging window."""

    #: phases measured directly; "compute" is the residual
    MEASURED = ("input", "checkpoint", "sync", "eval")

    def __init__(self, *, _clock=time.perf_counter):
        self._clock = _clock
        self._acc: dict[str, float] = {}

    def add(self, phase: str, seconds: float) -> None:
        self._acc[phase] = self._acc.get(phase, 0.0) + seconds

    class _PhaseCtx:
        __slots__ = ("clock", "phase", "t0")

        def __init__(self, clock: "PhaseClock", phase: str):
            self.clock, self.phase = clock, phase

        def __enter__(self):
            self.t0 = self.clock._clock()
            return self

        def __exit__(self, *exc):
            self.clock.add(self.phase, self.clock._clock() - self.t0)
            return False

    def phase(self, name: str) -> "_PhaseCtx":
        """``with clock.phase("checkpoint"): ...``"""
        return self._PhaseCtx(self, name)

    def window_row(self, *, steps: int, wall_s: float) -> dict[str, float]:
        """Per-step averages (ms) for the window, then reset.

        ``compute`` is the residual ``wall - sum(measured phases)`` clamped
        at 0 — with async dispatch the device work completes inside the wall
        clock even though no single bracket captured it."""
        steps = max(steps, 1)
        measured = sum(self._acc.values())
        row = {
            f"phase_{name}_ms": self._acc.get(name, 0.0) / steps * 1000.0
            for name in self.MEASURED
        }
        row["phase_compute_ms"] = max(wall_s - measured, 0.0) / steps * 1000.0
        self._acc.clear()
        return row

    @staticmethod
    def columns() -> tuple[str, ...]:
        """CSV columns :meth:`window_row` emits — declared up front so the
        MetricsWriter header includes them (``train/trainer.py``)."""
        return tuple(
            f"phase_{name}_ms" for name in PhaseClock.MEASURED
        ) + ("phase_compute_ms",)
