"""Job-lifecycle tracing: trace ids, OTel-compatible spans, trace assembly.

A ``trace_id`` is minted once at submit (``task_builder``) and rides the job
document, the backend dispatch env (``FTC_TRACE_ID``), supervisor
resubmissions, and serve loads — every attempt and every plane stamps the
same id, so one id names the job's whole life.

Spans are plain dicts in OTel shape (name, trace/span/parent ids, start/end
nanoseconds, attributes) so they can be shipped to any OTLP-speaking backend
without translation.  Two sources:

* the **trainer** records spans crash-safe to ``trace/trainer.jsonl`` in its
  artifacts dir (one flushed line per finished span — ``SpanRecorder``); the
  artifact sidecar ships them;
* the **controller** derives its spans from the job's event timeline
  (``build_trace``): the timeline is already recorded crash-safe in the job
  document, so the controller's span tree needs no second persistence path —
  pending/attempt/backoff/promotion/serve phases are reconstructed from the
  events they bracket, which also makes the tree gap-free by construction
  (every lifecycle event falls inside the phase span it delimits).

``GET /jobs/{id}/trace`` assembles both sources; the monitor exports the
same assembly to ``{artifacts_uri}/trace/trace.json`` when a job reaches a
terminal state, so traces survive control-plane restarts.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
from typing import Any

logger = logging.getLogger(__name__)

TRACE_DIRNAME = "trace"
TRAINER_SPANS_FILENAME = "trainer.jsonl"

#: nesting tolerance when validating child ⊆ parent intervals — events and
#: spans share one host clock, but float epoch→ns round-trips deserve slack
_EPS_NS = int(1e6)  # 1 ms


def new_trace_id() -> str:
    """128-bit lowercase hex trace id (the OTel wire width)."""
    return uuid.uuid4().hex


def new_span_id() -> str:
    """64-bit lowercase hex span id."""
    return uuid.uuid4().hex[:16]


def make_span(
    name: str,
    trace_id: str,
    *,
    start_ns: int,
    end_ns: int | None = None,
    parent_span_id: str | None = None,
    span_id: str | None = None,
    status: str = "ok",
    **attrs: Any,
) -> dict[str, Any]:
    return {
        "name": name,
        "trace_id": trace_id,
        "span_id": span_id or new_span_id(),
        "parent_span_id": parent_span_id,
        "start_ns": int(start_ns),
        "end_ns": None if end_ns is None else int(end_ns),
        "status": status,
        "attributes": {k: v for k, v in attrs.items() if v is not None},
    }


class SpanRecorder:
    """Trainer-side span log: one flushed JSONL line per FINISHED span
    (crash-safe — a kill mid-run loses at most the spans still open).

    Stdlib-only (runs inside pods).  Thread-safe: the async-checkpoint
    thread and the fit loop may both finish spans.
    """

    def __init__(
        self,
        artifacts_dir: str,
        trace_id: str,
        *,
        service: str = "trainer",
        attempt: int = 0,
        enabled: bool = True,
        _clock_ns=time.time_ns,
    ):
        self.dir = os.path.join(artifacts_dir, TRACE_DIRNAME)
        self.path = os.path.join(self.dir, TRAINER_SPANS_FILENAME)
        self.trace_id = trace_id
        self.service = service
        self.attempt = attempt
        self.enabled = enabled and bool(trace_id)
        self._clock_ns = _clock_ns
        self._lock = threading.Lock()
        self.write_failures = 0

    def start(self, name: str, *, parent: dict | None = None,
              **attrs: Any) -> dict[str, Any]:
        span = make_span(
            name, self.trace_id,
            start_ns=self._clock_ns(),
            parent_span_id=parent["span_id"] if parent else None,
            service=self.service, attempt=self.attempt or None, **attrs,
        )
        return span

    def finish(self, span: dict[str, Any], *, status: str = "ok",
               **attrs: Any) -> None:
        span["end_ns"] = self._clock_ns()
        span["status"] = status
        if attrs:
            span["attributes"].update(
                {k: v for k, v in attrs.items() if v is not None}
            )
        if not self.enabled:
            return
        try:
            with self._lock:
                os.makedirs(self.dir, exist_ok=True)
                with open(self.path, "a") as f:
                    f.write(json.dumps(span) + "\n")
                    f.flush()
        except OSError:
            with self._lock:  # finish() races itself across threads
                self.write_failures += 1
                failures = self.write_failures
            level = logging.WARNING if failures == 1 else logging.DEBUG
            logger.log(level, "span write to %s failed (%d so far)",
                       self.path, failures, exc_info=True)

    def record(self, name: str, *, start_ns: int, end_ns: int,
               status: str = "ok", **attrs: Any) -> dict[str, Any]:
        """Append an already-timed span (e.g. one a rollout worker stamped
        with its own ``time.time_ns`` and shipped over the transport) — same
        crash-safe JSONL write as :meth:`finish`, but the interval is the
        caller's, not this recorder's clock."""
        span = make_span(
            name, self.trace_id,
            start_ns=int(start_ns), end_ns=int(end_ns), status=status,
            service=self.service, attempt=self.attempt or None, **attrs,
        )
        if not self.enabled:
            return span
        try:
            with self._lock:
                os.makedirs(self.dir, exist_ok=True)
                with open(self.path, "a") as f:
                    f.write(json.dumps(span) + "\n")
                    f.flush()
        except OSError:
            with self._lock:
                self.write_failures += 1
                failures = self.write_failures
            level = logging.WARNING if failures == 1 else logging.DEBUG
            logger.log(level, "span write to %s failed (%d so far)",
                       self.path, failures, exc_info=True)
        return span

    class _SpanCtx:
        def __init__(self, recorder: "SpanRecorder", span: dict):
            self.recorder, self.span = recorder, span

        def __enter__(self):
            return self.span

        def __exit__(self, exc_type, exc, tb):
            self.recorder.finish(
                self.span, status="error" if exc_type else "ok"
            )
            return False

    def span(self, name: str, *, parent: dict | None = None, **attrs: Any):
        """``with recorder.span("checkpoint", step=40): ...``"""
        return self._SpanCtx(self, self.start(name, parent=parent, **attrs))


def parse_span_lines(raw: bytes | str) -> list[dict[str, Any]]:
    """Decode a span JSONL payload; torn lines are skipped."""
    if isinstance(raw, bytes):
        raw = raw.decode(errors="replace")
    out: list[dict[str, Any]] = []
    for line in raw.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict) and "span_id" in doc and "start_ns" in doc:
            out.append(doc)
    return out


# ---------------------------------------------------------------------------
# Controller-side trace assembly
# ---------------------------------------------------------------------------

#: events that end the "pending" phase — it runs submit → execution (the
#: "admitted" instant stays INSIDE it so admitted→running is never a gap)
_PENDING_ENDERS = {"running", "failed", "cancelled", "succeeded"}
#: events that end an attempt span (the job left execution)
_ATTEMPT_ENDERS = {
    "retrying", "failed", "succeeded", "cancelled", "lost", "lease-killed",
}


def _ns(ts: float) -> int:
    return int(float(ts) * 1e9)


def build_trace(
    job: dict[str, Any],
    trainer_spans: list[dict[str, Any]] | None = None,
    *,
    now: float | None = None,
) -> dict[str, Any]:
    """Assemble the job's span tree from its event timeline + trainer spans.

    ``job`` is the job document (``JobRecord.model_dump()``): ``events``,
    ``metadata.trace_id``, ``submitted_at``, ``end_time``.  Returns
    ``{"trace_id", "job_id", "spans": [...], "problems": [...]}`` where
    ``problems`` is ``validate_trace``'s verdict (empty = well-formed,
    gap-free).  Phases still open when assembled are closed at ``now`` and
    marked ``in_progress``.
    """
    now = time.time() if now is None else now
    events = sorted(
        (e for e in (job.get("events") or []) if isinstance(e.get("ts"), (int, float))),
        key=lambda e: e["ts"],
    )
    trace_id = (job.get("metadata") or {}).get("trace_id") or ""
    first_ts = events[0]["ts"] if events else job.get("submitted_at") or now
    start_ts = min(first_ts, job.get("submitted_at") or first_ts)
    last_ts = events[-1]["ts"] if events else start_ts
    end_ts = job.get("end_time") or None
    root_open = end_ts is None and (job.get("status") or "") not in (
        "succeeded", "failed", "cancelled",
    )
    root_end = max(filter(None, (end_ts, last_ts, now if root_open else None)))
    root = make_span(
        "job", trace_id,
        start_ns=_ns(start_ts), end_ns=_ns(root_end),
        service="controller", job_id=job.get("job_id"),
        status_final=job.get("status"), in_progress=root_open or None,
    )
    spans: list[dict[str, Any]] = [root]

    def phase(name: str, start: float, end: float | None, **attrs):
        open_ = end is None
        spans.append(make_span(
            name, trace_id,
            start_ns=_ns(start), end_ns=_ns(root_end if open_ else end),
            parent_span_id=root["span_id"], service="controller",
            in_progress=open_ or None, **attrs,
        ))
        return spans[-1]

    pending_since: float | None = None
    attempt_since: float | None = None
    attempt_no = 0
    promo_since: float | None = None
    serve_since: float | None = None
    for e in events:
        name, ts, attrs = e["event"], e["ts"], e.get("attrs") or {}
        if name in ("submitted", "resubmitted", "queued") and pending_since is None \
                and attempt_since is None:
            pending_since = ts
        if name in _PENDING_ENDERS and pending_since is not None:
            phase("pending", pending_since, ts, attempt=attempt_no + 1)
            pending_since = None
        if name == "running" and attempt_since is None:
            attempt_no = int(attrs.get("attempt") or attempt_no + 1)
            attempt_since = ts
        if name in _ATTEMPT_ENDERS and attempt_since is not None:
            phase(f"attempt-{attempt_no}", attempt_since, ts,
                  attempt=attempt_no, ended_by=name)
            attempt_since = None
        if name == "retrying" and pending_since is None and attempt_since is None:
            pending_since = ts  # backoff + requeue until it runs again
        if name == "promotion-started":
            promo_since = ts
        if name in ("promoted", "promotion-failed", "unpromoted"):
            # a settle without a recorded start — an unpromote (nothing
            # precedes it) or a failed unpromote — still gets an
            # instantaneous span so the event is covered, not a "gap"
            phase("promotion", ts if promo_since is None else promo_since,
                  ts, outcome=name)
            promo_since = None
        if name.startswith("serve-") and name != "serve-unloaded" \
                and serve_since is None:
            # any serve-plane event opens the phase: the fleet emits
            # replica-started events while the session is still being
            # assembled, BEFORE serve-loaded lands (docs/serving.md §Fleet)
            serve_since = ts
        if name == "serve-unloaded" and serve_since is not None:
            phase("serve", serve_since, ts)
            serve_since = None
    # close still-open phases at the root's end
    if pending_since is not None:
        phase("pending", pending_since, None, attempt=attempt_no + 1)
    if attempt_since is not None:
        phase(f"attempt-{attempt_no}", attempt_since, None, attempt=attempt_no)
    if promo_since is not None:
        phase("promotion", promo_since, None)
    if serve_since is not None:
        phase("serve", serve_since, None)

    # graft trainer spans under their attempt span (matched by attempt attr;
    # unmatched spans hang off the root so nothing is dropped)
    by_attempt = {
        s["attributes"].get("attempt"): s
        for s in spans
        if s["name"].startswith("attempt-")
    }
    trainer_ids = {s.get("span_id") for s in trainer_spans or []}
    for ts_span in trainer_spans or []:
        grafted = dict(ts_span)
        if trace_id:
            grafted["trace_id"] = trace_id
        pid = grafted.get("parent_span_id")
        if pid is None or pid not in trainer_ids:
            # no recorded parent, or the parent never landed — a kill loses
            # the spans still open (the crash-safe JSONL holds FINISHED
            # spans only), so a killed job's children would dangle off the
            # lost fit span: graft under the attempt/root instead
            parent = by_attempt.get(grafted.get("attributes", {}).get("attempt"))
            grafted["parent_span_id"] = (parent or root)["span_id"]
        spans.append(grafted)

    return {
        "trace_id": trace_id,
        "job_id": job.get("job_id"),
        "spans": spans,
        "problems": validate_trace(spans, events),
    }


def validate_trace(
    spans: list[dict[str, Any]],
    events: list[dict[str, Any]] | None = None,
) -> list[str]:
    """Structural checks: every parent resolves, every child's interval nests
    inside its parent's, and (when ``events`` are given) every event instant
    is covered by at least one non-root span — the "gap-free" property the
    e2e timeline test gates on.  Returns human-readable problems; [] = ok."""
    problems: list[str] = []
    by_id = {s["span_id"]: s for s in spans}
    for s in spans:
        pid = s.get("parent_span_id")
        if pid is not None:
            parent = by_id.get(pid)
            if parent is None:
                problems.append(f"span {s['name']!r}: unknown parent {pid}")
                continue
            if s["start_ns"] < parent["start_ns"] - _EPS_NS:
                problems.append(
                    f"span {s['name']!r} starts before parent {parent['name']!r}"
                )
            if s.get("end_ns") is not None and parent.get("end_ns") is not None \
                    and s["end_ns"] > parent["end_ns"] + _EPS_NS:
                problems.append(
                    f"span {s['name']!r} ends after parent {parent['name']!r}"
                )
        if s.get("end_ns") is not None and s["end_ns"] + _EPS_NS < s["start_ns"]:
            problems.append(f"span {s['name']!r} ends before it starts")
    for e in events or []:
        ts_ns = _ns(e["ts"])
        covered = any(
            s.get("parent_span_id") is not None
            and s["start_ns"] - _EPS_NS <= ts_ns
            and (s.get("end_ns") is None or ts_ns <= s["end_ns"] + _EPS_NS)
            for s in spans
        )
        if not covered:
            problems.append(
                f"event {e['event']!r} at ts={e['ts']} not covered by any span"
            )
    return problems


async def export_trace(state, store, job_id: str) -> bool:
    """Assemble and persist ``trace/trace.json`` next to a settled job's
    artifacts — traces survive control-plane restarts and substrate cleanup.

    Best-effort and idempotent (``metadata.trace_exported`` is the latch), so
    EVERY path that settles a job calls it: the monitor's succeeded/failed
    branches, the supervisor's terminal-failure writes, the lease-kill path,
    and the API's cancel handler.  ``state``/``store`` are duck-typed
    (StateStore/ObjectStore) to keep this module dependency-free.
    """
    try:
        job = await state.get_job(job_id)
        if job is None or not job.status.is_final or not job.artifacts_uri:
            return False
        if job.metadata.get("trace_exported"):
            return False
        spans_uri = (
            f"{job.artifacts_uri}/{TRACE_DIRNAME}/{TRAINER_SPANS_FILENAME}"
        )
        trainer_spans = []
        if await store.exists(spans_uri):
            trainer_spans = parse_span_lines(await store.get_bytes(spans_uri))
        trace = build_trace(job.model_dump(mode="json"), trainer_spans)
        await store.put_bytes(
            f"{job.artifacts_uri}/{TRACE_DIRNAME}/trace.json",
            json.dumps(trace, indent=2).encode(),
        )
        await state.merge_job_metadata(job_id, {"trace_exported": True})
        return True
    except Exception:
        logger.debug("trace export failed for %s", job_id, exc_info=True)
        return False
