"""The job-lifecycle event timeline (docs/observability.md §Timeline).

Every plane that moves a job appends a structured event to the job document
(``StateStore.append_job_event``): the API on submit/cancel/promote, the
monitor on every observed status transition, the retry supervisor on
preempt/resize/retry/resubmit, the serve manager on load/unload.  The trainer
— which has no state-store access — appends to ``events.jsonl`` in its
artifacts dir instead; the artifact sidecar ships it and the monitor ingests
new rows into the job document (the same channel ``heartbeat.json`` rides).

Exactly-once: every emitter stamps an idempotency ``key`` and
``append_job_event`` drops duplicates, so an emitter that retries after a
crash (the monitor appends the event BEFORE the status write it describes)
converges to one event per transition instance.

Event dict shape (the timeline API serves these verbatim)::

    {"ts": 1722700000.0, "event": "running", "key": "running:a1",
     "attrs": {"attempt": 1, "slices": 2}}
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any

logger = logging.getLogger(__name__)

EVENTS_FILENAME = "events.jsonl"

# ---------------------------------------------------------------------------
# Canonical event names (the catalog docs/observability.md documents).
# Controller-side lifecycle:
SUBMITTED = "submitted"            # API accepted the job (task_builder)
QUEUED = "queued"                  # re-entered the queue (monitor)
ADMITTED = "admitted"              # scheduler granted chips (monitor)
RUNNING = "running"                # attempt is executing (monitor)
RESTARTING = "restarting"          # backend-local restart (monitor)
PREEMPTED = "preempted"            # evicted for a preemptor (supervisor)
RESIZED = "resized"                # scheduler shrink/grow (supervisor)
RETRYING = "retrying"              # waiting out a backoff (supervisor)
RESUBMITTED = "resubmitted"        # handed back to the backend (supervisor)
SUCCEEDED = "succeeded"
FAILED = "failed"
CANCELLED = "cancelled"
LOST = "lost"                      # vanished from the backend (monitor)
LEASE_KILLED = "lease-killed"      # liveness lease expired (monitor)
PROMOTION_STARTED = "promotion-started"
PROMOTED = "promoted"
PROMOTION_FAILED = "promotion-failed"
UNPROMOTED = "unpromoted"
SERVE_LOADED = "serve-loaded"
SERVE_UNLOADED = "serve-unloaded"
PROFILE_REQUESTED = "profile-requested"
# Trainer-side (via events.jsonl → monitor ingest):
TRAIN_STARTED = "train-started"
CHECKPOINT_COMMITTED = "checkpoint-committed"
PROFILE_CAPTURED = "profile-captured"
PREEMPT_EXIT = "preempt-exit"
TRAIN_FINISHED = "train-finished"


def make_event(
    event: str,
    *,
    ts: float | None = None,
    key: str | None = None,
    **attrs: Any,
) -> dict[str, Any]:
    """One timeline event; ``key`` is the exactly-once idempotency handle."""
    doc: dict[str, Any] = {
        "ts": time.time() if ts is None else float(ts),
        "event": event,
        "attrs": {k: v for k, v in attrs.items() if v is not None},
    }
    if key:
        doc["key"] = key
    return doc


async def append_event_safe(
    state, job_id: str, event: str, *, key: str | None = None,
    ts: float | None = None, **attrs: Any,
) -> bool:
    """Best-effort timeline append shared by every control-plane emitter
    (monitor, supervisor, API, serve) — observability must never stall the
    plane that carries it.  ``state`` is duck-typed (StateStore)."""
    try:
        await state.append_job_event(
            job_id, make_event(event, key=key, ts=ts, **attrs)
        )
        return True
    except Exception:
        logger.debug("timeline append (%s) failed for %s", event, job_id,
                     exc_info=True)
        return False


class EventLogWriter:
    """Trainer-side lifecycle events, appended to ``events.jsonl`` in the
    artifacts dir (rank 0 only; stdlib-only — runs inside pods).

    Crash-safe by construction: one flushed JSON line per event, append-only.
    The file is RESTORED into a fresh sandbox on resume (``backends/local.py``
    stages it back with the checkpoints) so the line index — the monitor's
    ingest watermark — stays monotonic across attempts.
    """

    def __init__(
        self,
        artifacts_dir: str,
        *,
        trace_id: str = "",
        attempt: int = 0,
        enabled: bool = True,
    ):
        self.path = os.path.join(artifacts_dir, EVENTS_FILENAME)
        self.trace_id = trace_id
        self.attempt = attempt
        self.enabled = enabled
        self.write_failures = 0

    def emit(self, event: str, *, force: bool = False, **attrs: Any) -> bool:
        """``force=True`` writes even when the tracing kill switch disabled
        the writer — for confirmations of explicitly operator-requested
        actions (an armed profile window must never complete silently)."""
        if not (self.enabled or force):
            return False
        doc = make_event(event, **attrs)
        if self.trace_id:
            doc["trace_id"] = self.trace_id
        if self.attempt:
            doc["attrs"].setdefault("attempt", self.attempt)
        try:
            with open(self.path, "a") as f:
                f.write(json.dumps(doc) + "\n")
                f.flush()
        except OSError:
            # observability must never crash the run it observes (the
            # heartbeat writer's contract)
            self.write_failures += 1
            level = logging.WARNING if self.write_failures == 1 else logging.DEBUG
            logger.log(level, "event write to %s failed (%d so far)",
                       self.path, self.write_failures, exc_info=True)
            return False
        return True


def parse_event_lines(raw: bytes | str) -> list[dict[str, Any]]:
    """Decode an ``events.jsonl`` payload; torn/garbage lines are skipped
    (a crash mid-append must not poison the whole timeline)."""
    if isinstance(raw, bytes):
        raw = raw.decode(errors="replace")
    out: list[dict[str, Any]] = []
    for line in raw.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict) and isinstance(doc.get("event"), str):
            out.append(doc)
    return out
