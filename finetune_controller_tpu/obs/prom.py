"""Prometheus histogram support for the ``/metrics`` exporters.

The controller's exporter (``controller/server.py::prometheus_metrics``) only
spoke gauges and counters; latency questions ("what's the p99 queue wait?",
"how is step time split across phases?") need *histograms*.  This module is
the shared implementation: cumulative ``_bucket{le=...}`` series plus
``_sum``/``_count``, rendered in the text exposition format, with bounded
label cardinality (labels are fixed small sets like the step phase — never
per-request ids).

:class:`ObsHub` is the process-wide registry a runtime carries: the monitor
observes queue waits and step phases into it, the retry supervisor observes
retry latency, the serve batcher observes TTFT, and both the API server's
``/metrics`` and the standalone monitor daemon's metrics listener render it —
alongside ``ftc_build_info`` and ``ftc_uptime_seconds`` for the process.
"""

from __future__ import annotations

import math
import time
from typing import Any, Iterable


def escape_label(value: Any) -> str:
    """Escape a label VALUE per the exposition format: backslash, double
    quote, and newline must be escaped or a hostile job_id/flavor name
    breaks the whole scrape.  The single implementation for the whole
    /metrics payload (the server aliases it as ``prom_escape``) — it lives
    here because the stdlib-only obs layer must not import the
    aiohttp-bearing server module."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    out = f"{v:g}"
    return out


class Histogram:
    """One Prometheus histogram family, optionally labelled.

    ``buckets`` are the finite upper bounds (ascending); ``+Inf`` is implicit.
    ``label_names`` is a fixed tuple — every observation must supply exactly
    those labels, keeping cardinality a design-time decision.
    """

    def __init__(
        self,
        name: str,
        help_text: str,
        buckets: Iterable[float],
        label_names: tuple[str, ...] = (),
    ):
        self.name = name
        self.help_text = help_text
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one finite bucket")
        self.label_names = tuple(label_names)
        #: label-values tuple -> [per-bucket counts..., +Inf count]
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}

    def observe(self, value: float, **labels: Any) -> None:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {tuple(labels)}"
            )
        key = tuple(str(labels[n]) for n in self.label_names)
        counts = self._counts.get(key)
        if counts is None:
            counts = self._counts[key] = [0] * (len(self.buckets) + 1)
            self._sums[key] = 0.0
        value = float(value)
        for i, le in enumerate(self.buckets):
            if value <= le:
                counts[i] += 1
                break
        else:
            counts[-1] += 1
        self._sums[key] += value

    def count(self, **labels: Any) -> int:
        key = tuple(str(labels[n]) for n in self.label_names)
        return sum(self._counts.get(key, ()))

    def render(self) -> list[str]:
        """Text-exposition lines (``le`` buckets are CUMULATIVE per the
        format; an empty histogram renders only its TYPE/HELP header so
        scrapers learn the family exists)."""
        lines = [
            f"# HELP {self.name} {self.help_text}",
            f"# TYPE {self.name} histogram",
        ]
        for key in sorted(self._counts):
            base = ",".join(
                f'{n}="{escape_label(v)}"'
                for n, v in zip(self.label_names, key)
            )
            cum = 0
            for le, n in zip(
                self.buckets + (math.inf,), self._counts[key]
            ):
                cum += n
                label = f'{base},le="{_fmt(le)}"' if base else f'le="{_fmt(le)}"'
                lines.append(f"{self.name}_bucket{{{label}}} {cum}")
            suffix = f"{{{base}}}" if base else ""
            lines.append(f"{self.name}_sum{suffix} {self._sums[key]:g}")
            lines.append(f"{self.name}_count{suffix} {cum}")
        return lines


#: step-phase bucket bounds in MILLISECONDS — sub-ms CPU test steps through
#: multi-second large-model steps (docs/observability.md documents these)
STEP_PHASE_BUCKETS_MS = (
    0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000, 30000,
)
#: queue wait / retry latency bounds in SECONDS
WAIT_BUCKETS_S = (0.1, 0.5, 1, 2, 5, 10, 30, 60, 120, 300, 600, 1800, 3600)
#: serve time-to-first-token bounds in SECONDS
TTFT_BUCKETS_S = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10)

#: metrics-CSV column -> histogram phase label (what the monitor ingests)
PHASE_COLUMNS = {
    "phase_input_ms": "input",
    "phase_compute_ms": "compute",
    "phase_checkpoint_ms": "checkpoint",
    "phase_sync_ms": "sync",
    "phase_eval_ms": "eval",
}


class ObsHub:
    """The process's observability registry: histograms + identity series.

    One per control-plane process (``Runtime.obs``); components receive it at
    construction and observe into it, the metrics handlers render it.
    """

    def __init__(self, *, _clock=time.time):
        self._clock = _clock
        self.started_at = _clock()
        self.step_phase_ms = Histogram(
            "ftc_step_phase_ms",
            "Per-step time by trainer phase (ms), from synced metrics rows",
            STEP_PHASE_BUCKETS_MS, ("phase",),
        )
        self.queue_wait_seconds = Histogram(
            "ftc_queue_wait_seconds",
            "Submit (or requeue) to RUNNING, per attempt",
            WAIT_BUCKETS_S,
        )
        self.retry_latency_seconds = Histogram(
            "ftc_retry_latency_seconds",
            "Attempt failure to resubmission (backoff + queue)",
            WAIT_BUCKETS_S,
        )
        self.serve_ttft_seconds = Histogram(
            "ftc_serve_ttft_seconds",
            "Serve request submit to first generated token",
            TTFT_BUCKETS_S,
        )

    def observe_step_phases(self, row: dict[str, Any]) -> int:
        """Feed one metrics-CSV row's ``phase_*_ms`` columns; returns the
        number of phases observed (0 = the row carries no phase data)."""
        n = 0
        for column, phase in PHASE_COLUMNS.items():
            raw = row.get(column)
            if raw in (None, ""):
                continue
            try:
                value = float(raw)
            except (TypeError, ValueError):
                continue
            self.step_phase_ms.observe(value, phase=phase)
            n += 1
        return n

    def render(self) -> list[str]:
        lines: list[str] = []
        for hist in (
            self.step_phase_ms,
            self.queue_wait_seconds,
            self.retry_latency_seconds,
            self.serve_ttft_seconds,
        ):
            lines.extend(hist.render())
        return lines

    def render_process_info(
        self, *, process: str, version: str, backend: str
    ) -> list[str]:
        """``ftc_build_info`` (constant 1, identity in labels) and
        ``ftc_uptime_seconds`` for this process."""
        labels = (
            f'process="{escape_label(process)}",'
            f'version="{escape_label(version)}",'
            f'backend="{escape_label(backend)}"'
        )
        return [
            "# TYPE ftc_build_info gauge",
            f"ftc_build_info{{{labels}}} 1",
            "# TYPE ftc_uptime_seconds gauge",
            f'ftc_uptime_seconds{{process="{escape_label(process)}"}} '
            f"{self._clock() - self.started_at:.3f}",
        ]
