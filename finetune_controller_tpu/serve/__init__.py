"""Continuous-batching inference serving (docs/serving.md).

The lifecycle closer: the reference hands promoted artifacts to an unnamed
external inference stack (SURVEY.md §3.4); this package serves them.

* :mod:`engine`  — slot-based batch decode over the flax ``cache`` collection
  (fixed decode slots, bucketed prefill, bounded compile count), with an
  optional paged KV layout where lanes hold pool pages proportional to their
  actual length (docs/serving.md §Paged KV);
* :mod:`kv_pages` — the page pool's host-side allocator: free list,
  copy-on-write refcounts, reservation-backed admission control;
* :mod:`adapters` — multi-tenant unmerged-LoRA registry: stacked per-tenant
  adapters multiplexed on one base fleet via per-lane adapter ids;
* :mod:`batcher` — asyncio admission queue with backpressure + deadlines +
  per-tenant deficit-round-robin fairness;
* :mod:`fleet`   — N health-checked replicas per served job: stall/fault
  detection, restart with resilience backoff, graceful drain, zero-downtime
  checkpoint rollover;
* :mod:`router`  — spreads requests over the fleet with failover retries,
  idempotent request ids (exactly-once), and Retry-After load shedding;
* :mod:`loader`  — promoted-checkpoint resolution/loading + LoRA merge +
  adapter-only staging for multi-tenant fleets;
* :mod:`service` — aiohttp routes mounted on the controller server.

With ``serve_transport=process`` the fleet's replicas are worker PROCESSES
behind an RPC socket (``finetune_controller_tpu/transport/``,
docs/serving.md §Cross-process transport) — same fleet/router semantics,
real core-level scaling.
"""

from .adapters import AdapterRegistry, UnknownAdapter
from .engine import BatchEngine, EngineConfig, GenRequest, GenResult
from .fleet import Replica, ReplicaFleet, ReplicaState
from .kv_pages import KVPagePool, PageRun, PoolExhausted
from .router import FleetUnavailable, ReplicaRouter

__all__ = [
    "AdapterRegistry",
    "BatchEngine",
    "EngineConfig",
    "FleetUnavailable",
    "GenRequest",
    "GenResult",
    "KVPagePool",
    "PageRun",
    "PoolExhausted",
    "Replica",
    "ReplicaFleet",
    "ReplicaRouter",
    "ReplicaState",
    "UnknownAdapter",
]
