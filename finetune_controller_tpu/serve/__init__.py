"""Continuous-batching inference serving (docs/serving.md).

The lifecycle closer: the reference hands promoted artifacts to an unnamed
external inference stack (SURVEY.md §3.4); this package serves them.

* :mod:`engine`  — slot-based batch decode over the flax ``cache`` collection
  (fixed decode slots, bucketed prefill, bounded compile count);
* :mod:`batcher` — asyncio admission queue with backpressure + deadlines;
* :mod:`loader`  — promoted-checkpoint resolution/loading + LoRA merge;
* :mod:`service` — aiohttp routes mounted on the controller server.
"""

from .engine import BatchEngine, EngineConfig, GenRequest, GenResult

__all__ = ["BatchEngine", "EngineConfig", "GenRequest", "GenResult"]
