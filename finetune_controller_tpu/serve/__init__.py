"""Continuous-batching inference serving (docs/serving.md).

The lifecycle closer: the reference hands promoted artifacts to an unnamed
external inference stack (SURVEY.md §3.4); this package serves them.

* :mod:`engine`  — slot-based batch decode over the flax ``cache`` collection
  (fixed decode slots, bucketed prefill, bounded compile count);
* :mod:`batcher` — asyncio admission queue with backpressure + deadlines;
* :mod:`fleet`   — N health-checked replicas per served job: stall/fault
  detection, restart with resilience backoff, graceful drain, zero-downtime
  checkpoint rollover;
* :mod:`router`  — spreads requests over the fleet with failover retries,
  idempotent request ids (exactly-once), and Retry-After load shedding;
* :mod:`loader`  — promoted-checkpoint resolution/loading + LoRA merge;
* :mod:`service` — aiohttp routes mounted on the controller server.
"""

from .engine import BatchEngine, EngineConfig, GenRequest, GenResult
from .fleet import Replica, ReplicaFleet, ReplicaState
from .router import FleetUnavailable, ReplicaRouter

__all__ = [
    "BatchEngine",
    "EngineConfig",
    "FleetUnavailable",
    "GenRequest",
    "GenResult",
    "Replica",
    "ReplicaFleet",
    "ReplicaRouter",
    "ReplicaState",
]
