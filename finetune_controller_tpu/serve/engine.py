"""Slot-based continuous-batching decode engine.

The serving core: a fixed batch of ``slots`` decode lanes runs ONE jitted
single-token step, and requests are admitted into free lanes between steps —
a new request joins mid-flight instead of waiting for the batch to drain
(the VirtualFlow idea: request slots decoupled from physical batch shape, so
traffic shape never changes the compiled program).

Two memory regimes for the KV cache (docs/serving.md §Paged KV):

* **unpaged** (the PR-4/6 layout): every lane owns a contiguous
  ``cache_len`` stripe of the batch cache, reserved at admit time whatever
  the request's actual length;
* **paged** (``EngineConfig.page_tokens > 0``): the cache is a shared pool
  of fixed-size pages (``serve/kv_pages.py``) addressed through per-lane
  page tables that ride into every jitted call — a lane materializes pages
  as its tokens actually arrive (prompt pages at admit, one page per
  ``page_tokens`` decode steps after), eviction frees them immediately, and
  the prefix cache stores page RUNS shared copy-on-write instead of
  full-shape snapshots.  Admission reserves a request's worst-case page
  count up front, so growth can never OOM mid-flight: a pool too full to
  host a request is backpressure (:class:`~finetune_controller_tpu.serve.
  kv_pages.PoolExhausted` → the batcher keeps it queued → a full queue is a
  429 with ``Retry-After``), never a crash.

Multi-tenant unmerged-LoRA multiplexing (docs/serving.md §Multi-tenant
adapters, ``EngineConfig.tenant_slots > 0``): the model's ``"tenants"``
collection stacks per-tenant adapters and each lane's adapter is selected by
the per-row ``adapter_ids`` vector the engine passes alongside the batch —
N fine-tuned tenants share one base-model engine, and the prefix cache keys
namespaces by adapter id so one tenant's KV never splices into another's.

Compile-count contract (armed with ``analysis.recompile_guard``):

* unpaged: prefill compiles once per **prompt bucket** (+ once more per
  bucket for the prefix-reuse suffix prefill when the cache is on); the
  decode step compiles **once** at ``(slots, 1)``;
* paged: ONE prefill program serves fresh prompts and suffix continuations
  alike (the page table makes them the same shape), so the budget is
  ``len(prompt_buckets) + 1`` with or without the prefix cache.

Two host↔device traffic rules keep the hot path hot (docs/performance.md):
prefix reuse (``serve/prefix_cache.py``) and on-device token selection (the
decode step returns a ``(slots,)`` int32 token vector, never the logits).

Correctness anchor (proved in ``tests/test_serve.py`` /
``tests/test_kv_pages.py``): greedy output for any request is bit-identical
to single-request :func:`~finetune_controller_tpu.models.generate.
cached_generate`, no matter what else shares the batch, whether the cache is
paged or not.  Per-row ops are independent of other rows; masked cache slots
(including anything gathered through an unmaterialized page-table entry's
scratch page) contribute exactly 0.0 to the softmax; and the per-row cache
index lets each lane write and attend at its own position.

MoE configs are refused: expert-capacity routing couples rows through the
shared capacity budget, so batching invariance cannot hold there.
Multimodal configs are refused until the image prefix learns per-slot fill.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.recompile_guard import RecompileGuard
from ..models.generate import _sample
from .adapters import (
    AdapterRegistry,
    UnknownAdapter,
    _leaf_name,
    install_into,
)
from .kv_pages import HostPagePool, HostRun, KVPagePool, PageRun, PoolExhausted
from .prefix_cache import PrefixCache, resolve_reuse_length

logger = logging.getLogger(__name__)


class PromptTooLong(ValueError):
    """Prompt exceeds the largest configured prefill bucket."""


class EngineBusy(RuntimeError):
    """No free slot (the batcher queues instead of surfacing this)."""


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Shape of the serving batch — these knobs bound the compile count."""

    #: fixed decode lanes (the physical batch); the compiled decode step
    #: always runs all of them, occupied or not
    slots: int = 8
    #: prefill pad targets, ascending; one prefill compile per bucket used
    prompt_buckets: tuple[int, ...] = (32, 128, 512)
    #: per-request cap on generated tokens; also sizes the KV cache
    max_new_tokens: int = 128
    #: byte budget for the prefix-reuse KV cache (0 = disabled): admissions
    #: whose prompt shares a cached prefix prefill only the suffix
    #: (``serve/prefix_cache.py``; ``serve_prefix_cache_mb`` in Settings)
    prefix_cache_bytes: int = 0
    #: compile budget: defaults to len(prompt_buckets) + 1 (the decode step),
    #: or 2*len(prompt_buckets) + 1 with the prefix cache on AND paging off
    #: (fill AND fill_from per bucket); the guard RAISES past it — an
    #: unexpected compile on the serve path is a latency bug, not a warning
    recompile_budget: int = 0
    #: paged KV (docs/serving.md §Paged KV): sequence positions per page;
    #: 0 keeps the unpaged contiguous-lane layout
    page_tokens: int = 0
    #: total pool pages including the scratch page; 0 = auto-size to the
    #: unpaged capacity (``slots * pages_per_lane + 1``) — set it lower to
    #: actually oversubscribe memory, which is the point
    pool_pages: int = 0
    #: multi-tenant adapter stack slots INCLUDING base slot 0; 0 = off
    tenant_slots: int = 0
    #: stacked adapter rank ceiling (tenants pad up to it, bit-neutrally)
    tenant_rank: int = 0
    #: host-RAM KV tier byte budget (docs/serving.md §KV tiering;
    #: ``serve_kv_host_pool_mb`` in Settings): 0 = off.  Paged + prefix
    #: cache only — past the DEVICE prefix budget, LRU entries demote to
    #: pinned host pages and restore on touch, so idle-session and
    #: long-context KV stops competing with hot decode for device pages
    host_pool_bytes: int = 0

    @property
    def cache_len(self) -> int:
        return max(self.prompt_buckets) + self.max_new_tokens

    @property
    def paged(self) -> bool:
        return self.page_tokens > 0

    @property
    def pages_per_lane(self) -> int:
        """Page-table width: pages covering one full-length lane."""
        if not self.page_tokens:
            return 0
        return -(-self.cache_len // self.page_tokens)

    @property
    def effective_pool_pages(self) -> int:
        if not self.paged:
            return 0
        return self.pool_pages or (self.slots * self.pages_per_lane + 1)

    def bucket_for(self, prompt_len: int) -> int:
        for b in self.prompt_buckets:
            if prompt_len <= b:
                return b
        raise PromptTooLong(
            f"prompt length {prompt_len} exceeds the largest prefill bucket "
            f"{max(self.prompt_buckets)}"
        )


@dataclasses.dataclass
class GenRequest:
    request_id: str
    tokens: list[int]                  # prompt token ids
    max_new_tokens: int = 32
    temperature: float = 0.0           # 0 = greedy (the bit-reproducible path)
    top_k: int = 0
    eos_id: int | None = None
    seed: int = 0                      # sampling stream (temperature > 0)
    #: multi-tenant serving: which loaded adapter decodes this request
    #: ("" = the base model, stack slot 0)
    adapter_id: str = ""


@dataclasses.dataclass
class GenResult:
    request_id: str
    prompt_tokens: list[int]
    generated: list[int]               # includes the eos token when hit
    finish_reason: str                 # "length" | "eos" | "evicted"
    steps: int                         # decode steps this request rode
    admitted_at: float = 0.0
    finished_at: float = 0.0
    #: which fleet replica decoded this request (router-annotated; "" when
    #: the engine is driven directly) — the router → replica trace hop
    replica_id: str = ""


@dataclasses.dataclass
class _Slot:
    lane: int = 0                      # this slot's row in the batch cache
    req: GenRequest | None = None
    next_pos: int = 0                  # sequence position of the token to feed
    last_token: int = 0                # token to feed at next_pos
    generated: list[int] = dataclasses.field(default_factory=list)
    rng: Any = None                    # per-request sampling stream
    admitted_at: float = 0.0
    # paged-mode bookkeeping (``serve/kv_pages.py``)
    pages: list[int] = dataclasses.field(default_factory=list)
    reserved: int = 0                  # booked-but-unmaterialized pages
    adapter_id: str = ""               # tenant serving this lane

    @property
    def active(self) -> bool:
        return self.req is not None


def _batch_axis(big_shape: tuple, small_shape: tuple) -> int:
    """The axis where a B=1 prefill cache leaf maps into the slots-wide batch
    cache leaf (scanned models carry a leading layer axis, so it is not a
    fixed position)."""
    for ax, (b, s) in enumerate(zip(big_shape, small_shape)):
        if s == 1 and b > 1:
            return ax
    return 0  # shapes identical (slots == 1): write-in-place anywhere


class BatchEngine:
    """Continuous-batching decode over shared serving weights.

    Host-driven: :meth:`admit` fills a free lane, :meth:`step` advances every
    active lane one token and returns whatever finished.  The asyncio layer
    (``serve/batcher.py``) owns queuing/deadlines; this class owns device
    state and numerics.
    """

    def __init__(
        self,
        model: Any,
        variables: dict,
        config: EngineConfig | None = None,
        adapters: AdapterRegistry | None = None,
    ):
        cfg = model.cfg
        if getattr(cfg, "n_experts", 0):
            raise ValueError(
                "BatchEngine does not serve MoE configs: expert-capacity "
                "routing couples batch rows, breaking batching invariance"
            )
        if getattr(cfg, "vision", None) is not None:
            raise ValueError("BatchEngine serves text-only models (no pixels)")
        self.config = config or EngineConfig()
        self.variables = variables
        # --- multi-tenant adapters -----------------------------------------
        if adapters is None and self.config.tenant_slots > 0:
            adapters = AdapterRegistry(
                self.config.tenant_slots, max(1, self.config.tenant_rank)
            )
        self.adapters = adapters
        tenant_slots = adapters.capacity if adapters is not None else 0
        tenant_rank = adapters.max_rank if adapters is not None else 0
        # --- paged KV pool --------------------------------------------------
        self._pool: KVPagePool | None = None
        pool_pages = self.config.effective_pool_pages
        if self.config.paged:
            if pool_pages - 1 < self.config.pages_per_lane:
                raise ValueError(
                    f"kv page pool too small: {pool_pages} pages cannot hold "
                    f"one full lane ({self.config.pages_per_lane} pages of "
                    f"{self.config.page_tokens} tokens)"
                )
        self._dcfg = cfg.replace(
            remat=False, attention_impl="xla",
            max_seq_len=self.config.cache_len,
            kv_page_tokens=self.config.page_tokens,
            kv_pool_pages=pool_pages,
            lora_tenant_slots=tenant_slots,
            lora_tenant_rank=tenant_rank,
        )
        self._dmodel = type(model)(cfg=self._dcfg)
        per_bucket = 1
        if self.config.prefix_cache_bytes > 0 and not self.config.paged:
            per_bucket = 2  # fill + fill_from; paged mode has ONE fill
        budget = self.config.recompile_budget or (
            per_bucket * len(self.config.prompt_buckets) + 1
        )
        self.guard = RecompileGuard(budget, on_excess="raise",
                                    name="serve-engine")
        self._slots = [_Slot(lane=i) for i in range(self.config.slots)]
        self._tenants: Any = {}
        self._cache = self._init_cache()
        if self.config.paged:
            page_bytes = sum(
                leaf.nbytes // pool_pages
                for path, leaf in
                jax.tree_util.tree_leaves_with_path(self._cache)
                if _leaf_name(path) in ("k", "v")
            )
            self._pool = KVPagePool(
                pool_pages, self.config.page_tokens, page_bytes
            )
        self._prefix_cache = (
            PrefixCache(self.config.prefix_cache_bytes, pool=self._pool)
            if self.config.prefix_cache_bytes > 0 else None
        )
        # host-RAM KV tier (docs/serving.md §KV tiering): meaningful only
        # with BOTH paging (the page is the transfer unit) and the prefix
        # cache (entries are the demotable population)
        self._host_pool: HostPagePool | None = None
        if (self.config.host_pool_bytes > 0 and self._pool is not None
                and self._prefix_cache is not None):
            self._host_pool = HostPagePool(
                self.config.host_pool_bytes, self._pool.page_bytes
            )
            self._prefix_cache.enable_tier(
                self._host_pool, self._demote_run, self._restore_run
            )
        # host masters for the per-call arguments: lane page tables (paged)
        # and per-lane adapter slots (tenants) — tiny int32 arrays shipped
        # into every jitted call, so admission/eviction never touches device
        # state beyond the index park
        self._tables = np.zeros(
            (self.config.slots, max(1, self.config.pages_per_lane)), np.int32
        )
        self._adapter_slots = np.zeros((self.config.slots,), np.int32)
        # per-lane sampling streams, mirrored to the decode step as a
        # (slots, 2) uint32 leaf — rows for greedy lanes are inert
        self._rng_keys = np.zeros((self.config.slots, 2), np.uint32)
        (self._fill, self._fill_from, self._fill_paged, self._decode,
         self._insert, self._set_lane_index, self._copy_page,
         self._read_page, self._write_page) = self._build_fns()
        if self.adapters is not None:
            self.sync_adapters()
        # counters the /metrics gauges read
        self.steps_total = 0
        self.tokens_generated_total = 0
        self.requests_finished_total = 0
        self.prefix_hits_total = 0
        self.prefix_misses_total = 0
        self.prefill_tokens_saved_total = 0
        #: per-tenant token counters ("" = base model)
        self.tokens_by_tenant: dict[str, int] = {}
        self._prefix_warned = False
        # runtime transfer guard on the decode hot window
        # (FTC_TRANSFER_GUARD=raise|warn; armed by BENCH_MODE=serve):
        # every per-step host->device argument is device_put EXPLICITLY
        # before the guarded dispatch, so a steady-state decode step that
        # moves anything else across the boundary aborts loudly
        from ..analysis.transfer_guard import TransferGuard

        self._transfer_guard = TransferGuard.from_env(name="serve-decode")

    # ---- mode helpers -----------------------------------------------------

    @property
    def paged(self) -> bool:
        return self._pool is not None

    @property
    def tenant_mode(self) -> bool:
        return self.adapters is not None

    def _tenants_arg(self):
        return self._tenants

    def _page_table_arg(self):
        return jnp.asarray(self._tables) if self.paged else None

    def _adapter_ids_arg(self):
        return (jnp.asarray(self._adapter_slots)
                if self.tenant_mode else None)

    # ---- adapters ---------------------------------------------------------

    def install_adapter(self, adapter_id: str) -> None:
        """Write one registered tenant's (rank-padded) stacks into this
        engine's device tenants tree — an atomic reference swap, safe to run
        while a decode step is in flight on the previous tree."""
        entry = self.adapters.get(adapter_id)
        if entry is None:
            raise UnknownAdapter(f"adapter {adapter_id!r} is not registered")
        self._tenants = install_into(
            self._tenants, entry.slot, entry.tree, entry.alpha, entry.rank
        )

    def remove_adapter(self, adapter_id: str, slot: int) -> None:
        """Zero a departed tenant's slot and drop its prefix-cache namespace
        (the slot id may be reused by a different tenant)."""
        self._tenants = install_into(self._tenants, slot, None, 0.0, 1)
        self.drop_prefix_namespace(adapter_id)

    def drop_prefix_namespace(self, adapter_id: str) -> None:
        """Evict every prefix-cache entry computed under ``adapter_id`` —
        required whenever the tenant's WEIGHTS change (unload, and the
        in-place refresh of a tenant rollover): KV produced by the old
        deltas must never splice into lanes decoding with the new ones."""
        if self._prefix_cache is not None:
            self._prefix_cache.drop_namespace(adapter_id)

    def sync_adapters(self) -> None:
        """Install every registered tenant — fresh replicas and rollover
        generations call this before taking traffic."""
        for entry in self.adapters.entries():
            self.install_adapter(entry.adapter_id)

    def active_by_tenant(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for slot in self._slots:
            if slot.active:
                out[slot.adapter_id] = out.get(slot.adapter_id, 0) + 1
        return out

    # ---- jitted pieces ----------------------------------------------------

    def _init_cache(self):
        """Zero batch cache shaped by a throwaway (slots, 1) decode trace
        (paged mode: the page pools + per-lane index; tenant mode also
        creates the zero adapter stacks)."""
        tokens = jnp.zeros((self.config.slots, 1), jnp.int32)
        mutable = ("cache", "tenants") if self._dcfg.lora_tenant_slots \
            else ("cache",)
        kwargs: dict[str, Any] = {}
        if self.config.paged:
            kwargs["page_table"] = jnp.zeros(
                (self.config.slots, self.config.pages_per_lane), jnp.int32
            )
        if self._dcfg.lora_tenant_slots:
            kwargs["adapter_ids"] = jnp.zeros((self.config.slots,), jnp.int32)
        _, variables = self._dmodel.apply(
            self.variables, tokens,
            positions=jnp.zeros((self.config.slots, 1), jnp.int32),
            deterministic=True, decode=True, mutable=mutable, **kwargs,
        )
        if "tenants" in variables:
            self._tenants = variables["tenants"]  # zeros: slot 0 = base
        return jax.tree.map(jnp.zeros_like, variables["cache"])

    def _build_fns(self) -> tuple[Callable, ...]:
        dmodel = self._dmodel

        def _assemble(variables, tenants, cache=None):
            out = dict(variables)
            if tenants:
                out["tenants"] = tenants
            if cache is not None:
                out["cache"] = cache
            return out

        def _index_setter(value):
            def fix(path, leaf):
                return (jnp.full_like(leaf, value)
                        if _leaf_name(path) == "index" else leaf)

            return fix

        @jax.jit
        def fill(variables, tenants, tokens, adapter_ids, last_idx, true_len):
            """Prefill one request (B=1, right-padded to a bucket): logits at
            the TRUE last prompt position + a cache whose index rows read
            ``true_len`` (the model wrote the padded length)."""
            logits, updated = dmodel.apply(
                _assemble(variables, tenants), tokens, deterministic=True,
                decode=True, mutable=("cache",), adapter_ids=adapter_ids,
            )
            cache = jax.tree_util.tree_map_with_path(
                _index_setter(true_len), updated["cache"]
            )
            return jnp.take(logits, last_idx, axis=1).astype(jnp.float32), cache

        @jax.jit
        def fill_from(variables, tenants, cache, tokens, adapter_ids, start,
                      last_idx, true_len):
            """Suffix prefill over a B=1 prefix snapshot: the first ``start``
            cache positions are reused as-is, the (bucket-padded) suffix
            ``tokens`` runs a chunked forward at absolute positions
            ``[start, start + bucket)``.  Returns logits at the TRUE last
            prompt position + a lane-ready cache whose index rows read
            ``true_len`` — the same contract as ``fill``, which is what makes
            a prefix hit invisible to everything downstream."""
            cache = jax.tree_util.tree_map_with_path(
                _index_setter(start), cache
            )
            positions = (
                start + jnp.arange(tokens.shape[1], dtype=jnp.int32)[None, :]
            )
            logits, updated = dmodel.apply(
                _assemble(variables, tenants, cache), tokens,
                positions=positions, deterministic=True, decode=True,
                mutable=("cache",), adapter_ids=adapter_ids,
            )
            cache = jax.tree_util.tree_map_with_path(
                _index_setter(true_len), updated["cache"]
            )
            return jnp.take(logits, last_idx, axis=1).astype(jnp.float32), cache

        @jax.jit
        def fill_paged(variables, tenants, cache, tokens, page_table,
                       adapter_ids, start, last_idx):
            """Paged prefill/suffix-prefill, ONE program for both: a B=1
            forward whose writes scatter through ``page_table`` into the
            shared pools and whose attention gathers back through it
            (``models/llama.py`` paged branch).  ``start`` is 0 for a fresh
            prompt or the reuse length over spliced prefix pages; the lane's
            true index is set host-side after the call, so no index fixup
            pass is needed here."""
            positions = (
                start + jnp.arange(tokens.shape[1], dtype=jnp.int32)[None, :]
            )
            logits, updated = dmodel.apply(
                _assemble(variables, tenants, cache), tokens,
                positions=positions, deterministic=True, decode=True,
                mutable=("cache",), page_table=page_table,
                adapter_ids=adapter_ids,
            )
            return (jnp.take(logits, last_idx, axis=1).astype(jnp.float32),
                    updated["cache"])

        @jax.jit
        def decode(variables, tenants, cache, tokens, positions, temps,
                   top_ks, rngs, page_table, adapter_ids):
            """One batched decode step with ON-DEVICE token selection: returns
            ``(slots,)`` int32 next tokens + advanced per-lane PRNG keys +
            the updated cache — the per-step device→host transfer no longer
            scales with vocab size.  Greedy lanes take the in-graph argmax;
            sampled lanes walk the SAME ``_sample`` stream a single-request
            ``cached_generate(rng=PRNGKey(seed))`` walks (scale → per-lane
            top-k mask → split → categorical), so per-request sampled decodes
            stay reproducible independent of batch-mates."""
            logits, updated = dmodel.apply(
                _assemble(variables, tenants, cache), tokens,
                positions=positions, deterministic=True, decode=True,
                mutable=("cache",), page_table=page_table,
                adapter_ids=adapter_ids,
            )
            logits = logits[:, -1].astype(jnp.float32)   # (slots, V)
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            vocab = logits.shape[-1]

            def lane_sample(lane_logits, temp, top_k, key, greedy_tok):
                # mirrors models.generate._sample with traced temp/top_k;
                # the greedy fallback keeps inactive/greedy lanes inert
                scaled = lane_logits / jnp.where(temp > 0.0, temp, 1.0)
                kth = jnp.sort(scaled)[jnp.clip(vocab - top_k, 0, vocab - 1)]
                dist = jnp.where(
                    (top_k > 0) & (scaled < kth), -jnp.inf, scaled
                )
                split = jax.random.split(key)
                tok = jax.random.categorical(split[1], dist).astype(jnp.int32)
                sampled = temp > 0.0
                return (
                    jnp.where(sampled, tok, greedy_tok),
                    jnp.where(sampled, split[0], key),
                )

            tokens_out, rngs_out = jax.lax.cond(
                jnp.any(temps > 0.0),
                lambda: jax.vmap(lane_sample)(logits, temps, top_ks, rngs,
                                              greedy),
                # all-greedy traffic skips the per-lane vocab sort entirely
                lambda: (greedy, rngs),
            )
            return tokens_out, rngs_out, updated["cache"]

        @jax.jit
        def insert(cache, one, slot):
            """Write a B=1 prefill cache into batch lane ``slot``."""

            def put(big, small):
                ax = _batch_axis(big.shape, small.shape)
                starts = [jnp.asarray(0, jnp.int32)] * big.ndim
                starts[ax] = jnp.asarray(slot, jnp.int32)
                return jax.lax.dynamic_update_slice(big, small, tuple(starts))

            return jax.tree.map(put, cache, one)

        @jax.jit
        def set_lane_index(cache, slot, value):
            """Point one lane's cache-index rows at ``value``: 0 parks a
            freed lane (its throwaway decode writes stay benign and
            in-bounds — scratch page 0 in paged mode, position 0 unpaged),
            a prompt length arms a just-admitted paged lane (index leaves
            are batch-last: ``(B,)``, or ``(L, B)`` scanned)."""

            def fix(path, leaf):
                return (leaf.at[..., slot].set(value)
                        if _leaf_name(path) == "index" else leaf)

            return jax.tree_util.tree_map_with_path(fix, cache)

        @jax.jit
        def copy_page(cache, dst, src):
            """Copy-on-write: duplicate pool page ``src`` into ``dst`` across
            every layer's K and V pools (the page axis sits at ``ndim - 4``;
            scanned models carry a leading layer axis)."""

            def fix(path, leaf):
                if _leaf_name(path) not in ("k", "v"):
                    return leaf
                ax = leaf.ndim - 4
                page = jax.lax.dynamic_slice_in_dim(leaf, src, 1, axis=ax)
                return jax.lax.dynamic_update_slice_in_dim(
                    leaf, page, dst, axis=ax
                )

            return jax.tree_util.tree_map_with_path(fix, cache)

        @jax.jit
        def read_page(cache, src):
            """Slice pool page ``src`` out of every K/V leaf (KV tiering's
            demote path) — fixed shapes, so all page ids share ONE compile;
            leaf order is the tree traversal order ``write_page`` replays."""
            return [
                jax.lax.dynamic_slice_in_dim(leaf, src, 1, axis=leaf.ndim - 4)
                for path, leaf in jax.tree_util.tree_leaves_with_path(cache)
                if _leaf_name(path) in ("k", "v")
            ]

        @jax.jit
        def write_page(cache, dst, pages):
            """Write per-leaf page slices (a ``read_page`` result, possibly
            round-tripped through the host tier) into pool page ``dst``."""
            it = iter(pages)

            def fix(path, leaf):
                if _leaf_name(path) not in ("k", "v"):
                    return leaf
                return jax.lax.dynamic_update_slice_in_dim(
                    leaf, next(it), dst, axis=leaf.ndim - 4
                )

            return jax.tree_util.tree_map_with_path(fix, cache)

        # insert/set_lane_index/copy_page/read_page/write_page have exactly
        # one signature each (the cache trees are fixed-shape), so they stay
        # outside the guard: the budget counts the shapes that can vary with
        # traffic — prefill buckets and the decode step
        return (
            self.guard.wrap(fill, "fill"),
            self.guard.wrap(fill_from, "fill_from"),
            self.guard.wrap(fill_paged, "fill_paged"),
            self.guard.wrap(decode, "decode_step"),
            insert,
            set_lane_index,
            copy_page,
            read_page,
            write_page,
        )

    # ---- slot management --------------------------------------------------

    @property
    def free_slots(self) -> int:
        return sum(1 for s in self._slots if not s.active)

    @property
    def active_requests(self) -> int:
        return self.config.slots - self.free_slots

    @property
    def compilations(self) -> int:
        return self.guard.compilations

    @property
    def prefix_cache_bytes(self) -> int:
        return self._prefix_cache.total_bytes if self._prefix_cache else 0

    @property
    def prefix_cache_entries(self) -> int:
        return len(self._prefix_cache) if self._prefix_cache else 0

    def kv_page_stats(self) -> dict[str, int]:
        """Pool gauges for /metrics (empty when unpaged); with the host
        tier armed, its gauges and transfer counters ride along."""
        if self._pool is None:
            return {}
        stats = self._pool.stats()
        if self._host_pool is not None:
            stats.update(self._host_pool.stats())
        return stats

    def kv_slack_pages(self) -> int | None:
        """Pages still promisable to new admissions (None when unpaged) —
        the router's page-aware routing signal."""
        return self._pool.slack() if self._pool is not None else None

    def _request_span(self, req: GenRequest) -> int:
        """Last written sequence position + 1 for ``req``: the prompt plus
        every decode step's write (the final token is recorded, not
        written)."""
        return len(req.tokens) + max(0, req.max_new_tokens - 1)

    def admission_pages(self, req: GenRequest) -> int:
        """Worst-case pages admitting ``req`` reserves (0 when unpaged) —
        the batcher sums this over a multi-request admission batch so the
        batch as a WHOLE fits the pool, not just each request alone."""
        if self._pool is None:
            return 0
        return self._pool.pages_for(self._request_span(req))

    def can_admit(self, req: GenRequest, pending_pages: int = 0) -> bool:
        """Whether :meth:`admit` would succeed NOW — the batcher's gate, so
        pool pressure keeps requests queued instead of failing them.
        ``pending_pages`` adds pages already promised to requests picked for
        the same admission batch but not yet admitted.  Conservative in
        paged mode: ignores prefix sharing, so a True can never turn into a
        mid-admission exhaustion.  Permanently-impossible requests return
        True so ``admit`` raises their real error."""
        if self.free_slots == 0:
            return False
        if self._pool is None:
            return True
        need = self._pool.pages_for(self._request_span(req))
        if need > self._pool.usable_pages:
            return True  # impossible forever: let admit() fail it loudly
        return self._pool.can_reserve(need + pending_pages)

    def _resolve_adapter(self, req: GenRequest) -> tuple[int, str]:
        """(stack slot, prefix-cache namespace) for the request's tenant."""
        if not req.adapter_id:
            return 0, ""
        if self.adapters is None:
            raise UnknownAdapter(
                f"request {req.request_id} names adapter "
                f"{req.adapter_id!r} but this engine has no adapter "
                "registry (serve_max_adapters=0)"
            )
        return self.adapters.resolve(req.adapter_id), req.adapter_id

    def _resolve_prefix(self, tokens: list[int], plen: int, ns: str):
        """Longest reusable cached prefix for ``tokens`` under the adapter
        namespace ``ns``, at bucket granularity; returns ``(reuse_len,
        snapshot)`` or ``(0, None)``."""
        match_len, snapshot = self._prefix_cache.lookup(tokens, namespace=ns)
        if snapshot is None:
            return 0, None
        reuse = resolve_reuse_length(
            match_len, plen, self.config.prompt_buckets, self.config.cache_len
        )
        if reuse <= 0:
            return 0, None
        return reuse, snapshot

    def admit(self, req: GenRequest) -> GenResult | None:
        """Prefill ``req`` into a free lane (raises :class:`EngineBusy` when
        the batch is full, :class:`PromptTooLong` past the largest bucket,
        :class:`~finetune_controller_tpu.serve.kv_pages.PoolExhausted` when
        the paged pool cannot host it yet — use :meth:`can_admit` to gate).

        With the prefix cache on, the longest cached prefix of the prompt
        UNDER THE REQUEST'S ADAPTER is spliced in and only the
        (bucket-padded) suffix runs a forward — greedy/sampled outputs stay
        bit-identical to the cache-off path because causal KV depends only
        on the tokens before it (and on the adapter, which the namespace
        pins).

        Returns a :class:`GenResult` when the request finishes ON admission
        (its first sampled token hits eos, or ``max_new_tokens == 1``) —
        such a request never occupies a lane across a step."""
        slot_id = next(
            (i for i, s in enumerate(self._slots) if not s.active), None
        )
        if slot_id is None:
            raise EngineBusy("all decode slots are busy")
        plen = len(req.tokens)
        if plen < 1:
            raise ValueError("empty prompt")
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        cap = self.config.max_new_tokens
        if req.max_new_tokens > cap:
            raise ValueError(f"max_new_tokens {req.max_new_tokens} > engine cap {cap}")
        a_slot, ns = self._resolve_adapter(req)
        self.config.bucket_for(plen)  # PromptTooLong before any allocation
        if self.paged:
            logits = self._prefill_paged(req, slot_id, plen, a_slot, ns)
        else:
            logits = self._prefill_unpaged(req, slot_id, plen, a_slot, ns)
        self._adapter_slots[slot_id] = a_slot
        slot = self._slots[slot_id]
        slot.req = req
        slot.generated = []
        slot.next_pos = plen
        slot.adapter_id = req.adapter_id
        slot.rng = jax.random.PRNGKey(req.seed)
        slot.admitted_at = time.monotonic()
        result = self._emit(slot, logits)
        if result is None and req.temperature > 0.0:
            # hand the post-first-token stream to the device-side sampler
            self._rng_keys[slot_id] = np.asarray(slot.rng, np.uint32)
        return result

    # ---- unpaged prefill --------------------------------------------------

    def _prefill_unpaged(self, req, slot_id, plen, a_slot, ns):
        bucket = self.config.bucket_for(plen)
        ids1 = (jnp.asarray([a_slot], jnp.int32)
                if self.tenant_mode else None)
        reuse, snapshot = (
            self._resolve_prefix(req.tokens, plen, ns)
            if self._prefix_cache is not None else (0, None)
        )
        if snapshot is not None:
            suffix = req.tokens[reuse:]
            sbucket = self.config.bucket_for(len(suffix))
            padded = np.zeros((1, sbucket), np.int32)
            padded[0, :len(suffix)] = suffix
            logits, one = self._fill_from(
                self.variables, self._tenants_arg(), snapshot,
                jnp.asarray(padded), ids1,
                jnp.asarray(reuse, jnp.int32),
                jnp.asarray(len(suffix) - 1, jnp.int32),
                jnp.asarray(plen, jnp.int32),
            )
            self.prefix_hits_total += 1
            self.prefill_tokens_saved_total += reuse
        else:
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :plen] = req.tokens
            logits, one = self._fill(
                self.variables, self._tenants_arg(), jnp.asarray(padded),
                ids1, jnp.asarray(plen - 1, jnp.int32),
                jnp.asarray(plen, jnp.int32),
            )
            if self._prefix_cache is not None:
                self.prefix_misses_total += 1
        if self._prefix_cache is not None:
            # the hit path's `one` is a full-prompt cache too, so every
            # admission leaves its prompt resolvable for the next request
            if (not self._prefix_cache.insert(tuple(req.tokens), one,
                                              namespace=ns)
                    and not self._prefix_warned):
                self._prefix_warned = True
                logger.warning(
                    "prefix cache cannot hold a single KV snapshot (%d B > "
                    "budget %d B) — every admission will miss; raise "
                    "serve_prefix_cache_mb or disable the cache",
                    sum(x.nbytes for x in jax.tree.leaves(one)),
                    self._prefix_cache.budget_bytes,
                )
        self._cache = self._insert(self._cache, one, slot_id)
        return logits

    # ---- paged prefill ----------------------------------------------------

    def _evict_hook(self):
        if self._prefix_cache is None:
            return None
        if self._host_pool is not None:
            # tier armed: page pressure demotes LRU entries to host RAM
            # instead of destroying them (falls back to eviction when the
            # host tier is full)
            return self._prefix_cache.demote_or_evict
        return self._prefix_cache.evict_oldest

    # ---- host KV tier transfers (docs/serving.md §KV tiering) -------------
    #
    # Both directions run in ADMISSION paths (prefix lookup/insert, page
    # growth) — never inside the transfer-guarded decode dispatch, which is
    # what keeps the guard's "decode moves only its per-step feeds" contract
    # intact with the tier on.

    def _demote_run(self, run: PageRun) -> HostRun | None:
        """Copy every page of ``run`` into host slots (device state is NOT
        touched — the prefix cache releases the device refs after the swap).
        None when the host tier cannot hold the run."""
        hp = self._host_pool
        if hp is None or not hp.can_hold(len(run.pages)):
            return None
        slots = hp.alloc(len(run.pages))
        for slot_id, page in zip(slots, run.pages):
            slices = self._read_page(self._cache, jnp.asarray(page, jnp.int32))
            hp.write(slot_id, [np.asarray(x) for x in jax.device_get(slices)])
        return HostRun(slots=tuple(slots), n_tokens=run.n_tokens)

    def _restore_run(self, host_run: HostRun) -> PageRun | None:
        """Upload a demoted run back into freshly allocated device pages.
        Admission-style allocation — reserve first (None on exhaustion: the
        caller treats the hit as a miss), then materialize page by page,
        shedding OTHER cache entries under pressure.  The returned pages
        hold synthetic lane refs the prefix cache converts to cache refs."""
        pool = self._pool
        n = len(host_run.slots)
        try:
            pool.reserve(n)
        except PoolExhausted:
            return None
        pages: list[int] = []
        try:
            for slot_id in host_run.slots:
                phys = pool.alloc_reserved(self._evict_hook())
                pages.append(phys)
                self._cache = self._write_page(
                    self._cache, jnp.asarray(phys, jnp.int32),
                    [jnp.asarray(x) for x in self._host_pool.read(slot_id)],
                )
        except BaseException:
            pool.lane_release(pages, n - len(pages))
            raise
        return PageRun(pages=tuple(pages), n_tokens=host_run.n_tokens)

    def _b1_cache(self, start: int):
        """Per-admission B=1 view over the live cache: the shared pools ride
        along by reference, the per-lane index leaves shrink to one row
        holding the prefill's start position."""

        def fix(path, leaf):
            if _leaf_name(path) == "index":
                return jnp.full(leaf.shape[:-1] + (1,), start, jnp.int32)
            return leaf

        return jax.tree_util.tree_map_with_path(fix, self._cache)

    def _merge_pools(self, updated_cache):
        """Take the (B=1 apply's) updated pool leaves back into the batch
        cache, keeping the batch-shaped index leaves."""

        def pick(path, batch_leaf, b1_leaf):
            return b1_leaf if _leaf_name(path) in ("k", "v") else batch_leaf

        self._cache = jax.tree_util.tree_map_with_path(
            pick, self._cache, updated_cache
        )

    def _prefill_paged(self, req, slot_id, plen, a_slot, ns):
        pool, t = self._pool, self._pool.page_tokens
        need_total = pool.pages_for(self._request_span(req))
        if need_total > pool.usable_pages:
            raise ValueError(
                f"request {req.request_id} needs {need_total} kv pages but "
                f"the pool holds {pool.usable_pages} — raise "
                "serve_kv_pool_pages or shrink the request"
            )
        reuse, run = (
            self._resolve_prefix(req.tokens, plen, ns)
            if self._prefix_cache is not None else (0, None)
        )
        shared = list(run.pages[: reuse // t]) if run is not None else []
        pool.reserve(need_total - len(shared))  # PoolExhausted backpressure
        for page in shared:
            pool.lane_ref(page)
        slot = self._slots[slot_id]
        slot.pages = list(shared)
        slot.reserved = need_total - len(shared)
        row = np.zeros((self._tables.shape[1],), np.int32)
        row[: len(shared)] = shared
        try:
            # materialize the pages the prompt writes NOW; decode growth
            # spends the rest of the reservation page-by-page
            prompt_pages = pool.pages_for(plen)
            for i in range(len(shared), prompt_pages):
                phys = pool.alloc_reserved(self._evict_hook())
                row[i] = phys
                slot.pages.append(phys)
                slot.reserved -= 1
            if run is not None and reuse % t:
                # copy-on-write boundary: the page holding position `reuse`
                # keeps the entry's prefix KV but will be written by this
                # lane's suffix — it must be a private copy
                self._cache = self._copy_page(
                    self._cache,
                    jnp.asarray(int(row[reuse // t]), jnp.int32),
                    jnp.asarray(int(run.pages[reuse // t]), jnp.int32),
                )
                pool.cow_copies_total += 1
            start = reuse if run is not None else 0
            suffix = req.tokens[start:]
            sbucket = self.config.bucket_for(len(suffix))
            padded = np.zeros((1, sbucket), np.int32)
            padded[0, :len(suffix)] = suffix
            ids1 = (jnp.asarray([a_slot], jnp.int32)
                    if self.tenant_mode else None)
            logits, updated = self._fill_paged(
                self.variables, self._tenants_arg(), self._b1_cache(start),
                jnp.asarray(padded), jnp.asarray(row[None, :]), ids1,
                jnp.asarray(start, jnp.int32),
                jnp.asarray(len(suffix) - 1, jnp.int32),
            )
        except BaseException:
            # roll the lane's pool state back so a failed prefill (bad
            # request shape, injected fault) never leaks pages
            pool.lane_release(slot.pages, slot.reserved)
            slot.pages, slot.reserved = [], 0
            raise
        self._merge_pools(updated)
        self._cache = self._set_lane_index(
            self._cache, jnp.asarray(slot_id, jnp.int32),
            jnp.asarray(plen, jnp.int32),
        )
        self._tables[slot_id, :] = row
        if self._prefix_cache is not None:
            if run is not None:
                self.prefix_hits_total += 1
                self.prefill_tokens_saved_total += start
            else:
                self.prefix_misses_total += 1
            run_new = PageRun(
                pages=tuple(int(x) for x in row[:pool.pages_for(plen)]),
                n_tokens=plen,
            )
            if (not self._prefix_cache.insert(tuple(req.tokens), run_new,
                                              namespace=ns)
                    and not self._prefix_warned):
                self._prefix_warned = True
                logger.warning(
                    "prefix cache cannot hold a single page run (%d pages x "
                    "%d B > budget %d B) — every admission will miss; raise "
                    "serve_prefix_cache_mb or disable the cache",
                    len(run_new.pages), pool.page_bytes,
                    self._prefix_cache.budget_bytes,
                )
        return logits

    def evict(self, request_id: str) -> GenResult | None:
        """Drop an in-flight request (deadline blown / client gone); frees
        the lane — and, in paged mode, its pool pages — immediately and
        parks its cache index at 0 (see :meth:`_finish`): the freed lane
        still rides every step, decoding throwaway tokens at benign
        in-bounds positions that other rows never see, until re-admission
        overwrites it."""
        for slot in self._slots:
            if slot.active and slot.req.request_id == request_id:
                return self._finish(slot, "evicted")
        return None

    def _emit(self, slot: _Slot, logits) -> GenResult | None:
        """Select the FIRST token for a just-admitted lane from its prefill
        logits row (host-side — a B=1 admission transfer, not the per-step
        hot path, which selects on device)."""
        req = slot.req
        if req.temperature <= 0.0:
            tok = int(np.argmax(np.asarray(logits[0], np.float32)))
        else:
            # the same _sample stream a single-request cached_generate(B=1,
            # rng=PRNGKey(seed)) walks, so sampled decodes are reproducible
            # per request, independent of batch-mates
            nxt, slot.rng = _sample(
                logits[:1], temperature=req.temperature, top_k=req.top_k,
                rng=slot.rng,
            )
            tok = int(nxt[0])
        return self._record(slot, tok)

    def _record(self, slot: _Slot, tok: int) -> GenResult | None:
        """Host bookkeeping for one selected token: eos/length latching."""
        req = slot.req
        slot.generated.append(tok)
        slot.last_token = tok
        self.tokens_generated_total += 1
        self.tokens_by_tenant[slot.adapter_id] = (
            self.tokens_by_tenant.get(slot.adapter_id, 0) + 1
        )
        if req.eos_id is not None and tok == req.eos_id:
            return self._finish(slot, "eos")
        if len(slot.generated) >= req.max_new_tokens:
            return self._finish(slot, "length")
        return None

    def _finish(self, slot: _Slot, reason: str) -> GenResult:
        req = slot.req
        result = GenResult(
            request_id=req.request_id,
            prompt_tokens=list(req.tokens),
            generated=list(slot.generated),
            finish_reason=reason,
            steps=len(slot.generated),
            admitted_at=slot.admitted_at,
            finished_at=time.monotonic(),
        )
        slot.req = None
        slot.generated = []
        slot.rng = None
        slot.last_token = 0
        slot.next_pos = 0
        slot.adapter_id = ""
        self._adapter_slots[slot.lane] = 0
        if self.paged:
            # free the lane's pages (shared refs drop; exclusive pages still
            # referenced by prefix-cache entries stay resident for reuse)
            # and return the unspent reservation — eviction reclaims memory
            # IMMEDIATELY, the paged contract
            self._pool.lane_release(slot.pages, slot.reserved)
            slot.pages = []
            slot.reserved = 0
            self._tables[slot.lane, :] = 0
        # park the lane's device cache index at 0: a freed lane still rides
        # every decode step, and left at its stale position it would creep
        # toward (and past) the cache end — reset keeps its throwaway writes
        # benign and in-bounds (scratch page 0 in paged mode) until
        # re-admission overwrites the lane
        self._cache = self._set_lane_index(
            self._cache, jnp.asarray(slot.lane, jnp.int32),
            jnp.asarray(0, jnp.int32),
        )
        self.requests_finished_total += 1
        return result

    # ---- the decode loop --------------------------------------------------

    def _grow_pages(self) -> None:
        """Materialize the page each active lane's NEXT write lands in, when
        it has not been allocated yet — reservation-backed, so the free list
        (after evicting cache-only pages) can never come up short."""
        t = self._pool.page_tokens
        width = self._tables.shape[1]
        for slot in self._slots:
            if not slot.active:
                continue
            page_idx = slot.next_pos // t
            if page_idx < width and self._tables[slot.lane, page_idx] == 0:
                phys = self._pool.alloc_reserved(self._evict_hook())
                self._tables[slot.lane, page_idx] = phys
                slot.pages.append(phys)
                slot.reserved -= 1

    def step(self) -> list[GenResult]:
        """One batched decode step; returns requests that finished on it.

        Token selection happens IN the compiled step: the host receives a
        ``(slots,)`` int32 vector (plus the advanced per-lane PRNG keys),
        never the ``(slots, vocab)`` logits array."""
        if self.active_requests == 0:
            return []
        if self.paged:
            self._grow_pages()
        tokens = np.zeros((self.config.slots, 1), np.int32)
        positions = np.zeros((self.config.slots, 1), np.int32)
        temps = np.zeros((self.config.slots,), np.float32)
        top_ks = np.zeros((self.config.slots,), np.int32)
        for i, slot in enumerate(self._slots):
            if slot.active:
                tokens[i, 0] = slot.last_token
                positions[i, 0] = slot.next_pos
                temps[i] = max(slot.req.temperature, 0.0)
                top_ks[i] = slot.req.top_k
        # the tiny per-step host->device feeds (last tokens, positions,
        # sampling params — slots×a-few int32/float32) are converted BEFORE
        # the guarded window: they are the decode step's entire intended
        # transfer budget, and anything else crossing the boundary inside
        # the dispatch trips the transfer guard
        args = (
            self.variables, self._tenants_arg(), self._cache,
            jnp.asarray(tokens), jnp.asarray(positions),
            jnp.asarray(temps), jnp.asarray(top_ks),
            jnp.asarray(self._rng_keys),
            self._page_table_arg(), self._adapter_ids_arg(),
        )
        if self._transfer_guard is not None:
            next_tokens, rng_keys, self._cache = self._transfer_guard.run(
                "decode", self._decode, *args
            )
        else:
            next_tokens, rng_keys, self._cache = self._decode(*args)
        self.steps_total += 1
        next_tokens = np.asarray(next_tokens)
        # np.array (not asarray): admit() writes per-lane rows into this
        # buffer, and a zero-copy view of a jax array is read-only
        self._rng_keys = np.array(rng_keys, np.uint32)
        finished: list[GenResult] = []
        for i, slot in enumerate(self._slots):
            if not slot.active:
                continue
            slot.next_pos += 1
            done = self._record(slot, int(next_tokens[i]))
            if done is not None:
                finished.append(done)
        return finished

    def run(self, requests: list[GenRequest]) -> dict[str, GenResult]:
        """Synchronous convenience driver (tests/bench): admit everything —
        overflow waits for a lane or for pool pages — and step until the
        batch drains."""
        results: dict[str, GenResult] = {}
        pending = list(requests)
        guard_steps = itertools.count()
        limit = sum(r.max_new_tokens for r in requests) + len(requests) + 8
        while pending or self.active_requests:
            while pending and self.free_slots and self.can_admit(pending[0]):
                done = self.admit(pending.pop(0))
                if done is not None:  # finished on admission (eos / max_new=1)
                    results[done.request_id] = done
            if pending and not self.active_requests \
                    and not self.can_admit(pending[0]):
                raise PoolExhausted(
                    f"request {pending[0].request_id} can never admit: the "
                    "kv page pool is exhausted with no work in flight"
                )
            for done in self.step():
                results[done.request_id] = done
            if next(guard_steps) > limit:  # pragma: no cover - safety valve
                raise RuntimeError("engine.run failed to converge")
        missing = [r.request_id for r in requests if r.request_id not in results]
        if missing:  # pragma: no cover - engine invariant
            raise RuntimeError(f"requests did not finish: {missing}")
        return results


def warm_engine(engine: "BatchEngine", *, warm_new: int | None = None) -> None:
    """Pay every compile an engine will ever need BEFORE it takes traffic:
    one dummy request per prompt bucket plus a decode step.  The zero-downtime
    rollover contract depends on a fresh replica not compiling under load —
    the in-process fleet and the transport worker share this exact warmup so
    process-mode replicas are warm-started too (docs/serving.md §Fleet).
    Warmup counter noise is zeroed; the shapes are exactly the budgeted ones,
    so the recompile guard stays armed and accurate."""
    new_tokens = warm_new if warm_new is not None \
        else min(2, engine.config.max_new_tokens)
    for bucket in engine.config.prompt_buckets:
        engine.run([GenRequest(
            request_id=f"_warm-{bucket}", tokens=[1] * bucket,
            max_new_tokens=new_tokens,
        )])
    engine.steps_total = 0
    engine.tokens_generated_total = 0
    engine.requests_finished_total = 0
    engine.prefix_hits_total = 0
    engine.prefix_misses_total = 0
    engine.prefill_tokens_saved_total = 0
    engine.tokens_by_tenant = {}
