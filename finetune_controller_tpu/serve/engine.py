"""Slot-based continuous-batching decode engine.

The serving core: a fixed batch of ``slots`` decode lanes runs ONE jitted
single-token step, and requests are admitted into free lanes between steps —
a new request joins mid-flight instead of waiting for the batch to drain
(the VirtualFlow idea: request slots decoupled from physical batch shape, so
traffic shape never changes the compiled program).

Compile-count contract (armed with ``analysis.recompile_guard``):

* prefill compiles once per **prompt bucket** (prompts are right-padded to
  the smallest configured bucket that fits; causality makes the pad slots
  invisible to the real tokens);
* with the prefix cache enabled, suffix prefill (``fill_from``) compiles
  once per prompt bucket too — suffixes pad to the same bucket table, so
  the budget grows by exactly ``len(prompt_buckets)``;
* the decode step compiles **once**, at ``(slots, 1)``, regardless of how
  many requests come and go.

Two host↔device traffic rules keep the hot path hot (docs/performance.md):

* **prefix reuse** (``serve/prefix_cache.py``): ``admit`` resolves the
  longest cached prefix of the prompt, splices that B=1 KV snapshot into
  the lane, and prefills only the suffix — shared system prompts stop
  recomputing prefill;
* **on-device token selection**: the decode step returns a ``(slots,)``
  int32 token vector (in-graph argmax for greedy; in-graph ``_sample``
  walking stacked per-lane PRNG keys for temperature > 0), so the per-step
  device→host transfer is ``slots*4 + slots*8`` bytes instead of
  ``slots*vocab*4``.  Host keeps only eos/length bookkeeping.

Correctness anchor (proved in ``tests/test_serve.py``): greedy output for
any request is bit-identical to single-request
:func:`~finetune_controller_tpu.models.generate.cached_generate`, no matter
what else shares the batch.  Three properties make that hold:

* every per-row op in the decode path (matmul rows, RMSNorm, RoPE, the
  per-row-masked ``single_token_attention``) is independent of other rows;
* masked cache slots contribute exactly 0.0 to the softmax (the f32-min
  fill underflows ``exp`` to zero), so a bucketed cache length is invisible;
* the per-row cache index (``models/llama.py::_decode_attention``) lets each
  lane write and attend at its own position.

MoE configs are refused: expert-capacity routing couples rows through the
shared capacity budget, so batching invariance cannot hold there.
Multimodal configs are refused until the image prefix learns per-slot fill.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.recompile_guard import RecompileGuard
from ..models.generate import _sample
from .prefix_cache import PrefixCache, resolve_reuse_length

logger = logging.getLogger(__name__)


class PromptTooLong(ValueError):
    """Prompt exceeds the largest configured prefill bucket."""


class EngineBusy(RuntimeError):
    """No free slot (the batcher queues instead of surfacing this)."""


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Shape of the serving batch — these knobs bound the compile count."""

    #: fixed decode lanes (the physical batch); the compiled decode step
    #: always runs all of them, occupied or not
    slots: int = 8
    #: prefill pad targets, ascending; one prefill compile per bucket used
    prompt_buckets: tuple[int, ...] = (32, 128, 512)
    #: per-request cap on generated tokens; also sizes the KV cache
    max_new_tokens: int = 128
    #: byte budget for the prefix-reuse KV cache (0 = disabled): admissions
    #: whose prompt shares a cached prefix prefill only the suffix
    #: (``serve/prefix_cache.py``; ``serve_prefix_cache_mb`` in Settings)
    prefix_cache_bytes: int = 0
    #: compile budget: defaults to len(prompt_buckets) + 1 (the decode step),
    #: or 2*len(prompt_buckets) + 1 with the prefix cache on (fill AND
    #: fill_from per bucket); the guard RAISES past it — an unexpected
    #: compile on the serve path is a latency bug, not a warning
    recompile_budget: int = 0

    @property
    def cache_len(self) -> int:
        return max(self.prompt_buckets) + self.max_new_tokens

    def bucket_for(self, prompt_len: int) -> int:
        for b in self.prompt_buckets:
            if prompt_len <= b:
                return b
        raise PromptTooLong(
            f"prompt length {prompt_len} exceeds the largest prefill bucket "
            f"{max(self.prompt_buckets)}"
        )


@dataclasses.dataclass
class GenRequest:
    request_id: str
    tokens: list[int]                  # prompt token ids
    max_new_tokens: int = 32
    temperature: float = 0.0           # 0 = greedy (the bit-reproducible path)
    top_k: int = 0
    eos_id: int | None = None
    seed: int = 0                      # sampling stream (temperature > 0)


@dataclasses.dataclass
class GenResult:
    request_id: str
    prompt_tokens: list[int]
    generated: list[int]               # includes the eos token when hit
    finish_reason: str                 # "length" | "eos" | "evicted"
    steps: int                         # decode steps this request rode
    admitted_at: float = 0.0
    finished_at: float = 0.0
    #: which fleet replica decoded this request (router-annotated; "" when
    #: the engine is driven directly) — the router → replica trace hop
    replica_id: str = ""


@dataclasses.dataclass
class _Slot:
    lane: int = 0                      # this slot's row in the batch cache
    req: GenRequest | None = None
    next_pos: int = 0                  # sequence position of the token to feed
    last_token: int = 0                # token to feed at next_pos
    generated: list[int] = dataclasses.field(default_factory=list)
    rng: Any = None                    # per-request sampling stream
    admitted_at: float = 0.0

    @property
    def active(self) -> bool:
        return self.req is not None


def _batch_axis(big_shape: tuple, small_shape: tuple) -> int:
    """The axis where a B=1 prefill cache leaf maps into the slots-wide batch
    cache leaf (scanned models carry a leading layer axis, so it is not a
    fixed position)."""
    for ax, (b, s) in enumerate(zip(big_shape, small_shape)):
        if s == 1 and b > 1:
            return ax
    return 0  # shapes identical (slots == 1): write-in-place anywhere


class BatchEngine:
    """Continuous-batching decode over shared serving weights.

    Host-driven: :meth:`admit` fills a free lane, :meth:`step` advances every
    active lane one token and returns whatever finished.  The asyncio layer
    (``serve/batcher.py``) owns queuing/deadlines; this class owns device
    state and numerics.
    """

    def __init__(
        self,
        model: Any,
        variables: dict,
        config: EngineConfig | None = None,
    ):
        cfg = model.cfg
        if getattr(cfg, "n_experts", 0):
            raise ValueError(
                "BatchEngine does not serve MoE configs: expert-capacity "
                "routing couples batch rows, breaking batching invariance"
            )
        if getattr(cfg, "vision", None) is not None:
            raise ValueError("BatchEngine serves text-only models (no pixels)")
        self.config = config or EngineConfig()
        self.variables = variables
        self._dcfg = cfg.replace(
            remat=False, attention_impl="xla",
            max_seq_len=self.config.cache_len,
        )
        self._dmodel = type(model)(cfg=self._dcfg)
        self._prefix_cache = (
            PrefixCache(self.config.prefix_cache_bytes)
            if self.config.prefix_cache_bytes > 0 else None
        )
        per_bucket = 2 if self._prefix_cache is not None else 1
        budget = self.config.recompile_budget or (
            per_bucket * len(self.config.prompt_buckets) + 1
        )
        self.guard = RecompileGuard(budget, on_excess="raise",
                                    name="serve-engine")
        self._slots = [_Slot(lane=i) for i in range(self.config.slots)]
        self._cache = self._init_cache()
        # per-lane sampling streams, mirrored to the decode step as a
        # (slots, 2) uint32 leaf — rows for greedy lanes are inert
        self._rng_keys = np.zeros((self.config.slots, 2), np.uint32)
        (self._fill, self._fill_from, self._decode,
         self._insert, self._reset_lane) = self._build_fns()
        # counters the /metrics gauges read
        self.steps_total = 0
        self.tokens_generated_total = 0
        self.requests_finished_total = 0
        self.prefix_hits_total = 0
        self.prefix_misses_total = 0
        self.prefill_tokens_saved_total = 0
        self._prefix_warned = False

    # ---- jitted pieces ----------------------------------------------------

    def _init_cache(self):
        """Zero batch cache shaped by a throwaway (slots, 1) decode trace."""
        tokens = jnp.zeros((self.config.slots, 1), jnp.int32)
        _, variables = self._dmodel.apply(
            self.variables, tokens,
            positions=jnp.zeros((self.config.slots, 1), jnp.int32),
            deterministic=True, decode=True, mutable=("cache",),
        )
        return jax.tree.map(jnp.zeros_like, variables["cache"])

    def _build_fns(self) -> tuple[Callable, ...]:
        dmodel = self._dmodel

        def _index_setter(value):
            def fix(path, leaf):
                name = getattr(path[-1], "key", getattr(path[-1], "name", ""))
                return jnp.full_like(leaf, value) if name == "index" else leaf

            return fix

        @jax.jit
        def fill(variables, tokens, last_idx, true_len):
            """Prefill one request (B=1, right-padded to a bucket): logits at
            the TRUE last prompt position + a cache whose index rows read
            ``true_len`` (the model wrote the padded length)."""
            logits, updated = dmodel.apply(
                variables, tokens, deterministic=True, decode=True,
                mutable=("cache",),
            )
            cache = jax.tree_util.tree_map_with_path(
                _index_setter(true_len), updated["cache"]
            )
            return jnp.take(logits, last_idx, axis=1).astype(jnp.float32), cache

        @jax.jit
        def fill_from(variables, cache, tokens, start, last_idx, true_len):
            """Suffix prefill over a B=1 prefix snapshot: the first ``start``
            cache positions are reused as-is, the (bucket-padded) suffix
            ``tokens`` runs a chunked forward at absolute positions
            ``[start, start + bucket)``.  Returns logits at the TRUE last
            prompt position + a lane-ready cache whose index rows read
            ``true_len`` — the same contract as ``fill``, which is what makes
            a prefix hit invisible to everything downstream."""
            cache = jax.tree_util.tree_map_with_path(
                _index_setter(start), cache
            )
            positions = (
                start + jnp.arange(tokens.shape[1], dtype=jnp.int32)[None, :]
            )
            logits, updated = dmodel.apply(
                {**variables, "cache": cache}, tokens, positions=positions,
                deterministic=True, decode=True, mutable=("cache",),
            )
            cache = jax.tree_util.tree_map_with_path(
                _index_setter(true_len), updated["cache"]
            )
            return jnp.take(logits, last_idx, axis=1).astype(jnp.float32), cache

        @jax.jit
        def decode(variables, cache, tokens, positions, temps, top_ks, rngs):
            """One batched decode step with ON-DEVICE token selection: returns
            ``(slots,)`` int32 next tokens + advanced per-lane PRNG keys +
            the updated cache — the per-step device→host transfer no longer
            scales with vocab size.  Greedy lanes take the in-graph argmax;
            sampled lanes walk the SAME ``_sample`` stream a single-request
            ``cached_generate(rng=PRNGKey(seed))`` walks (scale → per-lane
            top-k mask → split → categorical), so per-request sampled decodes
            stay reproducible independent of batch-mates."""
            logits, updated = dmodel.apply(
                {**variables, "cache": cache}, tokens, positions=positions,
                deterministic=True, decode=True, mutable=("cache",),
            )
            logits = logits[:, -1].astype(jnp.float32)   # (slots, V)
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            vocab = logits.shape[-1]

            def lane_sample(lane_logits, temp, top_k, key, greedy_tok):
                # mirrors models.generate._sample with traced temp/top_k;
                # the greedy fallback keeps inactive/greedy lanes inert
                scaled = lane_logits / jnp.where(temp > 0.0, temp, 1.0)
                kth = jnp.sort(scaled)[jnp.clip(vocab - top_k, 0, vocab - 1)]
                dist = jnp.where(
                    (top_k > 0) & (scaled < kth), -jnp.inf, scaled
                )
                split = jax.random.split(key)
                tok = jax.random.categorical(split[1], dist).astype(jnp.int32)
                sampled = temp > 0.0
                return (
                    jnp.where(sampled, tok, greedy_tok),
                    jnp.where(sampled, split[0], key),
                )

            tokens_out, rngs_out = jax.lax.cond(
                jnp.any(temps > 0.0),
                lambda: jax.vmap(lane_sample)(logits, temps, top_ks, rngs,
                                              greedy),
                # all-greedy traffic skips the per-lane vocab sort entirely
                lambda: (greedy, rngs),
            )
            return tokens_out, rngs_out, updated["cache"]

        @jax.jit
        def insert(cache, one, slot):
            """Write a B=1 prefill cache into batch lane ``slot``."""

            def put(big, small):
                ax = _batch_axis(big.shape, small.shape)
                starts = [jnp.asarray(0, jnp.int32)] * big.ndim
                starts[ax] = jnp.asarray(slot, jnp.int32)
                return jax.lax.dynamic_update_slice(big, small, tuple(starts))

            return jax.tree.map(put, cache, one)

        @jax.jit
        def reset_lane(cache, slot):
            """Park a freed lane: zero its cache-index rows so the dead lane
            keeps writing its throwaway decode tokens at in-bounds positions
            (index leaves are batch-last: ``(B,)``, or ``(L, B)`` scanned)."""

            def fix(path, leaf):
                name = getattr(path[-1], "key", getattr(path[-1], "name", ""))
                return leaf.at[..., slot].set(0) if name == "index" else leaf

            return jax.tree_util.tree_map_with_path(fix, cache)

        # insert and reset_lane have exactly one signature each (the cache
        # trees are fixed-shape), so they stay outside the guard: the budget
        # counts the shapes that can vary with traffic — prefill buckets
        # (fill and fill_from) and the decode step
        return (
            self.guard.wrap(fill, "fill"),
            self.guard.wrap(fill_from, "fill_from"),
            self.guard.wrap(decode, "decode_step"),
            insert,
            reset_lane,
        )

    # ---- slot management --------------------------------------------------

    @property
    def free_slots(self) -> int:
        return sum(1 for s in self._slots if not s.active)

    @property
    def active_requests(self) -> int:
        return self.config.slots - self.free_slots

    @property
    def compilations(self) -> int:
        return self.guard.compilations

    @property
    def prefix_cache_bytes(self) -> int:
        return self._prefix_cache.total_bytes if self._prefix_cache else 0

    @property
    def prefix_cache_entries(self) -> int:
        return len(self._prefix_cache) if self._prefix_cache else 0

    def _resolve_prefix(self, tokens: list[int], plen: int):
        """Longest reusable cached prefix for ``tokens`` at bucket
        granularity; returns ``(reuse_len, snapshot)`` or ``(0, None)``."""
        match_len, snapshot = self._prefix_cache.lookup(tokens)
        if snapshot is None:
            return 0, None
        reuse = resolve_reuse_length(
            match_len, plen, self.config.prompt_buckets, self.config.cache_len
        )
        if reuse <= 0:
            return 0, None
        return reuse, snapshot

    def admit(self, req: GenRequest) -> GenResult | None:
        """Prefill ``req`` into a free lane (raises :class:`EngineBusy` when
        the batch is full, :class:`PromptTooLong` past the largest bucket).

        With the prefix cache on, the longest cached prefix of the prompt is
        spliced in and only the (bucket-padded) suffix runs a forward —
        greedy/sampled outputs stay bit-identical to the cache-off path
        because causal KV depends only on the tokens before it.

        Returns a :class:`GenResult` when the request finishes ON admission
        (its first sampled token hits eos, or ``max_new_tokens == 1``) —
        such a request never occupies a lane across a step."""
        slot_id = next(
            (i for i, s in enumerate(self._slots) if not s.active), None
        )
        if slot_id is None:
            raise EngineBusy("all decode slots are busy")
        plen = len(req.tokens)
        if plen < 1:
            raise ValueError("empty prompt")
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        cap = self.config.max_new_tokens
        if req.max_new_tokens > cap:
            raise ValueError(f"max_new_tokens {req.max_new_tokens} > engine cap {cap}")
        bucket = self.config.bucket_for(plen)
        reuse, snapshot = (
            self._resolve_prefix(req.tokens, plen)
            if self._prefix_cache is not None else (0, None)
        )
        if snapshot is not None:
            suffix = req.tokens[reuse:]
            sbucket = self.config.bucket_for(len(suffix))
            padded = np.zeros((1, sbucket), np.int32)
            padded[0, :len(suffix)] = suffix
            logits, one = self._fill_from(
                self.variables, snapshot, jnp.asarray(padded),
                jnp.asarray(reuse, jnp.int32),
                jnp.asarray(len(suffix) - 1, jnp.int32),
                jnp.asarray(plen, jnp.int32),
            )
            self.prefix_hits_total += 1
            self.prefill_tokens_saved_total += reuse
        else:
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :plen] = req.tokens
            logits, one = self._fill(
                self.variables, jnp.asarray(padded),
                jnp.asarray(plen - 1, jnp.int32), jnp.asarray(plen, jnp.int32),
            )
            if self._prefix_cache is not None:
                self.prefix_misses_total += 1
        if self._prefix_cache is not None:
            # the hit path's `one` is a full-prompt cache too, so every
            # admission leaves its prompt resolvable for the next request
            if (not self._prefix_cache.insert(tuple(req.tokens), one)
                    and not self._prefix_warned):
                self._prefix_warned = True
                logger.warning(
                    "prefix cache cannot hold a single KV snapshot (%d B > "
                    "budget %d B) — every admission will miss; raise "
                    "serve_prefix_cache_mb or disable the cache",
                    sum(x.nbytes for x in jax.tree.leaves(one)),
                    self._prefix_cache.budget_bytes,
                )
        self._cache = self._insert(self._cache, one, slot_id)
        slot = self._slots[slot_id]
        slot.req = req
        slot.generated = []
        slot.next_pos = plen
        slot.rng = jax.random.PRNGKey(req.seed)
        slot.admitted_at = time.monotonic()
        result = self._emit(slot, logits)
        if result is None and req.temperature > 0.0:
            # hand the post-first-token stream to the device-side sampler
            self._rng_keys[slot_id] = np.asarray(slot.rng, np.uint32)
        return result

    def evict(self, request_id: str) -> GenResult | None:
        """Drop an in-flight request (deadline blown / client gone); frees
        the lane immediately and parks its cache index at 0 (see
        :meth:`_finish`) — the freed lane still rides every step, decoding
        throwaway tokens at benign in-bounds positions that other rows
        never see, until re-admission overwrites it."""
        for slot in self._slots:
            if slot.active and slot.req.request_id == request_id:
                return self._finish(slot, "evicted")
        return None

    def _emit(self, slot: _Slot, logits) -> GenResult | None:
        """Select the FIRST token for a just-admitted lane from its prefill
        logits row (host-side — a B=1 admission transfer, not the per-step
        hot path, which selects on device)."""
        req = slot.req
        if req.temperature <= 0.0:
            tok = int(np.argmax(np.asarray(logits[0], np.float32)))
        else:
            # the same _sample stream a single-request cached_generate(B=1,
            # rng=PRNGKey(seed)) walks, so sampled decodes are reproducible
            # per request, independent of batch-mates
            nxt, slot.rng = _sample(
                logits[:1], temperature=req.temperature, top_k=req.top_k,
                rng=slot.rng,
            )
            tok = int(nxt[0])
        return self._record(slot, tok)

    def _record(self, slot: _Slot, tok: int) -> GenResult | None:
        """Host bookkeeping for one selected token: eos/length latching."""
        req = slot.req
        slot.generated.append(tok)
        slot.last_token = tok
        self.tokens_generated_total += 1
        if req.eos_id is not None and tok == req.eos_id:
            return self._finish(slot, "eos")
        if len(slot.generated) >= req.max_new_tokens:
            return self._finish(slot, "length")
        return None

    def _finish(self, slot: _Slot, reason: str) -> GenResult:
        req = slot.req
        result = GenResult(
            request_id=req.request_id,
            prompt_tokens=list(req.tokens),
            generated=list(slot.generated),
            finish_reason=reason,
            steps=len(slot.generated),
            admitted_at=slot.admitted_at,
            finished_at=time.monotonic(),
        )
        slot.req = None
        slot.generated = []
        slot.rng = None
        slot.last_token = 0
        slot.next_pos = 0
        # park the lane's device cache index at 0: a freed lane still rides
        # every decode step, and left at its stale position it would creep
        # toward (and past) the cache end — reset keeps its throwaway writes
        # benign and in-bounds until re-admission overwrites the lane
        self._cache = self._reset_lane(
            self._cache, jnp.asarray(slot.lane, jnp.int32)
        )
        self.requests_finished_total += 1
        return result

    # ---- the decode loop --------------------------------------------------

    def step(self) -> list[GenResult]:
        """One batched decode step; returns requests that finished on it.

        Token selection happens IN the compiled step: the host receives a
        ``(slots,)`` int32 vector (plus the advanced per-lane PRNG keys),
        never the ``(slots, vocab)`` logits array."""
        if self.active_requests == 0:
            return []
        tokens = np.zeros((self.config.slots, 1), np.int32)
        positions = np.zeros((self.config.slots, 1), np.int32)
        temps = np.zeros((self.config.slots,), np.float32)
        top_ks = np.zeros((self.config.slots,), np.int32)
        for i, slot in enumerate(self._slots):
            if slot.active:
                tokens[i, 0] = slot.last_token
                positions[i, 0] = slot.next_pos
                temps[i] = max(slot.req.temperature, 0.0)
                top_ks[i] = slot.req.top_k
        next_tokens, rng_keys, self._cache = self._decode(
            self.variables, self._cache,
            jnp.asarray(tokens), jnp.asarray(positions),
            jnp.asarray(temps), jnp.asarray(top_ks),
            jnp.asarray(self._rng_keys),
        )
        self.steps_total += 1
        next_tokens = np.asarray(next_tokens)
        # np.array (not asarray): admit() writes per-lane rows into this
        # buffer, and a zero-copy view of a jax array is read-only
        self._rng_keys = np.array(rng_keys, np.uint32)
        finished: list[GenResult] = []
        for i, slot in enumerate(self._slots):
            if not slot.active:
                continue
            slot.next_pos += 1
            done = self._record(slot, int(next_tokens[i]))
            if done is not None:
                finished.append(done)
        return finished

    def run(self, requests: list[GenRequest]) -> dict[str, GenResult]:
        """Synchronous convenience driver (tests/bench): admit everything —
        overflow waits for a lane — and step until the batch drains."""
        results: dict[str, GenResult] = {}
        pending = list(requests)
        guard_steps = itertools.count()
        limit = sum(r.max_new_tokens for r in requests) + len(requests) + 8
        while pending or self.active_requests:
            while pending and self.free_slots:
                done = self.admit(pending.pop(0))
                if done is not None:  # finished on admission (eos / max_new=1)
                    results[done.request_id] = done
            for done in self.step():
                results[done.request_id] = done
            if next(guard_steps) > limit:  # pragma: no cover - safety valve
                raise RuntimeError("engine.run failed to converge")
        missing = [r.request_id for r in requests if r.request_id not in results]
        if missing:  # pragma: no cover - engine invariant
            raise RuntimeError(f"requests did not finish: {missing}")
        return results
