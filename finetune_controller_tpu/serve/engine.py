"""Slot-based continuous-batching decode engine.

The serving core: a fixed batch of ``slots`` decode lanes runs ONE jitted
single-token step, and requests are admitted into free lanes between steps —
a new request joins mid-flight instead of waiting for the batch to drain
(the VirtualFlow idea: request slots decoupled from physical batch shape, so
traffic shape never changes the compiled program).

Compile-count contract (armed with ``analysis.recompile_guard``):

* prefill compiles once per **prompt bucket** (prompts are right-padded to
  the smallest configured bucket that fits; causality makes the pad slots
  invisible to the real tokens);
* the decode step compiles **once**, at ``(slots, 1)``, regardless of how
  many requests come and go.

Correctness anchor (proved in ``tests/test_serve.py``): greedy output for
any request is bit-identical to single-request
:func:`~finetune_controller_tpu.models.generate.cached_generate`, no matter
what else shares the batch.  Three properties make that hold:

* every per-row op in the decode path (matmul rows, RMSNorm, RoPE, the
  per-row-masked ``single_token_attention``) is independent of other rows;
* masked cache slots contribute exactly 0.0 to the softmax (the f32-min
  fill underflows ``exp`` to zero), so a bucketed cache length is invisible;
* the per-row cache index (``models/llama.py::_decode_attention``) lets each
  lane write and attend at its own position.

MoE configs are refused: expert-capacity routing couples rows through the
shared capacity budget, so batching invariance cannot hold there.
Multimodal configs are refused until the image prefix learns per-slot fill.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.recompile_guard import RecompileGuard
from ..models.generate import _sample

logger = logging.getLogger(__name__)


class PromptTooLong(ValueError):
    """Prompt exceeds the largest configured prefill bucket."""


class EngineBusy(RuntimeError):
    """No free slot (the batcher queues instead of surfacing this)."""


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Shape of the serving batch — these knobs bound the compile count."""

    #: fixed decode lanes (the physical batch); the compiled decode step
    #: always runs all of them, occupied or not
    slots: int = 8
    #: prefill pad targets, ascending; one prefill compile per bucket used
    prompt_buckets: tuple[int, ...] = (32, 128, 512)
    #: per-request cap on generated tokens; also sizes the KV cache
    max_new_tokens: int = 128
    #: compile budget: defaults to len(prompt_buckets) + 1 (the decode step);
    #: the guard RAISES past it — an unexpected compile on the serve path is
    #: a latency bug, not a warning
    recompile_budget: int = 0

    @property
    def cache_len(self) -> int:
        return max(self.prompt_buckets) + self.max_new_tokens

    def bucket_for(self, prompt_len: int) -> int:
        for b in self.prompt_buckets:
            if prompt_len <= b:
                return b
        raise PromptTooLong(
            f"prompt length {prompt_len} exceeds the largest prefill bucket "
            f"{max(self.prompt_buckets)}"
        )


@dataclasses.dataclass
class GenRequest:
    request_id: str
    tokens: list[int]                  # prompt token ids
    max_new_tokens: int = 32
    temperature: float = 0.0           # 0 = greedy (the bit-reproducible path)
    top_k: int = 0
    eos_id: int | None = None
    seed: int = 0                      # sampling stream (temperature > 0)


@dataclasses.dataclass
class GenResult:
    request_id: str
    prompt_tokens: list[int]
    generated: list[int]               # includes the eos token when hit
    finish_reason: str                 # "length" | "eos" | "evicted"
    steps: int                         # decode steps this request rode
    admitted_at: float = 0.0
    finished_at: float = 0.0


@dataclasses.dataclass
class _Slot:
    req: GenRequest | None = None
    next_pos: int = 0                  # sequence position of the token to feed
    last_token: int = 0                # token to feed at next_pos
    generated: list[int] = dataclasses.field(default_factory=list)
    rng: Any = None                    # per-request sampling stream
    admitted_at: float = 0.0

    @property
    def active(self) -> bool:
        return self.req is not None


def _batch_axis(big_shape: tuple, small_shape: tuple) -> int:
    """The axis where a B=1 prefill cache leaf maps into the slots-wide batch
    cache leaf (scanned models carry a leading layer axis, so it is not a
    fixed position)."""
    for ax, (b, s) in enumerate(zip(big_shape, small_shape)):
        if s == 1 and b > 1:
            return ax
    return 0  # shapes identical (slots == 1): write-in-place anywhere


class BatchEngine:
    """Continuous-batching decode over shared serving weights.

    Host-driven: :meth:`admit` fills a free lane, :meth:`step` advances every
    active lane one token and returns whatever finished.  The asyncio layer
    (``serve/batcher.py``) owns queuing/deadlines; this class owns device
    state and numerics.
    """

    def __init__(
        self,
        model: Any,
        variables: dict,
        config: EngineConfig | None = None,
    ):
        cfg = model.cfg
        if getattr(cfg, "n_experts", 0):
            raise ValueError(
                "BatchEngine does not serve MoE configs: expert-capacity "
                "routing couples batch rows, breaking batching invariance"
            )
        if getattr(cfg, "vision", None) is not None:
            raise ValueError("BatchEngine serves text-only models (no pixels)")
        self.config = config or EngineConfig()
        self.variables = variables
        self._dcfg = cfg.replace(
            remat=False, attention_impl="xla",
            max_seq_len=self.config.cache_len,
        )
        self._dmodel = type(model)(cfg=self._dcfg)
        budget = self.config.recompile_budget or (
            len(self.config.prompt_buckets) + 1
        )
        self.guard = RecompileGuard(budget, on_excess="raise",
                                    name="serve-engine")
        self._slots = [_Slot() for _ in range(self.config.slots)]
        self._cache = self._init_cache()
        self._fill, self._decode, self._insert = self._build_fns()
        # counters the /metrics gauges read
        self.steps_total = 0
        self.tokens_generated_total = 0
        self.requests_finished_total = 0

    # ---- jitted pieces ----------------------------------------------------

    def _init_cache(self):
        """Zero batch cache shaped by a throwaway (slots, 1) decode trace."""
        tokens = jnp.zeros((self.config.slots, 1), jnp.int32)
        _, variables = self._dmodel.apply(
            self.variables, tokens,
            positions=jnp.zeros((self.config.slots, 1), jnp.int32),
            deterministic=True, decode=True, mutable=("cache",),
        )
        return jax.tree.map(jnp.zeros_like, variables["cache"])

    def _build_fns(self) -> tuple[Callable, Callable, Callable]:
        dmodel = self._dmodel

        @jax.jit
        def fill(variables, tokens, last_idx, true_len):
            """Prefill one request (B=1, right-padded to a bucket): logits at
            the TRUE last prompt position + a cache whose index rows read
            ``true_len`` (the model wrote the padded length)."""
            logits, updated = dmodel.apply(
                variables, tokens, deterministic=True, decode=True,
                mutable=("cache",),
            )
            def fix_index(path, leaf):
                name = getattr(path[-1], "key", getattr(path[-1], "name", ""))
                return jnp.full_like(leaf, true_len) if name == "index" else leaf

            cache = jax.tree_util.tree_map_with_path(
                fix_index, updated["cache"]
            )
            return jnp.take(logits, last_idx, axis=1).astype(jnp.float32), cache

        @jax.jit
        def decode(variables, cache, tokens, positions):
            logits, updated = dmodel.apply(
                {**variables, "cache": cache}, tokens, positions=positions,
                deterministic=True, decode=True, mutable=("cache",),
            )
            return logits[:, -1].astype(jnp.float32), updated["cache"]

        @jax.jit
        def insert(cache, one, slot):
            """Write a B=1 prefill cache into batch lane ``slot``."""

            def put(big, small):
                ax = _batch_axis(big.shape, small.shape)
                starts = [jnp.asarray(0, jnp.int32)] * big.ndim
                starts[ax] = jnp.asarray(slot, jnp.int32)
                return jax.lax.dynamic_update_slice(big, small, tuple(starts))

            return jax.tree.map(put, cache, one)

        # insert has exactly one signature (the cache trees are fixed-shape),
        # so it stays outside the guard: the budget counts the shapes that
        # can vary with traffic — prefill buckets and the decode step
        return (
            self.guard.wrap(fill, "fill"),
            self.guard.wrap(decode, "decode_step"),
            insert,
        )

    # ---- slot management --------------------------------------------------

    @property
    def free_slots(self) -> int:
        return sum(1 for s in self._slots if not s.active)

    @property
    def active_requests(self) -> int:
        return self.config.slots - self.free_slots

    @property
    def compilations(self) -> int:
        return self.guard.compilations

    def admit(self, req: GenRequest) -> GenResult | None:
        """Prefill ``req`` into a free lane (raises :class:`EngineBusy` when
        the batch is full, :class:`PromptTooLong` past the largest bucket).

        Returns a :class:`GenResult` when the request finishes ON admission
        (its first sampled token hits eos, or ``max_new_tokens == 1``) —
        such a request never occupies a lane across a step."""
        slot_id = next(
            (i for i, s in enumerate(self._slots) if not s.active), None
        )
        if slot_id is None:
            raise EngineBusy("all decode slots are busy")
        plen = len(req.tokens)
        if plen < 1:
            raise ValueError("empty prompt")
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        cap = self.config.max_new_tokens
        if req.max_new_tokens > cap:
            raise ValueError(f"max_new_tokens {req.max_new_tokens} > engine cap {cap}")
        bucket = self.config.bucket_for(plen)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :plen] = req.tokens
        logits, one = self._fill(
            self.variables, jnp.asarray(padded),
            jnp.asarray(plen - 1, jnp.int32), jnp.asarray(plen, jnp.int32),
        )
        self._cache = self._insert(self._cache, one, slot_id)
        slot = self._slots[slot_id]
        slot.req = req
        slot.generated = []
        slot.next_pos = plen
        slot.rng = jax.random.PRNGKey(req.seed)
        slot.admitted_at = time.monotonic()
        return self._emit(slot, logits)

    def evict(self, request_id: str) -> GenResult | None:
        """Drop an in-flight request (deadline blown / client gone); frees
        the lane immediately — the next :meth:`step` simply decodes garbage
        into it until re-admission, which other rows never see."""
        for slot in self._slots:
            if slot.active and slot.req.request_id == request_id:
                return self._finish(slot, "evicted")
        return None

    def _emit(self, slot: _Slot, logits) -> GenResult | None:
        """Sample the next token for one lane from its logits row."""
        req = slot.req
        if req.temperature <= 0.0:
            tok = int(np.argmax(np.asarray(logits[0], np.float32)))
        else:
            # the same _sample stream a single-request cached_generate(B=1,
            # rng=PRNGKey(seed)) walks, so sampled decodes are reproducible
            # per request, independent of batch-mates
            nxt, slot.rng = _sample(
                logits[:1], temperature=req.temperature, top_k=req.top_k,
                rng=slot.rng,
            )
            tok = int(nxt[0])
        slot.generated.append(tok)
        slot.last_token = tok
        self.tokens_generated_total += 1
        if req.eos_id is not None and tok == req.eos_id:
            return self._finish(slot, "eos")
        if len(slot.generated) >= req.max_new_tokens:
            return self._finish(slot, "length")
        return None

    def _finish(self, slot: _Slot, reason: str) -> GenResult:
        req = slot.req
        result = GenResult(
            request_id=req.request_id,
            prompt_tokens=list(req.tokens),
            generated=list(slot.generated),
            finish_reason=reason,
            steps=len(slot.generated),
            admitted_at=slot.admitted_at,
            finished_at=time.monotonic(),
        )
        slot.req = None
        slot.generated = []
        slot.rng = None
        self.requests_finished_total += 1
        return result

    # ---- the decode loop --------------------------------------------------

    def step(self) -> list[GenResult]:
        """One batched decode step; returns requests that finished on it."""
        if self.active_requests == 0:
            return []
        tokens = np.zeros((self.config.slots, 1), np.int32)
        positions = np.zeros((self.config.slots, 1), np.int32)
        for i, slot in enumerate(self._slots):
            if slot.active:
                tokens[i, 0] = slot.last_token
                positions[i, 0] = slot.next_pos
        logits, self._cache = self._decode(
            self.variables, self._cache,
            jnp.asarray(tokens), jnp.asarray(positions),
        )
        self.steps_total += 1
        host_logits = None
        finished: list[GenResult] = []
        for i, slot in enumerate(self._slots):
            if not slot.active:
                continue
            slot.next_pos += 1
            if slot.req.temperature <= 0.0:
                if host_logits is None:
                    host_logits = np.asarray(logits, np.float32)
                row = host_logits[i:i + 1]
            else:
                row = logits[i:i + 1]
            done = self._emit(slot, row)
            if done is not None:
                finished.append(done)
        return finished

    def run(self, requests: list[GenRequest]) -> dict[str, GenResult]:
        """Synchronous convenience driver (tests/bench): admit everything —
        overflow waits for a lane — and step until the batch drains."""
        results: dict[str, GenResult] = {}
        pending = list(requests)
        guard_steps = itertools.count()
        limit = sum(r.max_new_tokens for r in requests) + len(requests) + 8
        while pending or self.active_requests:
            while pending and self.free_slots:
                done = self.admit(pending.pop(0))
                if done is not None:  # finished on admission (eos / max_new=1)
                    results[done.request_id] = done
            for done in self.step():
                results[done.request_id] = done
            if next(guard_steps) > limit:  # pragma: no cover - safety valve
                raise RuntimeError("engine.run failed to converge")
        missing = [r.request_id for r in requests if r.request_id not in results]
        if missing:  # pragma: no cover - engine invariant
            raise RuntimeError(f"requests did not finish: {missing}")
        return results
