"""Block-granular KV page pool for the serve engine (docs/serving.md §Paged KV).

The PR-4/6 engine reserves a full ``cache_len`` KV lane per decode slot at
admit time, so HBM — not compute — caps concurrency: a 12-token request holds
the same bytes as a 640-token one.  This module is the vLLM-style answer
(PAPERS.md): the device KV cache becomes a pool of fixed-size pages
(``page_tokens`` sequence positions each, all layers of one page id move
together) and every lane holds only the pages its tokens actually occupy,
growing page-by-page as it decodes.

This class is the HOST-side allocator and accountant; the device arrays live
in the engine's flax ``cache`` collection (``models/llama.py`` paged branch)
and are addressed through per-lane page tables the engine passes into every
jitted call.  Single-threaded by contract — the batcher's drive loop already
serializes every engine call that touches it.

Page id 0 is the SCRATCH page: parked lanes and unmaterialized page-table
slots point at it, so their throwaway writes land somewhere harmlessly
in-bounds (reads of scratch positions are always masked to an exact-zero
softmax contribution).  It is never allocated.

Reference counting, because pages are shared copy-on-write:

* ``lane_refs`` — decode lanes holding the page (a prefix-cache splice refs
  the shared whole pages; a lane only ever WRITES pages it created itself,
  never shared ones — the page containing the reuse boundary is copied);
* ``cache_refs`` — prefix-cache entries holding the page (one count per
  entry; byte accounting charges a page once, on 0→1).

A page returns to the free list when both hit zero.

Admission control, so growth can never OOM mid-flight: ``reserve`` books the
worst case pages a request could still need (``ceil((prompt + max_new - 1) /
page_tokens)`` minus what it shares) before the lane is admitted, and
``alloc_reserved`` spends reservations one page at a time as the lane grows.
``slack`` counts free pages plus cache-only pages (evictable on demand) minus
outstanding reservations — the invariant ``slack >= 0`` means a reserved
page can always be materialized, evicting least-recently-used prefix-cache
entries if the free list is momentarily empty.
"""

from __future__ import annotations

import dataclasses

import numpy as np


class PoolExhausted(RuntimeError):
    """Not enough free/evictable pages to admit this request NOW — transient
    backpressure (the batcher keeps it queued; a full queue becomes a 429
    with a derived ``Retry-After``), never an OOM mid-decode."""


@dataclasses.dataclass(frozen=True)
class PageRun:
    """A prefix-cache entry's pages, in logical order: page ``i`` holds
    sequence positions ``[i*page_tokens, (i+1)*page_tokens)`` of the prompt
    whose key the entry is stored under."""

    pages: tuple[int, ...]
    n_tokens: int


@dataclasses.dataclass(frozen=True)
class HostRun:
    """A demoted prefix-cache entry: the same logical page run as a
    :class:`PageRun`, but the KV bytes live in :class:`HostPagePool` slots
    instead of device pool pages.  Self-contained by construction — demotion
    snapshots EVERY page of the run (shared ones included), so restoring
    never depends on pages other entries or lanes still hold."""

    slots: tuple[int, ...]
    n_tokens: int


class HostPagePool:
    """Host-RAM page tier behind the device :class:`KVPagePool`
    (docs/serving.md §KV tiering).

    A flat pool of ``capacity = budget_bytes // page_bytes`` host page
    slots.  Storage is one pinned numpy buffer per K/V cache leaf, shaped
    ``(capacity,) + page_slice_shape`` and allocated lazily on the first
    demotion (the engine defines the leaf set; this class only needs the
    bytes to land somewhere stable and reusable).  Single-threaded by the
    same contract as the device pool — the batcher's drive loop serializes
    every caller.

    The unit of transfer is one whole page id across every layer's K and V
    leaves — exactly the device pool's accounting unit, so device and host
    byte budgets (``serve_prefix_cache_mb`` vs ``serve_kv_host_pool_mb``)
    are directly comparable.
    """

    def __init__(self, budget_bytes: int, page_bytes: int):
        if page_bytes <= 0:
            raise ValueError("HostPagePool needs a positive page_bytes")
        self.page_bytes = int(page_bytes)
        self.capacity = max(0, int(budget_bytes) // self.page_bytes)
        # ascending hand-out order, like the device pool: deterministic slot
        # reuse keeps demote/restore tests reproducible
        self._free = list(range(self.capacity - 1, -1, -1))
        #: per-leaf pinned buffers, keyed by leaf ordinal in the engine's
        #: fixed K/V traversal order; created on first write
        self._buffers: list[np.ndarray] | None = None
        # counters for /metrics + tests (units: PAGES moved, not calls)
        self.demotions_total = 0
        self.restores_total = 0

    # ---- accounting -------------------------------------------------------

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.capacity - len(self._free)

    def can_hold(self, n: int) -> bool:
        return n <= len(self._free)

    # ---- slot lifecycle ---------------------------------------------------

    def alloc(self, n: int) -> list[int]:
        """Take ``n`` host slots (caller checks :meth:`can_hold` first — a
        full host tier is a soft condition, the entry just stays on
        device)."""
        if n > len(self._free):
            raise PoolExhausted(
                f"host kv tier exhausted: need {n} slot(s), "
                f"free {len(self._free)} of {self.capacity}"
            )
        return [self._free.pop() for _ in range(n)]

    def free(self, slots) -> None:
        for slot in slots:
            assert 0 <= slot < self.capacity, f"bad host slot {slot}"
            self._free.append(slot)
        assert len(self._free) <= self.capacity, "host slot accounting broke"

    # ---- page bytes -------------------------------------------------------

    def write(self, slot: int, pages: list[np.ndarray]) -> None:
        """Store one device page's per-leaf slices into host ``slot``."""
        if self._buffers is None:
            self._buffers = [
                np.empty((self.capacity,) + np.shape(p), p.dtype)
                for p in pages
            ]
        for buf, page in zip(self._buffers, pages):
            buf[slot] = page

    def read(self, slot: int) -> list[np.ndarray]:
        """The per-leaf page slices stored in ``slot`` (same order as the
        :meth:`write` that filled it)."""
        assert self._buffers is not None, "read before any write"
        return [buf[slot] for buf in self._buffers]

    # ---- observability ----------------------------------------------------

    def stats(self) -> dict[str, int]:
        return {
            "tier_host_pages_total": self.capacity,
            "tier_host_pages_used": self.used_count,
            "tier_host_bytes": self.used_count * self.page_bytes,
            "demotions_total": self.demotions_total,
            "restores_total": self.restores_total,
        }


class KVPagePool:
    """Free-list allocator + refcounts over ``num_pages`` device pages.

    ``page_bytes`` is the physical size of one page id across every layer's
    K and V pool leaves — the unit the prefix cache's physical-byte LRU and
    the ``ftc_serve_kv_pages_*`` gauges account in.
    """

    SCRATCH = 0

    def __init__(self, num_pages: int, page_tokens: int, page_bytes: int = 0):
        if num_pages < 2:
            raise ValueError("KVPagePool needs >= 2 pages (page 0 is scratch)")
        if page_tokens < 1:
            raise ValueError("page_tokens must be >= 1")
        self.num_pages = int(num_pages)
        self.page_tokens = int(page_tokens)
        self.page_bytes = int(page_bytes)
        # pop() hands out ascending ids — deterministic allocation order is
        # what makes evict-refill reuse tests (and failures) reproducible
        self._free = list(range(self.num_pages - 1, 0, -1))
        self._lane_refs = [0] * self.num_pages
        self._cache_refs = [0] * self.num_pages
        #: pages held ONLY by prefix-cache entries — evictable on demand, so
        #: they count toward admission slack
        self._cache_only = 0
        #: reserved-but-unmaterialized pages across all admitted lanes
        self.reserved_outstanding = 0
        # counters for /metrics + tests
        self.allocs_total = 0
        self.cow_copies_total = 0
        self.exhaustions_total = 0

    # ---- accounting -------------------------------------------------------

    @property
    def usable_pages(self) -> int:
        return self.num_pages - 1

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.usable_pages - len(self._free)

    @property
    def shared_count(self) -> int:
        """Pages with more than one holder (lanes + cache entries) — the
        copy-on-write savings gauge."""
        return sum(
            1 for p in range(1, self.num_pages)
            if self._lane_refs[p] + self._cache_refs[p] >= 2
        )

    def slack(self) -> int:
        """Pages still promisable to a new admission: free + evictable
        cache-only, minus reservations already promised to admitted lanes."""
        return len(self._free) + self._cache_only - self.reserved_outstanding

    def pages_for(self, n_tokens: int) -> int:
        return -(-max(0, int(n_tokens)) // self.page_tokens)

    def can_reserve(self, n: int) -> bool:
        return n <= self.slack()

    # ---- lane side --------------------------------------------------------

    def reserve(self, n: int) -> None:
        """Book ``n`` pages for a lane being admitted (raises
        :class:`PoolExhausted` past the slack)."""
        if n > self.slack():
            self.exhaustions_total += 1
            raise PoolExhausted(
                f"kv page pool exhausted: need {n} page(s), "
                f"slack {self.slack()} (free {len(self._free)}, "
                f"evictable {self._cache_only}, "
                f"reserved {self.reserved_outstanding})"
            )
        self.reserved_outstanding += n

    def unreserve(self, n: int) -> None:
        self.reserved_outstanding -= n
        assert self.reserved_outstanding >= 0, "reservation accounting broke"

    def alloc_reserved(self, evict_one=None) -> int:
        """Materialize one previously reserved page.  When the free list is
        empty, ``evict_one()`` (the engine's hook into the prefix cache's
        LRU) is called until a cache-only page frees — guaranteed to
        terminate by the ``slack`` invariant."""
        while not self._free:
            if evict_one is None or not evict_one():
                raise RuntimeError(
                    "kv page pool invariant broken: a reserved page could "
                    "not be materialized (free list empty, nothing evictable)"
                )
        page = self._free.pop()
        self._lane_refs[page] = 1
        self.reserved_outstanding -= 1
        assert self.reserved_outstanding >= 0, "reservation accounting broke"
        self.allocs_total += 1
        return page

    def lane_ref(self, page: int) -> None:
        """A lane takes a read-only share of an existing page (prefix
        splice)."""
        assert page != self.SCRATCH
        if self._lane_refs[page] == 0 and self._cache_refs[page] > 0:
            self._cache_only -= 1
        self._lane_refs[page] += 1

    def lane_release(self, pages, unused_reserved: int = 0) -> None:
        """Lane finished/evicted: drop its refs and return its unspent
        reservation."""
        for page in pages:
            if page == self.SCRATCH:
                continue
            self._lane_refs[page] -= 1
            assert self._lane_refs[page] >= 0, f"lane ref underflow p{page}"
            if self._lane_refs[page] == 0:
                if self._cache_refs[page] > 0:
                    self._cache_only += 1
                else:
                    self._free.append(page)
        if unused_reserved:
            self.unreserve(unused_reserved)

    # ---- prefix-cache side ------------------------------------------------

    def cache_ref(self, pages) -> int:
        """A prefix-cache entry takes refs on ``pages``; returns how many
        became cache-referenced for the FIRST time — the entry's physical
        byte charge is that count times ``page_bytes`` (shared pages are
        charged once, on their first referencing entry)."""
        newly = 0
        for page in pages:
            assert page != self.SCRATCH
            self._cache_refs[page] += 1
            if self._cache_refs[page] == 1:
                newly += 1
                if self._lane_refs[page] == 0:
                    self._cache_only += 1
        return newly

    def cache_release(self, pages) -> int:
        """Inverse of :meth:`cache_ref`; returns how many pages dropped their
        LAST cache reference (the byte credit)."""
        freed = 0
        for page in pages:
            self._cache_refs[page] -= 1
            assert self._cache_refs[page] >= 0, f"cache ref underflow p{page}"
            if self._cache_refs[page] == 0:
                freed += 1
                if self._lane_refs[page] == 0:
                    self._cache_only -= 1
                    self._free.append(page)
        return freed

    # ---- observability ----------------------------------------------------

    def stats(self) -> dict[str, int]:
        return {
            "pages_total": self.usable_pages,
            "pages_free": self.free_count,
            "pages_used": self.used_count,
            "pages_shared": self.shared_count,
            "pages_reserved": self.reserved_outstanding,
            "page_tokens": self.page_tokens,
            "page_bytes": self.page_bytes,
            "page_allocs_total": self.allocs_total,
            "cow_copies_total": self.cow_copies_total,
            "pool_exhaustions_total": self.exhaustions_total,
        }
