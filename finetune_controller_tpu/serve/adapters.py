"""Multi-tenant LoRA adapter registry (docs/serving.md §Multi-tenant adapters).

The millions-of-users move (ROADMAP item 2): instead of one merged-weights
replica set per promoted job, ONE base-model fleet serves N fine-tuned
tenants by keeping the adapters unmerged — every LoRA-targeted projection
carries stacked per-tenant ``(A, B, alpha/rank)`` tensors in the model's
``"tenants"`` collection (``models/lora.py``), and the decode step applies
each lane's adapter through a gathered batched einsum over the per-row
``adapter_ids`` vector (the same per-row trick as the PR-4 cache index).

This module owns the host side: slot assignment (slot 0 is the base model —
an all-zero stack whose delta is an exact 0.0), rank padding (tenants train
at different ranks; smaller ones zero-pad to the stack rank, which is
bit-neutral), and the functional device writes that install or clear one
tenant's slot in an engine's tenants tree.  Stacks are FIXED capacity
(``serve_max_adapters``), so registering a tenant is a device write, never a
shape change — the decode step never recompiles for tenant churn.

One registry serves a whole replica fleet; each replica engine holds its own
device copy of the stacks and is synced by the fleet on register/unregister,
spawn, and rollover (``serve/fleet.py``).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any

import numpy as np

logger = logging.getLogger(__name__)


class AdapterError(ValueError):
    """Registration refused (capacity, rank, shape mismatch)."""


class UnknownAdapter(ValueError):
    """A request named an adapter this registry has not loaded."""


@dataclasses.dataclass
class AdapterEntry:
    adapter_id: str
    slot: int
    tree: Any                     # host-side lora collection pytree
    alpha: float
    rank: int
    meta: dict[str, Any] = dataclasses.field(default_factory=dict)


def _leaf_name(path) -> str:
    last = path[-1]
    return getattr(last, "key", getattr(last, "name", ""))


def _subtree(tree: Any, path) -> Any:
    """Follow a tree_map_with_path prefix into ``tree`` (None when absent)."""
    node = tree
    for part in path:
        key = getattr(part, "key", getattr(part, "name", ""))
        if not isinstance(node, dict) or key not in node:
            return None
        node = node[key]
    return node


def install_into(tenants: Any, slot: int, adapter_tree: Any | None,
                 alpha: float, rank: int) -> Any:
    """Write one tenant's (rank-padded) adapter into stack slot ``slot`` of a
    device tenants tree; ``adapter_tree=None`` clears the slot (zero stack,
    scale 0).  Functional — returns the new tree; callers swap the engine's
    reference atomically so an in-flight decode step keeps its snapshot.

    Stack leaves carry the tenant axis at ``ndim - 3`` for ``lora_a``
    (N, in, R) / ``lora_b`` (N, R, out) and ``ndim - 1`` for ``scale`` (N,),
    with scanned models adding a leading layer axis to each.  Projections
    the adapter does not target stay zero — their delta is an exact 0.0.
    """
    import jax

    def fix(path, stack):
        name = _leaf_name(path)
        if name not in ("lora_a", "lora_b", "scale"):  # pragma: no cover
            return stack
        if name == "scale":
            value = (alpha / rank) if adapter_tree is not None else 0.0
            return stack.at[..., slot].set(np.asarray(value, stack.dtype))
        n_axis = stack.ndim - 3
        slot_shape = stack.shape[:n_axis] + stack.shape[n_axis + 1:]
        padded = np.zeros(slot_shape, np.float32)
        leaf = None
        if adapter_tree is not None:
            sub = _subtree(adapter_tree, path[:-1])
            leaf = sub.get(name) if isinstance(sub, dict) else None
        if leaf is not None:
            leaf = np.asarray(leaf, np.float32)
            try:
                if name == "lora_a":     # (..., in, r) -> (..., in, R)
                    padded[..., : leaf.shape[-1]] = leaf
                else:                    # (..., r, out) -> (..., R, out)
                    padded[..., : leaf.shape[-2], :] = leaf
            except (ValueError, IndexError) as e:
                raise AdapterError(
                    f"adapter leaf {'/'.join(str(getattr(p, 'key', p)) for p in path)} "
                    f"shape {leaf.shape} does not fit stack slot {slot_shape} "
                    f"(wrong base model or rank > stack rank?): {e}"
                ) from None
        index = (slice(None),) * n_axis + (slot,)
        return stack.at[index].set(padded.astype(stack.dtype))

    return jax.tree_util.tree_map_with_path(fix, tenants)


class AdapterRegistry:
    """Slot assignment + host copies for one served base model.

    ``capacity`` counts stack slots INCLUDING the reserved base slot 0, so a
    registry built from ``serve_max_adapters=4`` has capacity 5.
    """

    def __init__(self, capacity: int, max_rank: int):
        if capacity < 2:
            raise ValueError("adapter registry needs capacity >= 2 "
                             "(slot 0 is the base model)")
        if max_rank < 1:
            raise ValueError("adapter stack rank must be >= 1")
        self.capacity = int(capacity)
        self.max_rank = int(max_rank)
        self._entries: dict[str, AdapterEntry] = {}
        self._free_slots = list(range(self.capacity - 1, 0, -1))

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def free_slots(self) -> int:
        return len(self._free_slots)

    def resolve(self, adapter_id: str) -> int:
        """Stack slot for ``adapter_id`` ('' = the base model, slot 0)."""
        if not adapter_id:
            return 0
        entry = self._entries.get(adapter_id)
        if entry is None:
            raise UnknownAdapter(
                f"adapter {adapter_id!r} is not loaded on this fleet "
                f"(loaded: {sorted(self._entries) or 'none'})"
            )
        return entry.slot

    def get(self, adapter_id: str) -> AdapterEntry | None:
        return self._entries.get(adapter_id)

    def entries(self) -> list[AdapterEntry]:
        return sorted(self._entries.values(), key=lambda e: e.slot)

    def register(self, adapter_id: str, lora_tree: Any, alpha: float,
                 rank: int, meta: dict[str, Any] | None = None) -> AdapterEntry:
        """Assign (or re-use, for a tenant checkpoint rollover) a slot and
        record the host copy.  Device installation is the fleet's job —
        every replica engine applies :func:`install_into` with this entry."""
        if not adapter_id:
            raise AdapterError("adapter id must be non-empty")
        if rank < 1 or rank > self.max_rank:
            raise AdapterError(
                f"adapter rank {rank} outside [1, {self.max_rank}] "
                f"(raise serve_adapter_rank to stack higher ranks)"
            )
        existing = self._entries.get(adapter_id)
        if existing is not None:
            slot = existing.slot  # in-place refresh: tenant rollover
        elif self._free_slots:
            slot = self._free_slots.pop()
        else:
            raise AdapterError(
                f"adapter registry full ({self.capacity - 1} tenant slots); "
                "unload an adapter or raise serve_max_adapters"
            )
        entry = AdapterEntry(
            adapter_id=adapter_id, slot=slot, tree=lora_tree,
            alpha=float(alpha), rank=int(rank), meta=dict(meta or {}),
        )
        self._entries[adapter_id] = entry
        logger.info("adapter %s registered in slot %d (rank %d, alpha %s)",
                    adapter_id, slot, rank, alpha)
        return entry

    def unregister(self, adapter_id: str) -> AdapterEntry:
        entry = self._entries.pop(adapter_id, None)
        if entry is None:
            raise UnknownAdapter(f"adapter {adapter_id!r} is not loaded")
        self._free_slots.append(entry.slot)
        logger.info("adapter %s unregistered (slot %d freed)",
                    adapter_id, entry.slot)
        return entry

    def stats(self) -> dict[str, Any]:
        return {
            "capacity": self.capacity - 1,
            "loaded": len(self._entries),
            "adapters": {
                e.adapter_id: {"slot": e.slot, "rank": e.rank,
                               "alpha": e.alpha, **e.meta}
                for e in self.entries()
            },
        }


# ---------------------------------------------------------------------------
# Wire serialization (transport/: the registry-sync RPC)
# ---------------------------------------------------------------------------


def entry_to_wire(entry: AdapterEntry) -> dict[str, Any]:
    """Serialize one registry entry for the worker stack-sync RPC
    (docs/serving.md §Cross-process transport).  The adapter tree rides as a
    flax msgpack blob — megabytes of deltas, never model weights."""
    from flax import serialization

    host_tree = _to_host(entry.tree)
    return {
        "adapter_id": entry.adapter_id,
        "alpha": float(entry.alpha),
        "rank": int(entry.rank),
        "meta": dict(entry.meta),
        "tree": serialization.msgpack_serialize(host_tree),
    }


def entry_from_wire(doc: dict[str, Any]) -> tuple[str, Any, float, int, dict]:
    """Inverse of :func:`entry_to_wire` → ``(adapter_id, tree, alpha, rank,
    meta)``, the :meth:`AdapterRegistry.register` argument shape."""
    from flax import serialization

    tree = serialization.msgpack_restore(doc["tree"])
    return (
        str(doc["adapter_id"]), tree, float(doc["alpha"]), int(doc["rank"]),
        dict(doc.get("meta") or {}),
    )


def _to_host(tree: Any) -> Any:
    """Device arrays → numpy (msgpack_serialize refuses jax.Array leaves)."""
    import jax

    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)
