"""HTTP serving surface, mounted on the controller server.

Routes (``controller/server.py::build_app`` mounts them; the app's auth/CORS
middlewares apply — a bearer token that can read a job can generate from it):

* ``POST {prefix}/jobs/{job_id}/generate`` — generate from a promoted job's
  checkpoint (auto-loads on first use when ``serve_autoload`` is on);
* ``POST {prefix}/admin/serve/{job_id}/load`` / ``.../unload`` — explicit
  model lifecycle (admin);
* ``GET {prefix}/admin/serve`` — per-model engine/batcher stats (admin).

The manager refuses jobs whose promotion is not COMPLETED
(``serve/loader.py::resolve_promoted``) — serving a half-copied or deleted
deploy prefix would decode garbage with a 200 status.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import time
import uuid
from pathlib import Path
from typing import Any

from aiohttp import web

from .batcher import Batcher, DeadlineExceeded, QueueFull
from .engine import BatchEngine, EngineConfig, GenRequest, GenResult, PromptTooLong
from .loader import ServeLoadError, load_promoted

logger = logging.getLogger(__name__)

SERVE_KEY = web.AppKey("serve", object)


@dataclasses.dataclass
class _Session:
    job_id: str
    batcher: Batcher
    meta: dict[str, Any]
    loaded_at: float


class ServeManager:
    """Loaded serving sessions, one engine+batcher per promoted job."""

    def __init__(self, state, store, settings, *, obs=None):
        self.state = state
        self.store = store
        self.settings = settings
        #: observability hub (obs/prom.py): serve TTFT histogram + timeline
        #: events on load/unload (docs/observability.md)
        self.obs = obs
        self.sessions: dict[str, _Session] = {}
        self._load_lock = asyncio.Lock()
        self.work_dir = Path(settings.state_path) / "serve_cache"

    async def _event(self, job_id: str, event: str, **attrs) -> None:
        from ..obs.events import append_event_safe

        await append_event_safe(self.state, job_id, event, **attrs)

    def _engine_config(self) -> EngineConfig:
        s = self.settings
        return EngineConfig(
            slots=s.serve_slots,
            prompt_buckets=tuple(s.serve_prompt_buckets),
            max_new_tokens=s.serve_max_new_tokens,
            prefix_cache_bytes=(
                int(s.serve_prefix_cache_mb) * (1 << 20)
                if s.serve_prefix_cache else 0
            ),
        )

    async def load(self, job_id: str) -> dict[str, Any]:
        """Idempotent: returns the existing session's meta when loaded."""
        existing = self.sessions.get(job_id)
        if existing is not None:
            return existing.meta
        async with self._load_lock:  # single-flight per manager
            existing = self.sessions.get(job_id)
            if existing is not None:
                return existing.meta
            model, variables, meta = await load_promoted(
                self.state, self.store, job_id, self.work_dir,
                merge_lora=self.settings.serve_merge_lora,
            )
            # engine construction traces a forward to shape the batch cache —
            # device work that must not run on the event loop
            engine = await asyncio.to_thread(
                BatchEngine, model, variables, self._engine_config()
            )
            batcher = Batcher(
                engine,
                max_queue=self.settings.serve_max_queue,
                max_wait_ms=self.settings.serve_max_wait_ms,
                default_timeout_s=self.settings.serve_request_timeout_s,
                ttft_observe=(
                    self.obs.serve_ttft_seconds.observe
                    if self.obs is not None else None
                ),
            )
            self.sessions[job_id] = _Session(
                job_id=job_id, batcher=batcher, meta=meta,
                loaded_at=time.time(),
            )
            await self._event(
                job_id, "serve-loaded",
                checkpoint_step=meta.get("checkpoint_step"),
                lora_merged=meta.get("lora_merged"),
            )
            logger.info("serve session loaded for %s: %s", job_id, meta)
            return meta

    async def unload(self, job_id: str) -> bool:
        session = self.sessions.pop(job_id, None)
        if session is None:
            return False
        await session.batcher.close()
        await self._event(job_id, "serve-unloaded")
        logger.info("serve session unloaded for %s", job_id)
        return True

    async def generate(
        self, job_id: str, req: GenRequest, *, timeout_s: float | None = None
    ) -> tuple[GenResult, dict[str, Any]]:
        session = self.sessions.get(job_id)
        if session is None:
            if not self.settings.serve_autoload:
                raise ServeLoadError(
                    f"job {job_id!r} is not loaded for serving; "
                    f"POST /admin/serve/{job_id}/load first", status=409,
                )
            await self.load(job_id)
            session = self.sessions.get(job_id)
            if session is None:  # admin unloaded while we were loading
                raise ServeLoadError(
                    f"job {job_id!r} was unloaded while loading; retry",
                    status=409,
                )
        result = await session.batcher.submit(req, timeout_s=timeout_s)
        return result, session.meta

    def stats(self) -> dict[str, Any]:
        return {
            job_id: session.batcher.stats()
            for job_id, session in self.sessions.items()
        }

    async def close(self) -> None:
        for job_id in list(self.sessions):
            await self.unload(job_id)


# ---------------------------------------------------------------------------
# Handlers (lazy-import the server module: it imports us at build time)
# ---------------------------------------------------------------------------


def _json_error(status: int, detail: Any) -> web.Response:
    return web.json_response({"detail": detail}, status=status)


def _parse_gen_request(body: dict[str, Any], settings) -> GenRequest:
    tokens = body.get("tokens")
    if not isinstance(tokens, list) or not tokens \
            or not all(isinstance(t, int) and t >= 0 for t in tokens):
        raise ValueError("'tokens' must be a non-empty list of token ids")
    max_new = body.get("max_new_tokens", settings.serve_default_max_new_tokens)
    if not isinstance(max_new, int) or max_new < 1:
        raise ValueError("'max_new_tokens' must be a positive integer")
    temperature = float(body.get("temperature", 0.0))
    top_k = int(body.get("top_k", 0))
    eos_id = body.get("eos_id")
    if eos_id is not None and not isinstance(eos_id, int):
        raise ValueError("'eos_id' must be an integer token id")
    return GenRequest(
        request_id=body.get("request_id") or f"gen-{uuid.uuid4().hex[:12]}",
        tokens=tokens,
        max_new_tokens=max_new,
        temperature=temperature,
        top_k=top_k,
        eos_id=eos_id,
        seed=int(body.get("seed", 0)),
    )


async def generate_job(request: web.Request) -> web.Response:
    """POST /jobs/{job_id}/generate — tokens in, tokens out."""
    from ..controller.server import (
        LIMITER_KEY,
        RUNTIME_KEY,
        _json_body,
        _owned_job,
    )

    rt = request.app[RUNTIME_KEY]
    limiter = request.app[LIMITER_KEY]
    user = request.get("user")
    uid = user.user_id if user else request.remote or "anon"
    if not await limiter.check(uid, "generate"):
        return _json_error(429, "rate limit exceeded (generate)")
    job = await _owned_job(request, request.match_info["job_id"])
    body = await _json_body(request)
    manager: ServeManager = request.app[SERVE_KEY]
    try:
        req = _parse_gen_request(body, rt.settings)
        timeout_raw = body.get("timeout_s")
        timeout_s = None if timeout_raw is None else float(timeout_raw)
    except (TypeError, ValueError) as e:
        return _json_error(400, str(e))
    t0 = time.monotonic()
    try:
        result, meta = await manager.generate(
            job.job_id, req, timeout_s=timeout_s
        )
    except QueueFull as e:
        return web.Response(
            status=429, headers={"Retry-After": "1"},
            body=json.dumps({"detail": str(e)}).encode(),
            content_type="application/json",
        )
    except DeadlineExceeded as e:
        return _json_error(504, str(e))
    except (PromptTooLong, ValueError) as e:
        return _json_error(400, str(e))
    except ServeLoadError as e:
        return _json_error(e.status, str(e))
    return web.json_response(
        {
            "job_id": job.job_id,
            "request_id": result.request_id,
            "prompt_tokens": result.prompt_tokens,
            "tokens": result.generated,
            "finish_reason": result.finish_reason,
            "latency_ms": round((time.monotonic() - t0) * 1000, 2),
            "model": {
                "checkpoint_step": meta.get("checkpoint_step"),
                "lora_merged": meta.get("lora_merged"),
            },
        }
    )


async def admin_serve_load(request: web.Request) -> web.Response:
    from ..controller.server import _admin

    _admin(request)
    manager: ServeManager = request.app[SERVE_KEY]
    try:
        meta = await manager.load(request.match_info["job_id"])
    except ServeLoadError as e:
        return _json_error(e.status, str(e))
    return web.json_response({"message": "loaded", "model": meta})


async def admin_serve_unload(request: web.Request) -> web.Response:
    from ..controller.server import _admin

    _admin(request)
    manager: ServeManager = request.app[SERVE_KEY]
    if not await manager.unload(request.match_info["job_id"]):
        return _json_error(404, "job is not loaded")
    return web.json_response({"message": "unloaded"})


async def admin_serve_status(request: web.Request) -> web.Response:
    from ..controller.server import _admin

    _admin(request)
    manager: ServeManager = request.app[SERVE_KEY]
    return web.json_response({"sessions": manager.stats()})


def add_serve_routes(app: web.Application, prefix: str) -> None:
    app.router.add_post(f"{prefix}/jobs/{{job_id}}/generate", generate_job)
    app.router.add_post(
        f"{prefix}/admin/serve/{{job_id}}/load", admin_serve_load
    )
    app.router.add_post(
        f"{prefix}/admin/serve/{{job_id}}/unload", admin_serve_unload
    )
    app.router.add_get(f"{prefix}/admin/serve", admin_serve_status)
