"""HTTP serving surface, mounted on the controller server.

Routes (``controller/server.py::build_app`` mounts them; the app's auth/CORS
middlewares apply — a bearer token that can read a job can generate from it):

* ``POST {prefix}/jobs/{job_id}/generate`` — generate from a promoted job's
  checkpoint (auto-loads on first use when ``serve_autoload`` is on);
* ``POST {prefix}/admin/serve/{job_id}/load`` / ``.../unload`` — explicit
  model lifecycle (admin).  ``load`` on an ALREADY-loaded job is the
  zero-downtime rollover trigger: if the promotion now points at a newer
  checkpoint, replicas spin up on it, traffic shifts, and the old replicas
  drain after their in-flight lanes finish (docs/serving.md §Fleet);
* ``GET {prefix}/admin/serve`` — per-model fleet/router stats (admin).

Every served job runs a :class:`~finetune_controller_tpu.serve.fleet.
ReplicaFleet` behind a :class:`~finetune_controller_tpu.serve.router.
ReplicaRouter` (``serve_replicas`` controls the floor; 1 keeps the PR-4
single-engine footprint but with health checks and drains).

The manager refuses jobs whose promotion is not COMPLETED
(``serve/loader.py::resolve_promoted``) — serving a half-copied or deleted
deploy prefix would decode garbage with a 200 status.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import time
import uuid
from pathlib import Path
from typing import Any

from aiohttp import web

from ..resilience.policy import RetryPolicy
from .adapters import AdapterError, AdapterRegistry, UnknownAdapter
from .batcher import DeadlineExceeded, QueueFull, ReplicaUnavailable
from .engine import EngineConfig, GenRequest, GenResult, PromptTooLong
from .fleet import AdapterBusy, ReplicaFleet
from .loader import (
    ServeLoadError,
    load_adapter as load_adapter_deltas,
    load_promoted,
    resolve_promoted,
)
from .router import FleetUnavailable, ReplicaRouter

logger = logging.getLogger(__name__)

SERVE_KEY = web.AppKey("serve", object)


@dataclasses.dataclass
class _Session:
    job_id: str
    fleet: ReplicaFleet
    router: ReplicaRouter
    meta: dict[str, Any]
    loaded_at: float
    tenant: Any = None  # sched/serve_tenant.py when autoscale is on
    #: multiplexed tenant adapters by job id (docs/serving.md §Multi-tenant
    #: adapters) — metas only; the weights live in the fleet's registry
    adapters: dict[str, dict[str, Any]] = dataclasses.field(
        default_factory=dict)
    #: process transport: the staged deploy prefix this generation's worker
    #: processes rebuild their weights from (removed on unload/rollover)
    worker_stage_dir: str | None = None


class ServeManager:
    """Loaded serving sessions, one replica fleet + router per promoted job."""

    def __init__(self, state, store, settings, *, obs=None, scheduler=None,
                 backend=None):
        self.state = state
        self.store = store
        self.settings = settings
        #: observability hub (obs/prom.py): serve TTFT histogram + timeline
        #: events on load/unload (docs/observability.md)
        self.obs = obs
        #: the backend's fair-share scheduler (serve-as-a-tenant autoscale,
        #: docs/scheduling.md §Serve tenant); None = static fleets
        self.scheduler = scheduler
        #: the training backend (docs/serving.md §Cross-process transport):
        #: with ``serve_transport=process`` worker sandboxes live under its
        #: substrate (``backend.serve_worker_root``); None falls back to the
        #: state dir
        self.backend = backend
        self.sessions: dict[str, _Session] = {}
        #: per-job single-flight loads: the dict insert is the CAS — exactly
        #: one racing ``load`` wins and does the work, the rest await its
        #: future (the ISSUE 10 loader-staleness fix, with the staging race
        #: itself removed by unique stage dirs in ``loader.load_promoted``)
        self._loading: dict[str, asyncio.Future] = {}
        #: tenant job id → base session job id: POST /jobs/{tenant}/generate
        #: routes to the base fleet with the tenant's adapter selected
        self._adapter_routes: dict[str, str] = {}
        self.work_dir = Path(settings.state_path) / "serve_cache"

    async def _event(self, job_id: str, event: str, **attrs) -> None:
        from ..obs.events import append_event_safe

        await append_event_safe(self.state, job_id, event, **attrs)

    def _engine_config(self) -> EngineConfig:
        s = self.settings
        return EngineConfig(
            slots=s.serve_slots,
            prompt_buckets=tuple(s.serve_prompt_buckets),
            max_new_tokens=s.serve_max_new_tokens,
            prefix_cache_bytes=(
                int(s.serve_prefix_cache_mb) * (1 << 20)
                if s.serve_prefix_cache else 0
            ),
            page_tokens=(s.serve_kv_page_tokens if s.serve_paged_kv else 0),
            pool_pages=(s.serve_kv_pool_pages if s.serve_paged_kv else 0),
            host_pool_bytes=(
                int(s.serve_kv_host_pool_mb) * (1 << 20)
                if s.serve_paged_kv else 0
            ),
        )

    def _batcher_kwargs(self) -> dict[str, Any]:
        return dict(
            max_queue=self.settings.serve_max_queue,
            max_wait_ms=self.settings.serve_max_wait_ms,
            default_timeout_s=self.settings.serve_request_timeout_s,
            drr_quantum_tokens=float(self.settings.serve_drr_quantum_tokens),
            ttft_observe=(
                self.obs.serve_ttft_seconds.observe
                if self.obs is not None else None
            ),
        )

    @property
    def _multi_tenant(self) -> bool:
        return self.settings.serve_max_adapters > 0

    @property
    def _transport_mode(self) -> str:
        mode = (self.settings.serve_transport or "inproc").strip().lower()
        if mode not in ("inproc", "process"):
            raise ServeLoadError(
                f"unknown serve_transport {mode!r} (expected 'inproc' or "
                "'process')", status=500,
            )
        return mode

    def _make_transport(self, job_id: str, payload_kwargs: dict[str, Any]):
        """Process-mode replica substrate: sandboxes under the backend's
        work dir when it offers one (the local backend does), else the state
        dir — one dir per worker with spec/log/heartbeat/socket file."""
        from ..transport.process import ProcessTransport

        s = self.settings
        root = None
        if self.backend is not None:
            root = self.backend.serve_worker_root(job_id)
        if root is None:
            root = Path(s.state_path) / "serve_workers" / job_id
            root.mkdir(parents=True, exist_ok=True)
        return ProcessTransport(
            job_id=job_id,
            root=root,
            payload={"builder": "deploy_dir", "kwargs": payload_kwargs},
            port_base=s.serve_worker_port_base,
            spawn_timeout_s=s.serve_worker_spawn_timeout_s,
            heartbeat_interval_s=s.serve_worker_heartbeat_s,
            probe_timeout_s=max(10.0, s.serve_health_interval_s * 5),
        )

    def _adapter_registry(self) -> AdapterRegistry | None:
        if not self._multi_tenant:
            return None
        return AdapterRegistry(
            self.settings.serve_max_adapters + 1,  # + the base slot 0
            self.settings.serve_adapter_rank,
        )

    async def _build_session(self, job_id, model, variables, meta,
                             *, transport=None) -> _Session:
        s = self.settings
        reward_spec = None
        if transport is not None and meta.get("task") == "reward":
            # reward jobs serve the scoring RPC: workers restore the head
            # from the same staged prefix the deploy_dir builder reads
            staged = (transport.payload.get("kwargs") or {}).get("dir")
            if staged:
                reward_spec = {"artifacts_dir": str(staged)}
        fleet = ReplicaFleet(
            job_id, model, variables, self._engine_config(),
            replicas=s.serve_replicas,
            batcher_kwargs=self._batcher_kwargs(),
            adapters=self._adapter_registry(),
            transport=transport,
            reward_spec=reward_spec,
            stall_timeout_s=s.serve_replica_stall_s,
            drain_timeout_s=s.serve_drain_timeout_s,
            restart_policy=RetryPolicy(
                max_attempts=s.serve_replica_restart_attempts,
                base_delay_s=s.retry_base_delay_s,
                max_delay_s=s.retry_max_delay_s,
            ),
            event_cb=(
                lambda event, **attrs: self._event(job_id, event, **attrs)
            ),
        )
        await fleet.start()
        router = ReplicaRouter(
            fleet,
            default_timeout_s=s.serve_request_timeout_s,
            failover_retries=s.serve_failover_retries,
        )
        session = _Session(
            job_id=job_id, fleet=fleet, router=router, meta=meta,
            loaded_at=time.time(),
        )
        if s.serve_autoscale and self.scheduler is not None:
            from ..sched.serve_tenant import ServeScalePolicy, ServeTenant

            flavor = s.serve_flavor or getattr(
                getattr(self.scheduler, "_catalog", None), "default_flavor", ""
            )
            if flavor:
                session.tenant = ServeTenant(
                    self.scheduler, fleet,
                    flavor=flavor, queue=s.serve_queue,
                    policy=ServeScalePolicy(
                        min_replicas=s.serve_min_replicas,
                        max_replicas=s.serve_max_replicas,
                        scale_up_queue_depth=s.serve_scale_up_queue_depth,
                        sustain_ticks=s.serve_scale_sustain_ticks,
                    ),
                )
                await session.tenant.attach_initial()
        fleet.start_health_loop(s.serve_health_interval_s)
        if session.tenant is not None:
            self._start_tenant_loop(session)
        return session

    def _start_tenant_loop(self, session: _Session) -> None:
        async def loop():
            while session.tenant is not None \
                    and self.sessions.get(session.job_id) is session:
                try:
                    await session.tenant.tick()
                # ftc: ignore[silent-except] -- logged: the autoscale loop must outlive a single tick's failure
                except Exception:
                    logger.exception("serve tenant tick failed for %s",
                                     session.job_id)
                await asyncio.sleep(self.settings.serve_health_interval_s)

        asyncio.get_running_loop().create_task(loop())

    async def load(self, job_id: str) -> dict[str, Any]:
        """Load a promoted job for serving (idempotent), or — when it is
        already loaded and its promotion points at a NEWER checkpoint —
        perform a zero-downtime rollover onto it."""
        racing = self._loading.get(job_id)
        if racing is not None:
            return await asyncio.shield(racing)
        future = asyncio.get_running_loop().create_future()
        self._loading[job_id] = future  # the CAS: we are the winner now
        try:
            meta = await self._load_or_rollover(job_id)
            future.set_result(meta)
            return meta
        except BaseException as exc:
            if not future.done():
                future.set_exception(exc)
                future.exception()  # losers or nobody: mark retrieved
            raise
        finally:
            self._loading.pop(job_id, None)

    async def _peek_latest_step(self, promotion_uri: str) -> int | None:
        """Newest ``checkpoints/step_N`` under the deploy prefix — a store
        LISTING, not a download: the cheap already-serving-this pre-check."""
        prefix = promotion_uri.rstrip("/") + "/"
        try:
            objs = await self.store.list_prefix(promotion_uri)
        except Exception:
            logger.debug("peek of %s failed; falling back to a full load",
                         promotion_uri, exc_info=True)
            return None
        steps = []
        for obj in objs:
            rel = obj.get("uri", "")[len(prefix):]
            if rel.startswith("checkpoints/step_"):
                raw = rel.split("/", 2)[1].rpartition("_")[2]
                if raw.isdigit():
                    steps.append(int(raw))
        return max(steps) if steps else None

    async def _load_or_rollover_process(self, job_id: str) -> dict[str, Any]:
        """The ``serve_transport=process`` load path (docs/serving.md
        §Cross-process transport): the control plane STAGES the promoted
        prefix and reads its meta, but never loads the weights — each worker
        process rebuilds them from the staged dir with its own JAX runtime
        (``transport/builders.py::deploy_dir``).  A rollover stages the new
        checkpoint, repoints the transport payload, and lets the fleet spin
        the next worker generation on it before draining the old one."""
        import shutil

        from .loader import stage_for_workers

        existing = self.sessions.get(job_id)
        if existing is not None:
            job = await resolve_promoted(self.state, job_id)
            if job.promotion_uri == existing.meta.get("promotion_uri"):
                peek = await self._peek_latest_step(job.promotion_uri)
                if peek is not None \
                        and peek == existing.meta.get("checkpoint_step"):
                    return existing.meta
        merge = self.settings.serve_merge_lora and not self._multi_tenant
        stage_dir, meta = await stage_for_workers(
            self.state, self.store, job_id, self.work_dir, merge_lora=merge,
        )
        base_adapter = None
        if self._multi_tenant:
            meta["lora_merged"] = False
            meta["multi_tenant"] = True
            meta["self_adapter"] = meta.get("lora_rank", 0) > 0
            if meta["self_adapter"]:
                # the job's own fine-tune serves as tenant #1; only the
                # DELTAS load here (megabytes) — the base stays in workers
                from .loader import _load_adapter_tree

                lora_tree, ameta = await asyncio.to_thread(
                    _load_adapter_tree, stage_dir
                )
                base_adapter = (
                    lora_tree, ameta["lora_alpha"], ameta["lora_rank"],
                )
        payload_kwargs = {
            "dir": str(stage_dir), "merge_lora": merge,
            "multi_tenant": self._multi_tenant,
        }
        if existing is not None:
            same = (
                existing.meta.get("checkpoint_step") == meta.get("checkpoint_step")
                and existing.meta.get("promotion_uri") == meta.get("promotion_uri")
            )
            if same:
                await asyncio.to_thread(
                    shutil.rmtree, stage_dir, ignore_errors=True
                )
                return existing.meta
            await self._event(
                job_id, "serve-rollover-requested",
                from_step=existing.meta.get("checkpoint_step"),
                to_step=meta.get("checkpoint_step"),
            )
            existing.fleet.transport.set_payload("deploy_dir", payload_kwargs)
            old_stage = existing.worker_stage_dir
            await existing.fleet.rollover(None, None)
            if base_adapter is not None:
                await existing.fleet.register_adapter(
                    job_id, *base_adapter,
                    meta={"checkpoint_step": meta.get("checkpoint_step")},
                )
            existing.meta = meta
            existing.worker_stage_dir = str(stage_dir)
            if old_stage:
                # the old generation drained inside rollover(): nothing
                # reads the superseded stage anymore
                await asyncio.to_thread(
                    shutil.rmtree, old_stage, ignore_errors=True
                )
            logger.info("serve rollover completed for %s (process): %s",
                        job_id, meta)
            return meta
        transport = self._make_transport(job_id, payload_kwargs)
        session = await self._build_session(
            job_id, None, None, meta, transport=transport
        )
        session.worker_stage_dir = str(stage_dir)
        self.sessions[job_id] = session
        if base_adapter is not None:
            await session.fleet.register_adapter(
                job_id, *base_adapter,
                meta={"checkpoint_step": meta.get("checkpoint_step")},
            )
        await self._event(
            job_id, "serve-loaded",
            checkpoint_step=meta.get("checkpoint_step"),
            lora_merged=meta.get("lora_merged"),
            replicas=session.fleet.target_replicas,
            transport="process",
        )
        logger.info("serve session loaded for %s (process workers): %s",
                    job_id, meta)
        return meta

    async def _load_or_rollover(self, job_id: str) -> dict[str, Any]:
        if self._transport_mode == "process":
            return await self._load_or_rollover_process(job_id)
        existing = self.sessions.get(job_id)
        if existing is not None:
            # cheap idempotence check BEFORE staging gigabytes: same deploy
            # prefix and no newer checkpoint step means the live session
            # already serves this exact artifact
            job = await resolve_promoted(self.state, job_id)
            if job.promotion_uri == existing.meta.get("promotion_uri"):
                peek = await self._peek_latest_step(job.promotion_uri)
                if peek is not None \
                        and peek == existing.meta.get("checkpoint_step"):
                    return existing.meta
        model, variables, meta = await load_promoted(
            self.state, self.store, job_id, self.work_dir,
            # multi-tenant fleets need the pristine base: the job's own
            # adapter is stripped below and served as tenant #1 instead
            merge_lora=(self.settings.serve_merge_lora
                        and not self._multi_tenant),
        )
        base_adapter = None
        if self._multi_tenant:
            from .loader import strip_lora_for_multitenant

            model, variables, lora_tree, alpha, rank = \
                await asyncio.to_thread(strip_lora_for_multitenant,
                                        model, variables)
            meta["lora_merged"] = False
            meta["multi_tenant"] = True
            meta["self_adapter"] = lora_tree is not None
            if lora_tree is not None:
                base_adapter = (lora_tree, alpha, rank)
        if existing is not None:
            same = (
                existing.meta.get("checkpoint_step") == meta.get("checkpoint_step")
                and existing.meta.get("promotion_uri") == meta.get("promotion_uri")
            )
            if same:
                # already serving exactly this artifact — idempotent
                return existing.meta
            await self._event(
                job_id, "serve-rollover-requested",
                from_step=existing.meta.get("checkpoint_step"),
                to_step=meta.get("checkpoint_step"),
            )
            await existing.fleet.rollover(model, variables)
            if base_adapter is not None:
                # the job's own adapter moved with the checkpoint: refresh
                # tenant #1 AFTER the rollover so the new generation serves
                # the new deltas (the registry slot is reused in place)
                await existing.fleet.register_adapter(
                    job_id, *base_adapter,
                    meta={"checkpoint_step": meta.get("checkpoint_step")},
                )
            existing.meta = meta
            logger.info("serve rollover completed for %s: %s", job_id, meta)
            return meta
        session = await self._build_session(job_id, model, variables, meta)
        self.sessions[job_id] = session
        if base_adapter is not None:
            await session.fleet.register_adapter(
                job_id, *base_adapter,
                meta={"checkpoint_step": meta.get("checkpoint_step")},
            )
        await self._event(
            job_id, "serve-loaded",
            checkpoint_step=meta.get("checkpoint_step"),
            lora_merged=meta.get("lora_merged"),
            replicas=session.fleet.target_replicas,
        )
        logger.info("serve session loaded for %s: %s", job_id, meta)
        return meta

    async def load_adapter(self, base_job_id: str,
                           adapter_job_id: str) -> dict[str, Any]:
        """Stage a promoted LoRA job's deltas onto an already-loaded base
        fleet as a multiplexed tenant (docs/serving.md §Multi-tenant
        adapters) — a device write per replica, never a fleet rebuild.
        Re-loading a tenant whose promotion moved refreshes its slot in
        place (the tenant-rollover path)."""
        if not self._multi_tenant:
            raise ServeLoadError(
                "multi-tenant serving is off (serve_max_adapters=0)",
                status=409,
            )
        session = self.sessions.get(base_job_id)
        if session is None:
            raise ServeLoadError(
                f"base job {base_job_id!r} is not loaded; "
                f"POST /admin/serve/{base_job_id}/load first", status=409,
            )
        if adapter_job_id == base_job_id:
            raise ServeLoadError(
                f"job {base_job_id!r} is the base of this fleet — its own "
                "adapter is already tenant #1", status=409,
            )
        routed = self._adapter_routes.get(adapter_job_id)
        if routed is not None and routed != base_job_id:
            raise ServeLoadError(
                f"adapter {adapter_job_id!r} is already multiplexed on base "
                f"{routed!r}; unload it there first", status=409,
            )
        lora_tree, meta = await load_adapter_deltas(
            self.state, self.store, adapter_job_id, self.work_dir,
            base_meta=session.meta,
        )
        try:
            slot = await session.fleet.register_adapter(
                adapter_job_id, lora_tree, meta["lora_alpha"],
                meta["lora_rank"],
                meta={"checkpoint_step": meta.get("checkpoint_step")},
            )
        except AdapterError as e:
            raise ServeLoadError(str(e), status=409) from e
        meta["slot"] = slot
        meta["base_job_id"] = base_job_id
        session.adapters[adapter_job_id] = meta
        self._adapter_routes[adapter_job_id] = base_job_id
        logger.info("adapter %s multiplexed onto %s: %s",
                    adapter_job_id, base_job_id, meta)
        return meta

    async def unload_adapter(self, base_job_id: str,
                             adapter_job_id: str) -> bool:
        session = self.sessions.get(base_job_id)
        if session is None or adapter_job_id not in session.adapters:
            return False
        try:
            await session.fleet.unregister_adapter(adapter_job_id)
        except AdapterBusy as e:
            raise ServeLoadError(str(e), status=409) from e
        session.adapters.pop(adapter_job_id, None)
        self._adapter_routes.pop(adapter_job_id, None)
        return True

    async def unload(self, job_id: str) -> bool:
        session = self.sessions.pop(job_id, None)
        if session is None:
            return False
        for tenant_id, base_id in list(self._adapter_routes.items()):
            if base_id == job_id:
                self._adapter_routes.pop(tenant_id, None)
        if session.tenant is not None:
            await session.tenant.close()
            session.tenant = None
        await session.fleet.close()
        if session.worker_stage_dir:
            import shutil

            await asyncio.to_thread(
                shutil.rmtree, session.worker_stage_dir, ignore_errors=True
            )
        await self._event(job_id, "serve-unloaded")
        logger.info("serve session unloaded for %s", job_id)
        return True

    async def generate(
        self, job_id: str, req: GenRequest, *, timeout_s: float | None = None
    ) -> tuple[GenResult, dict[str, Any]]:
        session = self.sessions.get(job_id)
        if session is None and not req.adapter_id:
            # a tenant job id routes to the base fleet multiplexing it
            base_id = self._adapter_routes.get(job_id)
            if base_id is not None:
                session = self.sessions.get(base_id)
                if session is not None:
                    req.adapter_id = job_id
        if session is None:
            if not self.settings.serve_autoload:
                raise ServeLoadError(
                    f"job {job_id!r} is not loaded for serving; "
                    f"POST /admin/serve/{job_id}/load first", status=409,
                )
            await self.load(job_id)
            session = self.sessions.get(job_id)
            if session is None:  # admin unloaded while we were loading
                raise ServeLoadError(
                    f"job {job_id!r} was unloaded while loading; retry",
                    status=409,
                )
        if not req.adapter_id and session.meta.get("self_adapter"):
            # multi-tenant base: the job's own fine-tune is tenant #1, so a
            # plain generate keeps serving the promoted behavior (slot 0
            # would be the raw pretrained base)
            req.adapter_id = session.job_id
        if req.adapter_id and req.adapter_id != session.job_id \
                and req.adapter_id not in session.adapters:
            raise UnknownAdapter(
                f"adapter {req.adapter_id!r} is not loaded on base "
                f"{session.job_id!r} (loaded: "
                f"{sorted(session.adapters) or 'none'})"
            )
        result = await session.router.submit(req, timeout_s=timeout_s)
        meta = session.meta
        if req.adapter_id and req.adapter_id in session.adapters:
            meta = {**meta, "adapter": req.adapter_id,
                    "adapter_checkpoint_step":
                        session.adapters[req.adapter_id].get(
                            "checkpoint_step")}
        return result, meta

    def stats(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for job_id, session in self.sessions.items():
            stats = session.fleet.stats()
            stats.update(session.router.stats())
            if session.tenant is not None:
                stats["autoscale"] = session.tenant.stats()
            if session.adapters:
                stats["adapter_jobs"] = {
                    tid: {"slot": m.get("slot"),
                          "checkpoint_step": m.get("checkpoint_step")}
                    for tid, m in session.adapters.items()
                }
            out[job_id] = stats
        return out

    async def close(self) -> None:
        for job_id in list(self.sessions):
            await self.unload(job_id)


# ---------------------------------------------------------------------------
# Handlers (lazy-import the server module: it imports us at build time)
# ---------------------------------------------------------------------------


def _json_error(status: int, detail: Any) -> web.Response:
    return web.json_response({"detail": detail}, status=status)


def _parse_gen_request(body: dict[str, Any], settings) -> GenRequest:
    tokens = body.get("tokens")
    if not isinstance(tokens, list) or not tokens \
            or not all(isinstance(t, int) and t >= 0 for t in tokens):
        raise ValueError("'tokens' must be a non-empty list of token ids")
    max_new = body.get("max_new_tokens", settings.serve_default_max_new_tokens)
    if not isinstance(max_new, int) or max_new < 1:
        raise ValueError("'max_new_tokens' must be a positive integer")
    temperature = float(body.get("temperature", 0.0))
    top_k = int(body.get("top_k", 0))
    eos_id = body.get("eos_id")
    if eos_id is not None and not isinstance(eos_id, int):
        raise ValueError("'eos_id' must be an integer token id")
    adapter = body.get("adapter", "")
    if adapter and not isinstance(adapter, str):
        raise ValueError("'adapter' must be a job id string")
    return GenRequest(
        request_id=body.get("request_id") or f"gen-{uuid.uuid4().hex[:12]}",
        tokens=tokens,
        max_new_tokens=max_new,
        temperature=temperature,
        top_k=top_k,
        eos_id=eos_id,
        seed=int(body.get("seed", 0)),
        adapter_id=adapter or "",
    )


async def generate_job(request: web.Request) -> web.Response:
    """POST /jobs/{job_id}/generate — tokens in, tokens out."""
    from ..controller.server import (
        LIMITER_KEY,
        RUNTIME_KEY,
        _json_body,
        _owned_job,
    )

    rt = request.app[RUNTIME_KEY]
    limiter = request.app[LIMITER_KEY]
    user = request.get("user")
    uid = user.user_id if user else request.remote or "anon"
    if not await limiter.check(uid, "generate"):
        return _json_error(429, "rate limit exceeded (generate)")
    job = await _owned_job(request, request.match_info["job_id"])
    body = await _json_body(request)
    manager: ServeManager = request.app[SERVE_KEY]
    try:
        req = _parse_gen_request(body, rt.settings)
        timeout_raw = body.get("timeout_s")
        timeout_s = None if timeout_raw is None else float(timeout_raw)
    except (TypeError, ValueError) as e:
        return _json_error(400, str(e))
    t0 = time.monotonic()
    try:
        result, meta = await manager.generate(
            job.job_id, req, timeout_s=timeout_s
        )
    except QueueFull as e:
        # Retry-After derived from observed queue depth and decode rate
        # (serve/batcher.py::retry_after_s) — callers back off for a useful
        # interval instead of guessing from a bare 429
        retry_after = max(1, round(e.retry_after_s or 1.0))
        return web.Response(
            status=429, headers={"Retry-After": str(retry_after)},
            body=json.dumps({
                "detail": str(e), "retry_after_s": retry_after,
            }).encode(),
            content_type="application/json",
        )
    except (FleetUnavailable, ReplicaUnavailable) as e:
        retry_after = max(1, round(getattr(e, "retry_after_s", None) or 2.0))
        return web.Response(
            status=503, headers={"Retry-After": str(retry_after)},
            body=json.dumps({"detail": str(e)}).encode(),
            content_type="application/json",
        )
    except DeadlineExceeded as e:
        return _json_error(504, str(e))
    except UnknownAdapter as e:
        return _json_error(404, str(e))
    except (PromptTooLong, ValueError) as e:
        return _json_error(400, str(e))
    except ServeLoadError as e:
        return _json_error(e.status, str(e))
    return web.json_response(
        {
            "job_id": job.job_id,
            "request_id": result.request_id,
            "prompt_tokens": result.prompt_tokens,
            "tokens": result.generated,
            "finish_reason": result.finish_reason,
            "latency_ms": round((time.monotonic() - t0) * 1000, 2),
            "replica_id": result.replica_id,
            "model": {
                "checkpoint_step": meta.get("checkpoint_step"),
                "lora_merged": meta.get("lora_merged"),
                "adapter": meta.get("adapter") or req.adapter_id or None,
            },
        }
    )


async def admin_serve_load(request: web.Request) -> web.Response:
    from ..controller.server import _admin

    _admin(request)
    manager: ServeManager = request.app[SERVE_KEY]
    try:
        meta = await manager.load(request.match_info["job_id"])
    except ServeLoadError as e:
        return _json_error(e.status, str(e))
    return web.json_response({"message": "loaded", "model": meta})


async def admin_serve_unload(request: web.Request) -> web.Response:
    from ..controller.server import _admin

    _admin(request)
    manager: ServeManager = request.app[SERVE_KEY]
    if not await manager.unload(request.match_info["job_id"]):
        return _json_error(404, "job is not loaded")
    return web.json_response({"message": "unloaded"})


async def admin_adapter_load(request: web.Request) -> web.Response:
    """POST /admin/serve/{job_id}/adapters/{adapter_job_id}/load — stage a
    promoted LoRA job's deltas onto the base fleet as a multiplexed tenant
    (docs/serving.md §Multi-tenant adapters)."""
    from ..controller.server import _admin

    _admin(request)
    manager: ServeManager = request.app[SERVE_KEY]
    try:
        meta = await manager.load_adapter(
            request.match_info["job_id"],
            request.match_info["adapter_job_id"],
        )
    except ServeLoadError as e:
        return _json_error(e.status, str(e))
    return web.json_response({"message": "adapter loaded", "adapter": meta})


async def admin_adapter_unload(request: web.Request) -> web.Response:
    from ..controller.server import _admin

    _admin(request)
    manager: ServeManager = request.app[SERVE_KEY]
    try:
        ok = await manager.unload_adapter(
            request.match_info["job_id"],
            request.match_info["adapter_job_id"],
        )
    except ServeLoadError as e:
        return _json_error(e.status, str(e))
    if not ok:
        return _json_error(404, "adapter is not loaded on this base")
    return web.json_response({"message": "adapter unloaded"})


async def admin_serve_status(request: web.Request) -> web.Response:
    from ..controller.server import _admin

    _admin(request)
    manager: ServeManager = request.app[SERVE_KEY]
    # process-wide shard-audit counters (analysis/shard_audit.py): every
    # serve-side weight load in this process audits the rule-table
    # shardings; violations > 0 means a load landed mis-sharded state
    from ..analysis.shard_audit import metrics_snapshot as shard_audit_snapshot

    return web.json_response({
        "sessions": manager.stats(),
        "shard_audit": shard_audit_snapshot(),
    })


def add_serve_routes(app: web.Application, prefix: str) -> None:
    app.router.add_post(f"{prefix}/jobs/{{job_id}}/generate", generate_job)
    app.router.add_post(
        f"{prefix}/admin/serve/{{job_id}}/load", admin_serve_load
    )
    app.router.add_post(
        f"{prefix}/admin/serve/{{job_id}}/unload", admin_serve_unload
    )
    app.router.add_post(
        f"{prefix}/admin/serve/{{job_id}}/adapters/{{adapter_job_id}}/load",
        admin_adapter_load,
    )
    app.router.add_post(
        f"{prefix}/admin/serve/{{job_id}}/adapters/{{adapter_job_id}}/unload",
        admin_adapter_unload,
    )
    app.router.add_get(f"{prefix}/admin/serve", admin_serve_status)
