"""Radix-trie prefix cache over prompt token ids (docs/serving.md).

Production fine-tuned models are overwhelmingly served behind one shared
system prompt, yet a vanilla continuous-batching engine re-runs the full
prefill for every request.  This cache stores the B=1 KV snapshot a prefill
produces — full ``cache_len`` shape, exactly what ``BatchEngine._insert``
splices into a decode lane — keyed by the prompt's token ids in a
path-compressed radix trie, so ``admit()`` can resolve the longest cached
prefix of a new prompt and prefill only the suffix (``fill_from``).

Why a *trie* and not an exact-match dict: causality.  The KV at position
``i`` depends only on tokens ``[0, i]``, so a snapshot stored for prompt
``K`` is a bit-exact KV source for ANY prompt sharing a prefix with ``K`` —
restricted to the shared positions.  The useful lookup is therefore
"longest common prefix with any stored key", which a radix walk answers in
O(len(prompt)).  The classic case: one snapshot for ``[system; user_A]``
serves ``[system; user_B]``'s whole system prompt.

Budgeting: snapshots are device-resident (HBM alongside the serving
weights), so the cache holds a strict **byte budget** and evicts least
recently used entries past it.  Entries larger than the whole budget are
refused outright.  Eviction only drops references — JAX arrays are
immutable and lanes receive device-side *copies* at splice time, so
evicting a snapshot mid-flight cannot perturb a request decoding from it
(pinned in ``tests/test_prefix_cache.py``).

Thread-safety: none needed — the cache is owned by a ``BatchEngine``, whose
accesses the batcher's single drive loop already serializes (same contract
as the engine's ``_slots``).
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Any, Callable

from .kv_pages import HostRun


def resolve_reuse_length(
    match_len: int,
    prompt_len: int,
    buckets: tuple[int, ...],
    cache_len: int,
) -> int:
    """Bucket-granular reuse length for a raw trie match of ``match_len``.

    Two constraints shrink the raw match:

    * at least one real suffix token must remain — the engine needs a
      forward over ``[L, prompt_len)`` to produce last-position logits, so
      a full-prompt hit reuses ``prompt_len - 1`` tokens and prefills one;
    * the suffix is right-padded to a prompt bucket ``b``, and the padded
      chunk must fit the lane: ``L + b <= cache_len``.

    For each bucket the feasible reuse is the range
    ``[prompt_len - b, min(match_len, prompt_len - 1, cache_len - b)]``
    (lower bound: the suffix must fit the bucket; upper bound: the trie
    match, the one-real-token rule, and the lane end).  The answer is the
    largest feasible L over all buckets — when bucket rounding overshoots
    the lane, this reuses *less* so a bigger padded suffix still fits
    (any prefix of the match is a valid KV source).

    Returns 0 when no usable reuse remains (treat as a miss).
    """
    best = 0
    for bucket in buckets:
        candidate = min(match_len, prompt_len - 1, cache_len - bucket)
        if candidate >= max(1, prompt_len - bucket):
            best = max(best, candidate)
    return best


@dataclasses.dataclass
class _Entry:
    key: tuple[int, ...]
    cache: Any               # B=1 device KV pytree, a kv_pages.PageRun, or
    #                          a kv_pages.HostRun (demoted to the host tier)
    nbytes: int
    node: "_Node"
    #: adapter namespace (docs/serving.md §Multi-tenant adapters): KV depends
    #: on the weights that produced it, so a cache key is (base model,
    #: adapter id, token ids) — a hit under one tenant's adapter must never
    #: splice into another tenant's lane
    ns: str = ""
    #: residency: "device" (cache is a KV pytree or PageRun), "host" (cache
    #: is a HostRun), or "in-flight" (a restore is mid-transfer — the entry
    #: is pinned against demotion/eviction until the swap lands)
    tier: str = "device"


class _Node:
    """Radix-trie node; edges are (label, child) keyed by the label's first
    token.  ``n_entries`` counts stored snapshots in the subtree (self
    included) so lookups can steer toward a live entry without scanning."""

    __slots__ = ("edges", "entry", "parent", "n_entries")

    def __init__(self, parent: "_Node | None" = None):
        self.edges: dict[int, tuple[tuple[int, ...], "_Node"]] = {}
        self.entry: _Entry | None = None
        self.parent = parent
        self.n_entries = 0


def _lcp(a: tuple[int, ...], b: tuple[int, ...]) -> int:
    n = min(len(a), len(b))
    i = 0
    while i < n and a[i] == b[i]:
        i += 1
    return i


class PrefixCache:
    """LRU byte-budgeted radix trie of KV snapshots, one trie per adapter
    namespace.

    Two storage flavors share the structure:

    * **unpaged** (``pool=None``): entries are full-shape B=1 device KV
      pytrees, charged their logical ``nbytes``;
    * **paged** (``pool`` = the engine's :class:`~finetune_controller_tpu.
      serve.kv_pages.KVPagePool`): entries are :class:`~finetune_controller_
      tpu.serve.kv_pages.PageRun` references into the shared pool, charged
      PHYSICAL bytes — a page shared copy-on-write by several entries (or
      still held by the lane that wrote it) is charged once, on its first
      cache reference, and credited when its last cache reference drops.
    """

    def __init__(self, budget_bytes: int, *, pool: Any = None):
        if budget_bytes <= 0:
            raise ValueError("PrefixCache needs a positive byte budget "
                             "(disable the cache instead of zeroing it)")
        self.budget_bytes = int(budget_bytes)
        self._pool = pool
        self._roots: dict[str, _Node] = {}
        self._lru: OrderedDict[tuple, _Entry] = OrderedDict()
        self.total_bytes = 0
        self.evictions_total = 0
        # host tier (docs/serving.md §KV tiering) — wired by the paged
        # engine via enable_tier(); unpaged caches never tier
        self._host_pool: Any = None
        self._demote_fn: Callable | None = None
        self._restore_fn: Callable | None = None

    def enable_tier(self, host_pool: Any, demote_fn: Callable,
                    restore_fn: Callable) -> None:
        """Arm the host-RAM tier: past the device byte budget, LRU entries
        DEMOTE to host slots instead of evicting, and a lookup hit on a
        demoted entry restores it on touch.

        ``demote_fn(PageRun) -> HostRun | None`` copies every page of a run
        into host slots (None when the host tier is full — the entry then
        falls through to plain eviction); ``restore_fn(HostRun) -> PageRun |
        None`` allocates fresh device pages (admission-style: reserve +
        alloc, holding synthetic lane refs this cache immediately converts
        to cache refs) and uploads the bytes (None when the device pool
        cannot host the run right now — the hit is treated as a miss and
        the entry stays demoted).  Both run in admission paths, never
        inside the transfer-guarded decode dispatch.
        """
        self._host_pool = host_pool
        self._demote_fn = demote_fn
        self._restore_fn = restore_fn

    def __len__(self) -> int:
        return len(self._lru)

    # ---- lookup -----------------------------------------------------------

    def lookup(self, tokens: list[int] | tuple[int, ...],
               namespace: str = "") -> tuple[int, Any]:
        """Longest common prefix with any key stored under ``namespace``.

        Returns ``(match_len, cache)``; ``(0, None)`` on a miss.  The hit
        entry is refreshed in the LRU order.
        """
        query = tuple(tokens)
        root = self._roots.get(namespace)
        if root is None:
            return 0, None
        node, depth = root, 0
        while depth < len(query):
            edge = node.edges.get(query[depth])
            if edge is None:
                break
            label, child = edge
            shared = _lcp(label, query[depth:])
            depth += shared
            node = child
            if shared < len(label):
                # diverged mid-edge: everything below child still shares
                # `depth` tokens with the query, nothing shares more
                break
        if depth == 0:
            return 0, None
        entry = self._pick(node)
        if entry is None:  # pragma: no cover - n_entries invariant
            return 0, None
        self._lru.move_to_end((entry.ns, entry.key))
        if isinstance(entry.cache, HostRun):
            # restore-on-touch: page the demoted run back into fresh device
            # pages before the caller splices it.  A failed restore (device
            # pool full right now) is a miss — the entry stays on host for
            # a later, less contended touch.
            if not self._restore(entry):
                return 0, None
        return depth, entry.cache

    def _restore(self, entry: _Entry) -> bool:
        if self._restore_fn is None:  # pragma: no cover - host entries only
            return False              # exist after enable_tier()
        host_run = entry.cache
        entry.tier = "in-flight"  # pin: restore's own allocations may demote
        try:                      # or evict OTHER entries, never this one
            new_run = self._restore_fn(host_run)
        finally:
            entry.tier = "host"
        if new_run is None:
            return False
        # the engine handed us pages holding synthetic admission (lane)
        # refs; convert them to cache refs, then drop the synthetic ones
        charged = self._pool.cache_ref(new_run.pages)
        self._pool.lane_release(new_run.pages)
        self.total_bytes += charged * self._pool.page_bytes
        entry.nbytes = charged * self._pool.page_bytes
        entry.cache = new_run
        entry.tier = "device"
        self._host_pool.free(host_run.slots)
        self._host_pool.restores_total += len(host_run.slots)
        # restoring may overshoot the device budget: shed LRU entries (to
        # host when possible) so the budget invariant holds after every
        # public call.  One sanctioned exception: an entry BIGGER than the
        # whole device budget (born demoted at insert) overshoots while it
        # is the only device-resident entry — it re-demotes as the LRU
        # victim of the next shed instead
        self._shrink(exclude=(entry.ns, entry.key))
        return True

    def _pick(self, node: _Node) -> _Entry | None:
        """Any live entry in ``node``'s subtree (they all share the resolved
        prefix); prefer the shallowest so the walk stays O(depth)."""
        while node is not None and node.n_entries:
            if node.entry is not None:
                return node.entry
            node = next(
                (child for _, child in node.edges.values() if child.n_entries),
                None,
            )
        return None

    # ---- insert / evict ---------------------------------------------------

    def insert(self, tokens: list[int] | tuple[int, ...], cache: Any,
               nbytes: int | None = None, namespace: str = "") -> bool:
        """Store ``cache`` under ``(namespace, tokens)``; returns False when
        refused (empty key, or the snapshot alone exceeds the budget).
        Re-inserting an existing key refreshes its LRU slot and keeps the
        stored snapshot (equal content by construction — same prompt, same
        weights, same adapter)."""
        key = tuple(tokens)
        if not key:
            return False
        existing = self._lru.get((namespace, key))
        if existing is not None:
            self._lru.move_to_end((namespace, key))
            return True
        if self._pool is not None:
            # paged: refuse by the entry's worst-case physical footprint;
            # the actual charge (below) counts already-shared pages once
            if len(cache.pages) * self._pool.page_bytes > self.budget_bytes:
                # tier armed: an entry too big for the DEVICE budget is
                # born demoted — snapshotted straight to host slots, zero
                # device charge (its pages stay lane-held until the writing
                # lane drains, then free).  This is what stops long-context
                # KV competing with hot decode for device pages: the entry
                # is still hittable, it just pages in on touch.
                if self._demote_fn is None:
                    return False
                host_run = self._demote_fn(cache)
                if host_run is None:
                    return False
                self._host_pool.demotions_total += len(host_run.slots)
                node = self._attach(key, namespace)
                entry = _Entry(key=key, cache=host_run, nbytes=0, node=node,
                               ns=namespace, tier="host")
                self._link(node, entry)
                return True
        else:
            if nbytes is None:
                nbytes = _tree_nbytes(cache)
            if nbytes > self.budget_bytes:
                return False
        node = self._attach(key, namespace)
        if self._pool is not None:
            nbytes = self._pool.cache_ref(cache.pages) * self._pool.page_bytes
        entry = _Entry(key=key, cache=cache, nbytes=nbytes, node=node,
                       ns=namespace)
        self._link(node, entry)
        self.total_bytes += nbytes
        self._shrink(exclude=(namespace, key))
        return True

    def _link(self, node: _Node, entry: _Entry) -> None:
        node.entry = entry
        walk = node
        while walk is not None:
            walk.n_entries += 1
            walk = walk.parent
        self._lru[(entry.ns, entry.key)] = entry

    def _shrink(self, exclude: tuple | None = None) -> None:
        """Enforce the DEVICE byte budget: demote LRU device entries to the
        host tier while one is available, evict otherwise.  ``exclude``
        protects the entry that triggered the shrink (just inserted or just
        restored — by definition MRU and within budget by itself)."""
        while self.total_bytes > self.budget_bytes:
            if not self._shed_one(exclude):
                break

    def _shed_one(self, exclude: tuple | None = None) -> bool:
        """Move one LRU device entry off the device: demote when the host
        tier accepts it, evict otherwise.  Returns False when nothing
        device-resident remains to shed."""
        victim = next(
            (e for e in self._lru.values()
             if e.tier == "device" and (e.ns, e.key) != exclude),
            None,
        )
        if victim is None:
            return False
        if self._demote_fn is not None:
            host_run = self._demote_fn(victim.cache)
            if host_run is not None:
                freed = self._pool.cache_release(victim.cache.pages)
                self.total_bytes -= freed * self._pool.page_bytes
                victim.nbytes = 0
                victim.cache = host_run
                victim.tier = "host"
                self._host_pool.demotions_total += len(host_run.slots)
                return True
        self._evict(victim)
        return True

    def demote_or_evict(self) -> bool:
        """The paged engine's page-pressure hook (``alloc_reserved``'s
        ``evict_one``) with the tier armed: shed the LRU device entry —
        demote when possible, so "evicting" under admission pressure stops
        destroying reusable KV — falling back to plain eviction (host full,
        or only host-resident entries left, whose eviction frees host
        slots but no device page; the ``slack`` invariant guarantees a
        device page frees before the LRU drains)."""
        if self._shed_one():
            return True
        return self.evict_oldest()

    def _attach(self, key: tuple[int, ...], namespace: str = "") -> _Node:
        """Walk/extend the trie to the node for ``key``, splitting edges."""
        node = self._roots.get(namespace)
        if node is None:
            node = self._roots[namespace] = _Node()
        i = 0
        while i < len(key):
            edge = node.edges.get(key[i])
            if edge is None:
                child = _Node(parent=node)
                node.edges[key[i]] = (key[i:], child)
                return child
            label, child = edge
            shared = _lcp(label, key[i:])
            if shared == len(label):
                node, i = child, i + shared
                continue
            # split the edge at the divergence point
            mid = _Node(parent=node)
            mid.n_entries = child.n_entries
            mid.edges[label[shared]] = (label[shared:], child)
            child.parent = mid
            node.edges[key[i]] = (label[:shared], mid)
            if shared == len(key) - i:
                return mid
            leaf = _Node(parent=mid)
            mid.edges[key[i + shared]] = (key[i + shared:], leaf)
            return leaf
        return node

    def evict_oldest(self) -> bool:
        """Evict the least recently used entry (any namespace) — the paged
        engine's hook for freeing pool pages under admission pressure.
        In-flight entries (a restore mid-transfer) are pinned."""
        victim = next(
            (e for e in self._lru.values() if e.tier != "in-flight"), None
        )
        if victim is None:
            return False
        self._evict(victim)
        return True

    def drop_namespace(self, namespace: str) -> int:
        """Evict every entry stored under ``namespace`` — an unloaded
        adapter's KV must never be spliceable again (its slot id may be
        reused by a different tenant)."""
        victims = [e for e in self._lru.values() if e.ns == namespace]
        for entry in victims:
            self._evict(entry)
        self._roots.pop(namespace, None)
        return len(victims)

    def _evict(self, entry: _Entry) -> None:
        self._lru.pop((entry.ns, entry.key), None)
        if isinstance(entry.cache, HostRun):
            # host-resident: the device was credited at demotion; dropping
            # the entry only returns its host slots
            self._host_pool.free(entry.cache.slots)
        elif self._pool is not None:
            # physical credit: only pages dropping their LAST cache
            # reference (shared pages stay charged to the surviving entries)
            self.total_bytes -= (
                self._pool.cache_release(entry.cache.pages)
                * self._pool.page_bytes
            )
        else:
            self.total_bytes -= entry.nbytes
        self.evictions_total += 1
        node = entry.node
        node.entry = None
        walk = node
        while walk is not None:
            walk.n_entries -= 1
            walk = walk.parent
        # prune now-dead branches so the trie never outgrows the live entries
        while (node.parent is not None and node.entry is None
               and not node.edges):
            parent = node.parent
            for first, (_, child) in list(parent.edges.items()):
                if child is node:
                    del parent.edges[first]
                    break
            node = parent
        for ns, root in list(self._roots.items()):
            if root.n_entries == 0 and not root.edges:
                del self._roots[ns]

    def stats(self) -> dict[str, int]:
        return {
            "entries": len(self._lru),
            "entries_host": sum(
                1 for e in self._lru.values() if e.tier == "host"
            ),
            "bytes": self.total_bytes,
            "budget_bytes": self.budget_bytes,
            "evictions_total": self.evictions_total,
            "namespaces": len(self._roots),
        }


def _tree_nbytes(cache: Any) -> int:
    import jax

    return sum(leaf.nbytes for leaf in jax.tree.leaves(cache))
