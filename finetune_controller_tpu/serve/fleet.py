"""Replica fleet: N health-checked engine replicas behind one served job.

The PR-4/6 serving plane was ONE engine process — a crash, a stuck decode, or
a checkpoint rollover took every in-flight request with it.  Promoted
checkpoints are immutable artifacts, so replicas are cattle
(docs/serving.md §Fleet): this module owns the herd for one job —

* each :class:`Replica` is a full serving stack (its own
  :class:`~finetune_controller_tpu.serve.engine.BatchEngine` +
  :class:`~finetune_controller_tpu.serve.batcher.Batcher`), the in-process
  equivalent of one ``ServeManager`` per process;
* **health** rides the same liveness idea as the trainer heartbeats
  (``resilience/heartbeat.py``): a replica with work in flight whose engine
  stops completing decode steps for ``stall_timeout_s`` — or whose drive
  loop survives a decode-step fault — is marked unhealthy, torn down (its
  requests fail with :class:`ReplicaUnavailable`, which the router retries
  on a survivor), and restarted with the resilience layer's seeded
  decorrelated-jitter backoff (``resilience/policy.py::RetryPolicy``) under
  a bounded attempt budget, exactly the supervisor pattern training uses;
* **drain** is the only way capacity leaves the fleet voluntarily: new
  admissions stop, queued requests bounce retryably, in-flight lanes finish
  (checkpoint rollover and scheduler-driven scale-down both go through it —
  never through a kill);
* **rollover** spins replicas on the NEW checkpoint first, shifts traffic
  (the router prefers the newest generation), and only then drains the old
  generation — no stop-the-world swap;
* the seeded chaos hand (``resilience/faults.py::ServeFault``) can kill or
  wedge a chosen replica at a chosen decode step, the injection path the
  serve-chaos tests and ``BENCH_MODE=serve`` share;
* **transport** (docs/serving.md §Cross-process transport): with a
  ``transport`` attached (``serve_transport=process``), every replica is a
  separate WORKER PROCESS — its own JAX runtime behind an RPC socket — and
  the fleet consumes it through the same batcher-shaped surface
  (``transport/client.py::RemoteReplica``), so health checks, failover,
  drain, rollover, adapter sync and autoscale run unchanged; detection adds
  a heartbeat lease (a SIGKILLed or wedged worker stops beating) and the
  respawn path spawns a fresh sandboxed process instead of an engine.
"""

from __future__ import annotations

import asyncio
import dataclasses
import enum
import itertools
import logging
import time
from typing import Any, Awaitable, Callable

from ..resilience.faults import ServeFaultInjector
from ..resilience.policy import RETRYABLE, RetryPolicy, classify_failure
from .batcher import Batcher, ReplicaUnavailable
from .engine import BatchEngine, EngineConfig, warm_engine

logger = logging.getLogger(__name__)


class AdapterBusy(RuntimeError):
    """Unload refused: the tenant still has requests queued or in flight."""


class ReplicaState(str, enum.Enum):
    HEALTHY = "healthy"
    DRAINING = "draining"
    FAILED = "failed"
    STOPPED = "stopped"


@dataclasses.dataclass
class Replica:
    """One serving stack inside the fleet — in-process (``batcher`` is a
    :class:`Batcher`) or a worker process (``batcher`` is a
    :class:`~finetune_controller_tpu.transport.client.RemoteReplica`, which
    implements the same surface)."""

    replica_id: str
    generation: int
    batcher: Any
    remote: bool = False
    state: ReplicaState = ReplicaState.HEALTHY
    started_at: float = 0.0
    #: clock reading when the engine last made observable progress (a decode
    #: step completed, or the replica was verifiably idle) — the health lease
    last_progress: float = 0.0
    last_steps_total: int = 0
    last_step_errors: int = 0

    @property
    def engine(self) -> BatchEngine:
        return self.batcher.engine

    @property
    def healthy(self) -> bool:
        return self.state is ReplicaState.HEALTHY

    def load(self) -> int:
        """Routing weight: queued + decoding requests on this replica."""
        return self.batcher.queue_depth + self.batcher.slots_busy

    def stats(self) -> dict[str, Any]:
        return {
            "state": self.state.value,
            "generation": self.generation,
            **self.batcher.stats(),
        }


@dataclasses.dataclass
class _PendingRestart:
    due_at: float
    prev_delay_s: float
    reason: str


class ReplicaFleet:
    """The replica set for one served job (docs/serving.md §Fleet).

    ``payload`` is the loaded serving model ``(model, variables)``; engine
    construction is heavy (a forward trace + first-use compiles) and always
    runs in a worker thread.  ``event_cb`` (async, best-effort) lands fleet
    decisions on the job's timeline.
    """

    #: per-replica stats that are cumulative COUNTERS: folded into
    #: ``_retired_totals`` when a replica leaves so aggregates never regress
    _COUNTER_KEYS = (
        "steps_total", "tokens_generated_total", "requests_completed_total",
        "requests_rejected_total", "deadline_drops_total",
        "step_errors_total", "prefix_hits_total", "prefix_misses_total",
        "prefill_tokens_saved_total", "kv_cow_copies_total",
        "kv_pool_exhaustions_total", "kv_demotions_total",
        "kv_restores_total",
    )
    #: point-in-time gauges: summed over LIVE replicas only
    _GAUGE_KEYS = (
        "queue_depth", "slots_busy", "slots_total", "compilations",
        "prefix_cache_bytes", "prefix_cache_entries",
        "kv_pages_total", "kv_pages_free", "kv_pages_used",
        "kv_pages_shared", "kv_tier_host_pages_total",
        "kv_tier_host_pages_used", "kv_tier_host_bytes",
    )
    #: per-tenant counter DICTS ({adapter_id: n}): folded like the scalar
    #: counters so retired replicas' tenant tokens never regress
    _DICT_COUNTER_KEYS = ("tokens_by_tenant",)
    #: per-tenant gauge dicts: summed over live replicas only
    _DICT_GAUGE_KEYS = ("queue_depth_by_tenant", "lanes_by_tenant")

    def __init__(
        self,
        job_id: str,
        model: Any,
        variables: dict,
        engine_config: EngineConfig,
        *,
        replicas: int = 1,
        batcher_kwargs: dict[str, Any] | None = None,
        stall_timeout_s: float = 15.0,
        drain_timeout_s: float = 30.0,
        restart_policy: RetryPolicy | None = None,
        fault: ServeFaultInjector | None = None,
        event_cb: Callable[..., Awaitable[Any]] | None = None,
        clock: Callable[[], float] = time.monotonic,
        warm_start: bool = True,
        adapters: "Any | None" = None,
        transport: "Any | None" = None,
        reward_spec: "dict[str, Any] | None" = None,
    ):
        self.job_id = job_id
        #: spec section forwarded to every worker spawn when the served job
        #: is a ``task: reward`` model: workers then load the reward head
        #: and answer the batched ``reward_score`` RPC
        #: (``prefs/rollout_plane.py::RewardScorer``).  Process transport
        #: only; in-process replicas have no RPC surface to expose it on.
        self.reward_spec = dict(reward_spec) if reward_spec else None
        #: cross-process mode: a ``transport/process.py::ProcessTransport``
        #: (or anything with its ``spawn``/``mode`` surface) — replicas are
        #: worker processes and ``model``/``variables`` may be None (the
        #: control plane never holds serving weights in that mode)
        self.transport = transport
        if transport is None and model is None:
            raise ValueError(
                "an in-process fleet needs (model, variables); pass a "
                "transport for process-mode replicas"
            )
        self._model = model
        self._variables = variables
        self._engine_config = engine_config
        #: shared multi-tenant adapter registry (serve/adapters.py); every
        #: replica engine holds its own device copy of the stacks, synced
        #: here on register/unregister/spawn/rollover
        self.adapters = adapters
        self.target_replicas = max(1, replicas)
        self._batcher_kwargs = dict(batcher_kwargs or {})
        self.stall_timeout_s = stall_timeout_s
        self.drain_timeout_s = drain_timeout_s
        #: restart budget + backoff for crashed/stuck replicas — the same
        #: policy shape the training retry supervisor runs
        self.restart_policy = restart_policy or RetryPolicy()
        self._fault = fault if fault is not None \
            else ServeFaultInjector.from_env()
        self._event_cb = event_cb
        self._clock = clock
        #: pay every prefill-bucket + decode compile at spawn, BEFORE the
        #: replica takes traffic — the zero-downtime rollover contract
        #: depends on a fresh generation not compiling under load
        self.warm_start = warm_start
        self.generation = 0
        self._replicas: dict[str, Replica] = {}
        self._seq = itertools.count()
        self._restarts_pending: list[_PendingRestart] = []
        #: consecutive failed/stuck replicas since the fleet last looked
        #: fully healthy — the restart policy's attempt counter
        self._failure_streak = 0
        #: last backoff delay handed out this streak — feeds next_delay so
        #: the decorrelated-jitter schedule actually grows across a crash
        #: loop (reset when the streak resets)
        self._last_restart_delay: float | None = None
        self._health_task: asyncio.Task | None = None
        self._closed = False
        # counters (/metrics + GET /admin/serve)
        self.replica_restarts_total = 0
        self.replicas_failed_total = 0
        self.drains_total = 0
        self.rollovers_total = 0
        #: counter totals folded in from replicas that left the fleet —
        #: the aggregate /metrics counters must stay monotonic across
        #: drains/restarts/rollovers
        self._retired_totals: dict[str, int] = {
            k: 0 for k in self._COUNTER_KEYS
        }
        self._retired_dict_totals: dict[str, dict[str, int]] = {
            k: {} for k in self._DICT_COUNTER_KEYS
        }

    # ---- events ------------------------------------------------------------

    async def _event(self, event: str, **attrs) -> None:
        if self._event_cb is None:
            return
        try:
            await self._event_cb(event, **attrs)
        except Exception:
            logger.debug("fleet event %s failed", event, exc_info=True)

    # ---- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        """Spawn the initial replica set.  Worker processes spawn
        CONCURRENTLY (each builds in its own process; the wall-clock is one
        spawn, not the fleet size times one); in-process engines build
        serially — parallel first-use XLA compiles in one runtime would
        race."""
        if self.transport is not None:
            await asyncio.gather(
                *(self.spawn_replica() for _ in range(self.target_replicas))
            )
            return
        for _ in range(self.target_replicas):
            await self.spawn_replica()

    def _build_engine(self) -> BatchEngine:
        """Worker-thread body: construct and (by default) WARM the engine —
        every compile this replica will ever need lands before it serves
        traffic (``engine.warm_engine``, shared with the transport worker's
        startup so process-mode replicas warm-start identically)."""
        engine = BatchEngine(self._model, self._variables,
                             self._engine_config, adapters=self.adapters)
        if self.warm_start:
            warm_engine(engine)
        return engine

    async def spawn_replica(self) -> Replica:
        """Put one replica in service: build an engine in a worker thread
        (in-process), or spawn + handshake a worker process (transport) and
        sync the adapter registry onto it."""
        rid = f"r{next(self._seq)}"
        if self.transport is not None:
            batcher = await self.transport.spawn(
                rid, self.generation,
                engine_config=self._engine_config,
                batcher_kwargs=self._batcher_kwargs,
                adapters=self.adapters,
                warm_start=self.warm_start,
                reward=self.reward_spec,
            )
            if self.adapters is not None and len(self.adapters):
                try:
                    from .adapters import entry_to_wire

                    wires = await asyncio.to_thread(
                        lambda: [entry_to_wire(e)
                                 for e in self.adapters.entries()]
                    )
                    await batcher.stack_sync(wires)
                except BaseException:
                    # the worker is alive but not yet in _replicas: nothing
                    # else will ever kill it — reap before propagating, or
                    # every failed respawn leaks one live process
                    await batcher.close()
                    raise
            remote = True
        else:
            engine = await asyncio.to_thread(self._build_engine)
            if self._fault is not None and self._fault.arm(rid, engine):
                logger.warning("replica %s armed with a serve fault", rid)
            batcher = Batcher(engine, **self._batcher_kwargs)
            remote = False
        now = self._clock()
        replica = Replica(
            replica_id=rid, generation=self.generation, batcher=batcher,
            remote=remote, started_at=now, last_progress=now,
        )
        self._replicas[rid] = replica
        await self._event(
            "serve-replica-started", replica=rid, generation=self.generation,
            transport=self.transport_mode,
        )
        logger.info("serve replica %s started (job=%s gen=%d transport=%s)",
                    rid, self.job_id, self.generation, self.transport_mode)
        return replica

    @property
    def transport_mode(self) -> str:
        return getattr(self.transport, "mode", None) or "inproc"

    # ---- multi-tenant adapters ---------------------------------------------

    async def register_adapter(self, adapter_id: str, lora_tree: Any,
                               alpha: float, rank: int,
                               meta: dict[str, Any] | None = None) -> int:
        """Register a tenant and install its stacks on EVERY live replica
        (device writes run in a worker thread; the engine swaps its tenants
        reference atomically, so in-flight steps are never torn).  Replicas
        spawned or rolled over later sync from the registry at build time."""
        if self.adapters is None:
            raise RuntimeError(
                "fleet has no adapter registry (serve_max_adapters=0)"
            )
        refresh = self.adapters.get(adapter_id) is not None
        entry = self.adapters.register(adapter_id, lora_tree, alpha, rank,
                                       meta=meta)
        entry_wire = None
        if any(r.remote for r in self._replicas.values()):
            from .adapters import entry_to_wire

            entry_wire = await asyncio.to_thread(entry_to_wire, entry)
        for replica in list(self._replicas.values()):
            if replica.remote:
                # registry-sync RPC: the worker registers + installs + (on
                # refresh) drops the namespace itself, same ordering as the
                # in-process path below
                await replica.batcher.adapter_register(entry_wire,
                                                       refresh=refresh)
                continue
            await asyncio.to_thread(replica.engine.install_adapter,
                                    adapter_id)
            if refresh:
                # tenant rollover: the deltas changed, so KV cached under
                # the old weights is poison for the new ones.  Drop AFTER
                # the (atomic) stack swap: an admission racing the drop can
                # only re-seed the namespace with NEW-weight KV, whereas
                # dropping first would let a racing old-stack admission
                # poison the fresh namespace permanently
                replica.engine.drop_prefix_namespace(adapter_id)
        await self._event(
            "serve-adapter-loaded", adapter=adapter_id, slot=entry.slot,
            rank=rank,
        )
        logger.info("adapter %s installed on %d replica(s) (job=%s slot=%d)",
                    adapter_id, len(self._replicas), self.job_id, entry.slot)
        return entry.slot

    async def unregister_adapter(self, adapter_id: str) -> None:
        """Remove a tenant: refuses while the tenant has queued or decoding
        requests anywhere in the fleet (its slot id may be reused — evicting
        live lanes would hand their KV to a stranger), then zeroes the slot
        and drops the tenant's prefix-cache namespace on every replica."""
        if self.adapters is None:
            raise RuntimeError(
                "fleet has no adapter registry (serve_max_adapters=0)"
            )
        busy = 0
        for replica in self._replicas.values():
            # tenant_busy covers the admission window the engine's lane view
            # misses (a request mid-admit in the worker thread has no lane
            # yet but HAS already resolved its adapter slot); for remote
            # replicas it is a FRESH rpc, never a stale probe cache
            busy += await replica.batcher.tenant_busy(adapter_id)
        if busy:
            raise AdapterBusy(
                f"adapter {adapter_id!r} has {busy} request(s) in flight or "
                "queued; drain them (or wait) before unloading"
            )
        entry = self.adapters.unregister(adapter_id)
        for replica in list(self._replicas.values()):
            if replica.remote:
                await replica.batcher.adapter_unregister(adapter_id)
                continue
            await asyncio.to_thread(
                replica.engine.remove_adapter, adapter_id, entry.slot
            )
        await self._event("serve-adapter-unloaded", adapter=adapter_id)

    def healthy_replicas(self) -> list[Replica]:
        return [r for r in self._replicas.values() if r.healthy]

    @property
    def replicas(self) -> dict[str, Replica]:
        return self._replicas

    async def drain_replica(self, replica_id: str, *, reason: str) -> bool:
        """Graceful removal: no new admissions, queued requests bounce
        retryably, in-flight lanes finish (bounded by ``drain_timeout_s``).
        The ONLY path scale-down and rollover use — never a kill."""
        replica = self._replicas.get(replica_id)
        if replica is None or replica.state in (
            ReplicaState.DRAINING, ReplicaState.STOPPED
        ):
            return False
        replica.state = ReplicaState.DRAINING
        self.drains_total += 1
        drained = await replica.batcher.drain(self.drain_timeout_s)
        replica.state = ReplicaState.STOPPED
        self._retire(replica)
        self._replicas.pop(replica_id, None)
        await self._event(
            "serve-replica-drained", replica=replica_id, reason=reason,
            clean=drained,
        )
        logger.info("serve replica %s drained (%s, clean=%s)",
                    replica_id, reason, drained)
        return drained

    async def fail_replica(
        self, replica_id: str, *, error: str, restart: bool = True
    ) -> None:
        """Immediate teardown of a crashed/stuck replica: its requests fail
        with :class:`ReplicaUnavailable` (the router re-enqueues them on a
        survivor) and a restart is scheduled with backoff when the attempt
        budget allows."""
        replica = self._replicas.pop(replica_id, None)
        if replica is None:
            return
        replica.state = ReplicaState.FAILED
        self.replicas_failed_total += 1
        self._retire(replica)
        await replica.batcher.close(ReplicaUnavailable(
            f"replica {replica_id} torn down: {error}"
        ))
        failure = classify_failure(None, error)
        self._failure_streak += 1
        await self._event(
            "serve-replica-unhealthy", replica=replica_id, error=error,
            failure_class=failure.value,
        )
        if not restart or self._closed:
            return
        if failure in RETRYABLE \
                and self._failure_streak <= self.restart_policy.max_attempts:
            delay = self.restart_policy.next_delay(self._last_restart_delay)
            self._last_restart_delay = delay
            self._restarts_pending.append(_PendingRestart(
                due_at=self._clock() + delay, prev_delay_s=delay, reason=error,
            ))
            logger.warning(
                "serve replica %s failed (%s); restart in %.1fs "
                "(streak %d/%d)", replica_id, error, delay,
                self._failure_streak, self.restart_policy.max_attempts,
            )
        elif not self._replicas and not self._restarts_pending:
            # budget spent AND the fleet just hit ZERO replicas: a fully
            # dead fleet with no pending restart would 503 forever (and,
            # under autoscale, hold its admitted chips against training
            # indefinitely).  Keep exactly one slow revival probe pending
            # at the backoff ceiling — bounded cadence, never a storm.
            delay = self.restart_policy.max_delay_s
            self._last_restart_delay = delay
            self._restarts_pending.append(_PendingRestart(
                due_at=self._clock() + delay, prev_delay_s=delay,
                reason=f"revival probe after: {error}",
            ))
            logger.error(
                "serve replica %s failed (%s); restart budget exhausted "
                "(%d/%d) and no replicas remain — probing revival every "
                "%.0fs", replica_id, error, self._failure_streak,
                self.restart_policy.max_attempts, delay,
            )
        else:
            logger.error(
                "serve replica %s failed (%s); restart budget exhausted "
                "(%d/%d) — fleet degraded to %d replica(s)",
                replica_id, error, self._failure_streak,
                self.restart_policy.max_attempts, len(self._replicas),
            )

    # ---- health ------------------------------------------------------------

    async def health_tick(self) -> dict[str, list[str]]:
        """One health pass: catch dead, faulted and stalled replicas, run
        due restarts.  Returns the actions taken (tests assert on them).

        Every replica answers ONE :meth:`~finetune_controller_tpu.serve.
        batcher.Batcher.health_probe` — live values in-process; for a worker
        process the probe stack is process-exit check → heartbeat lease →
        RPC, so a SIGKILLed worker, a wedged event loop, and a stalled
        decode are all caught here and answered with a kill + respawn
        (the LeaseChecker pattern, docs/serving.md §Cross-process
        transport).  Probes run concurrently: a slow worker costs one
        timeout, not the whole tick times the fleet size.
        """
        now = self._clock()
        actions: dict[str, list[str]] = {"failed": [], "restarted": []}
        checked = [r for r in list(self._replicas.values()) if r.healthy]

        async def probe_one(replica: Replica):
            try:
                return await replica.batcher.health_probe(), None
            # ftc: ignore[silent-except] -- not swallowed: a failed probe fails the replica below
            except Exception as exc:
                return None, exc

        probes = await asyncio.gather(*(probe_one(r) for r in checked))
        for replica, (probe, probe_err) in zip(checked, probes):
            if replica.replica_id not in self._replicas \
                    or not replica.healthy:
                # removed by an earlier failure this tick, or a concurrent
                # drain flipped it mid-probe (a draining replica's torn
                # connection fails the probe — that is the drain, not a
                # crash; failing it here would double-retire its counters
                # and queue a spurious restart)
                continue
            if probe_err is not None:
                # dead process, stale heartbeat, torn socket, rpc timeout —
                # the replica cannot prove liveness, so it is failed (and,
                # for a worker process, killed) + restarted with backoff
                actions["failed"].append(replica.replica_id)
                await self.fail_replica(
                    replica.replica_id,
                    error=f"liveness probe failed: {probe_err}",
                )
                continue
            if probe["step_errors_total"] > replica.last_step_errors:
                # the drive loop survived a decode fault (it keeps serving),
                # but a faulting engine is a crashed replica from the
                # fleet's point of view: tear down + restart with backoff
                actions["failed"].append(replica.replica_id)
                await self.fail_replica(
                    replica.replica_id,
                    error=f"decode step fault: {probe['last_step_error']}",
                )
                continue
            if probe["steps_total"] > replica.last_steps_total \
                    or probe["slots_busy"] == 0:
                replica.last_steps_total = probe["steps_total"]
                replica.last_progress = now
            elif now - replica.last_progress > self.stall_timeout_s:
                # work in flight, no decode step completing: the
                # stuck-decode shape — the replica holds lanes forever and
                # only this active check can reclaim them
                actions["failed"].append(replica.replica_id)
                await self.fail_replica(
                    replica.replica_id,
                    error=(
                        f"stuck decode: no step completed in "
                        f"{now - replica.last_progress:.1f}s with "
                        f"{probe['slots_busy']} request(s) in flight"
                    ),
                )
                continue
        if not self._restarts_pending \
                and len(self._replicas) >= self.target_replicas \
                and all(r.healthy for r in self._replicas.values()):
            # fleet fully healthy again: a future failure is a fresh
            # incident, not attempt N+1 of this one
            self._failure_streak = 0
            self._last_restart_delay = None
        due = [p for p in self._restarts_pending if p.due_at <= now]
        for pending in due:
            self._restarts_pending.remove(pending)
            if self._closed or len(self._replicas) >= self.target_replicas:
                continue
            try:
                replica = await self.spawn_replica()
            # ftc: ignore[silent-except] -- not swallowed: logged and rescheduled with grown backoff
            except Exception:
                # a worker-process spawn can itself fail (port races, a sick
                # host); reschedule the restart with the next backoff step
                # instead of silently dropping the slot from the fleet
                delay = self.restart_policy.next_delay(self._last_restart_delay)
                self._last_restart_delay = delay
                self._restarts_pending.append(_PendingRestart(
                    due_at=self._clock() + delay, prev_delay_s=delay,
                    reason=f"respawn failed after: {pending.reason}",
                ))
                logger.exception(
                    "serve replica respawn failed (job=%s); retrying in "
                    "%.1fs", self.job_id, delay,
                )
                continue
            self.replica_restarts_total += 1
            if replica.remote:
                from ..transport import incr as _transport_incr

                _transport_incr("worker_respawns_total")
            actions["restarted"].append(replica.replica_id)
            await self._event(
                "serve-replica-restarted", replica=replica.replica_id,
                after=pending.reason,
            )
        return actions

    def start_health_loop(self, interval_s: float) -> None:
        """Background health checks at ``interval_s`` (restarted if dead)."""
        if self._health_task is None or self._health_task.done():
            self._health_task = asyncio.get_running_loop().create_task(
                self._health_loop(max(0.05, interval_s))
            )

    async def _health_loop(self, interval_s: float) -> None:
        while not self._closed:
            try:
                await self.health_tick()
            # ftc: ignore[silent-except] -- logged: the health loop must outlive any single tick's failure
            except Exception:
                logger.exception("fleet health tick failed (job=%s)",
                                 self.job_id)
            await asyncio.sleep(interval_s)

    # ---- rollover ----------------------------------------------------------

    async def rollover(self, model: Any, variables: dict,
                       *, reason: str = "checkpoint rollover") -> None:
        """Zero-downtime payload swap: spin up the new generation FIRST,
        shift traffic (the router prefers the newest generation), then drain
        the old generation — in-flight lanes finish on the weights they
        started on.

        Process mode: the caller repoints the transport's payload (a freshly
        staged deploy dir) BEFORE calling this with ``model=variables=None``
        — new-generation workers rebuild from it; the control plane never
        holds the weights."""
        old = [r for r in self._replicas.values() if r.healthy]
        if self.transport is None:
            self._model = model
            self._variables = variables
        self.generation += 1
        self.rollovers_total += 1
        await self._event(
            "serve-rollover-started", generation=self.generation,
            reason=reason, old_replicas=len(old),
        )
        if self.transport is not None:
            await asyncio.gather(
                *(self.spawn_replica() for _ in range(max(1, len(old))))
            )
        else:
            for _ in range(max(1, len(old))):
                await self.spawn_replica()
        await asyncio.gather(*(
            self.drain_replica(r.replica_id, reason=reason) for r in old
        ))
        await self._event(
            "serve-rollover-completed", generation=self.generation,
        )

    async def close(self) -> None:
        self._closed = True
        if self._health_task is not None:
            self._health_task.cancel()
            try:
                await self._health_task
            except asyncio.CancelledError:
                pass
            self._health_task = None
        for replica in list(self._replicas.values()):
            await replica.batcher.close()
        self._replicas.clear()

    # ---- observability -----------------------------------------------------

    @staticmethod
    def _sum_dicts(into: dict[str, int], add: dict[str, int]) -> dict[str, int]:
        for k, v in (add or {}).items():
            into[k] = into.get(k, 0) + v
        return into

    def _retire(self, replica: Replica) -> None:
        stats = replica.batcher.stats()
        for key in self._COUNTER_KEYS:
            self._retired_totals[key] += stats.get(key, 0)
        for key in self._DICT_COUNTER_KEYS:
            self._sum_dicts(self._retired_dict_totals[key], stats.get(key))

    def stats(self) -> dict[str, Any]:
        """The PR-4 aggregate stats shape every existing consumer reads —
        counters are monotonic (retired replicas' totals folded in), gauges
        sum over live replicas — plus the per-replica rows."""
        replicas = {rid: r.stats() for rid, r in self._replicas.items()}
        agg: dict[str, Any] = {
            k: sum(r.get(k, 0) for r in replicas.values())
            for k in self._GAUGE_KEYS
        }
        for k in self._COUNTER_KEYS:
            agg[k] = self._retired_totals[k] + sum(
                r.get(k, 0) for r in replicas.values()
            )
        for k in self._DICT_COUNTER_KEYS:
            total = dict(self._retired_dict_totals[k])
            for r in replicas.values():
                self._sum_dicts(total, r.get(k) or {})
            agg[k] = total
        for k in self._DICT_GAUGE_KEYS:
            total: dict[str, int] = {}
            for r in replicas.values():
                self._sum_dicts(total, r.get(k) or {})
            agg[k] = total
        agg["adapters_loaded"] = (
            len(self.adapters) if self.adapters is not None else 0
        )
        agg["adapters"] = (
            self.adapters.stats()["adapters"]
            if self.adapters is not None else {}
        )
        agg.update({
            "replicas": replicas,
            "replicas_total": len(replicas),
            "replicas_healthy": sum(
                1 for r in self._replicas.values() if r.healthy
            ),
            "replicas_draining": sum(
                1 for r in self._replicas.values()
                if r.state is ReplicaState.DRAINING
            ),
            "generation": self.generation,
            "target_replicas": self.target_replicas,
            "transport": self.transport_mode,
            "worker_pids": sorted(
                r.batcher.pid for r in self._replicas.values() if r.remote
            ),
            "replica_restarts_total": self.replica_restarts_total,
            "replicas_failed_total": self.replicas_failed_total,
            "drains_total": self.drains_total,
            "rollovers_total": self.rollovers_total,
            "restarts_pending": len(self._restarts_pending),
        })
        return agg
