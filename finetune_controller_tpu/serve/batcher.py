"""Asyncio admission layer over :class:`~finetune_controller_tpu.serve.engine.BatchEngine`.

The engine is host-driven and synchronous; this wraps it in the control
plane's event loop:

* requests enter a bounded queue — **backpressure**: past ``max_queue`` the
  caller gets :class:`QueueFull` (the service maps it to HTTP 429) instead of
  unbounded memory growth;
* a single drive task admits queued requests into free lanes between decode
  steps (``max_batch`` lanes; a request joins mid-flight, never waits for the
  batch to drain) and runs the jitted step in a worker thread so the loop
  stays responsive;
* **deadlines**: a request that waited in the queue past its deadline is
  dropped with :class:`DeadlineExceeded` before ever touching the engine; an
  admitted request past its deadline is evicted between steps.  A caller may
  pass an ABSOLUTE ``deadline`` instead of a relative timeout — the fleet
  router (``serve/router.py``) uses this so a failover re-enqueue keeps the
  request's ORIGINAL deadline rather than minting a fresh one;
* ``max_wait_ms`` is the idle park interval: with nothing queued and nothing
  in flight the driver sleeps that long between re-checks rather than
  spinning.  Submissions wake it immediately (the ``_wake`` event), so the
  knob only bounds how stale the fallback re-check can go — floored at 1 ms
  so a zero can never busy-spin the loop;
* **drain** (docs/serving.md §Fleet): :meth:`drain` stops admissions, bounces
  still-queued requests with :class:`ReplicaUnavailable` (retryable on a
  survivor — they never touched a lane) and lets in-flight lanes finish
  before closing — the zero-downtime half of checkpoint rollover and of
  scheduler-driven scale-down;
* **per-tenant fairness** (docs/serving.md §Multi-tenant adapters): the
  queue is one FIFO per tenant (``GenRequest.adapter_id``; "" = the base
  model) admitted by deficit round robin — each round every waiting tenant
  earns ``drr_quantum_tokens`` of credit and admits requests while its
  credit covers their token cost (prompt + max_new), so one hot tenant
  flooding the queue cannot starve the others, while a single-tenant
  workload degenerates to the original FIFO exactly;
* the engine's :meth:`~finetune_controller_tpu.serve.engine.BatchEngine.
  can_admit` gates admission, so paged-KV pool pressure keeps requests
  QUEUED (and a full queue 429s with a derived ``Retry-After``) instead of
  failing them mid-admission.
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import logging
import time
from typing import Any

from .engine import BatchEngine, GenRequest, GenResult
from .kv_pages import PoolExhausted

logger = logging.getLogger(__name__)


class QueueFull(RuntimeError):
    """Admission queue at capacity — shed load (HTTP 429).

    ``retry_after_s`` (when known) is the batcher's drain-time estimate; the
    HTTP layer surfaces it as a ``Retry-After`` header so callers back off
    for a useful interval instead of guessing.
    """

    def __init__(self, message: str, retry_after_s: float | None = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before it finished."""


class ReplicaUnavailable(RuntimeError):
    """The replica serving this request died or is draining.

    The request did NOT complete (queued requests never touched a lane;
    in-flight lanes were evicted), so it is safe for the router to re-enqueue
    it on a surviving replica — the exactly-once contract holds because the
    failed attempt produced no result.
    """


@dataclasses.dataclass
class _Pending:
    req: GenRequest
    future: asyncio.Future
    enqueued_at: float
    deadline: float | None  # monotonic instant, None = no deadline


class Batcher:
    """One drive loop per served model; owns the engine between steps."""

    def __init__(
        self,
        engine: BatchEngine,
        *,
        max_queue: int = 64,
        max_wait_ms: float = 1000.0,
        default_timeout_s: float = 60.0,
        ttft_observe=None,
        drr_quantum_tokens: float = 256.0,
    ):
        self.engine = engine
        self.max_queue = max_queue
        self.max_wait_ms = max_wait_ms
        self.default_timeout_s = default_timeout_s
        #: time-to-first-token callback (seconds) — the obs hub's
        #: ``ftc_serve_ttft_seconds`` histogram (docs/observability.md);
        #: observed at admission: the prefill that admits a request also
        #: produces its first token
        self.ttft_observe = ttft_observe
        #: deficit-round-robin quantum: token-cost credit every waiting
        #: tenant earns per admission round (``serve_drr_quantum_tokens``)
        self.drr_quantum_tokens = max(1.0, drr_quantum_tokens)
        #: one FIFO per tenant, admitted by deficit round robin
        self._queues: collections.OrderedDict[
            str, collections.deque[_Pending]
        ] = collections.OrderedDict()
        self._deficit: dict[str, float] = {}
        self._inflight: dict[str, _Pending] = {}
        self._wake = asyncio.Event()
        self._task: asyncio.Task | None = None
        self._closed = False
        self._draining = False
        # counters surfaced by /metrics
        self.rejected_total = 0
        self.deadline_drops_total = 0
        self.completed_total = 0
        #: decode-step faults the drive loop survived (fleet health checks
        #: read this: a replica whose steps fault is torn down + restarted)
        self.step_errors_total = 0
        self.last_step_error: BaseException | None = None
        #: recent decode-step completion instants (monotonic) — the decode
        #: rate half of the Retry-After estimate
        self._step_stamps: collections.deque[float] = collections.deque(maxlen=64)
        #: EMA of decode steps per completed request — the work-per-request
        #: half of the Retry-After estimate
        self._avg_request_steps: float | None = None

    # ---- public surface ---------------------------------------------------

    @property
    def queue_depth(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def queue_depth_by_tenant(self) -> dict[str, int]:
        return {t: len(q) for t, q in self._queues.items() if q}

    def queued(self) -> list[_Pending]:
        """Snapshot of everything queued, in per-tenant FIFO order."""
        return [p for q in self._queues.values() for p in q]

    def inflight_by_tenant(self) -> dict[str, int]:
        """Requests registered in-flight (admitted OR mid-admission in the
        worker thread) per tenant — the engine's lane view alone misses the
        admission window, which matters to adapter-unload busy checks."""
        out: dict[str, int] = {}
        for p in self._inflight.values():
            tenant = p.req.adapter_id or ""
            out[tenant] = out.get(tenant, 0) + 1
        return out

    def _drain_queues(self) -> list[_Pending]:
        """Pop everything queued (drain/close paths)."""
        out: list[_Pending] = []
        for q in self._queues.values():
            out.extend(q)
        self._queues.clear()
        self._deficit.clear()
        return out

    @property
    def slots_busy(self) -> int:
        return self.engine.active_requests

    @property
    def _park_timeout_s(self) -> float:
        """Idle re-check interval of :meth:`_drive` — ``max_wait_ms`` with a
        1 ms floor (pinned in ``tests/test_serve.py``)."""
        return max(self.max_wait_ms, 1.0) / 1000.0

    def start(self) -> None:
        # restart a dead drive task too: a crashed loop (engine fault) must
        # not leave the batcher permanently accepting-but-never-serving
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(self._drive())

    async def close(self, exc: BaseException | None = None) -> None:
        """Tear down; pending futures fail with ``exc`` (default: the
        shutdown :class:`DeadlineExceeded` — a fleet teardown passes
        :class:`ReplicaUnavailable` instead so the router can fail over)."""
        self._closed = True
        self._wake.set()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        for p in self._drain_queues() + list(self._inflight.values()):
            if not p.future.done():
                p.future.set_exception(
                    exc if exc is not None
                    else DeadlineExceeded("server shutting down")
                )
        self._inflight.clear()

    async def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful shutdown: refuse new admissions, bounce still-QUEUED
        requests with :class:`ReplicaUnavailable` (they never touched a lane
        — a router retries them on a survivor), let IN-FLIGHT lanes finish,
        then close.  Returns True when every in-flight request completed
        within ``timeout_s`` (stragglers past it fail retryably too)."""
        self._draining = True
        bounced = self._drain_queues()
        for p in bounced:
            if not p.future.done():
                p.future.set_exception(ReplicaUnavailable(
                    f"request {p.req.request_id} bounced: replica draining"
                ))
        deadline = time.monotonic() + max(0.0, timeout_s)
        while self._inflight and time.monotonic() < deadline:
            self._wake.set()
            await asyncio.sleep(0.005)
        drained = not self._inflight
        if not drained:
            logger.warning(
                "drain timed out with %d request(s) still in flight; "
                "failing them over", len(self._inflight),
            )
        await self.close(ReplicaUnavailable("replica drained away"))
        return drained

    @property
    def draining(self) -> bool:
        return self._draining

    async def health_probe(self) -> dict[str, Any]:
        """Liveness + decode-progress snapshot, the fleet health check's ONE
        input (docs/serving.md §Fleet).  Async so the in-process Batcher and
        the cross-process :class:`~finetune_controller_tpu.transport.client.
        RemoteReplica` (where this is an RPC with a heartbeat-lease check in
        front) share a surface — the fleet cannot tell them apart."""
        return {
            "steps_total": self.engine.steps_total,
            "slots_busy": self.slots_busy,
            "queue_depth": self.queue_depth,
            "step_errors_total": self.step_errors_total,
            "last_step_error": (
                str(self.last_step_error)
                if self.last_step_error is not None else None
            ),
            "draining": self._draining,
            "inflight_by_tenant": self.inflight_by_tenant(),
        }

    async def tenant_busy(self, adapter_id: str) -> int:
        """Requests queued or in flight for one tenant — the adapter-unload
        busy check.  Async for the same transport-symmetry reason as
        :meth:`health_probe` (remote replicas answer with a fresh RPC, not a
        stale cache)."""
        tenant = adapter_id or ""
        return (
            self.inflight_by_tenant().get(tenant, 0)
            + self.queue_depth_by_tenant().get(tenant, 0)
        )

    def retry_after_s(self, extra_requests: int = 1) -> float:
        """Estimated seconds until ``extra_requests`` more requests queued NOW
        would complete — queue depth × observed steps-per-request over the
        observed decode-step rate (lanes run in parallel, so the work
        amortises over ``slots``).  The number behind the ``Retry-After``
        header on 429s; clamped to [1, 120] and 1.0 before any signal exists.
        """
        if not self._avg_request_steps or len(self._step_stamps) < 2:
            return 1.0
        span = self._step_stamps[-1] - self._step_stamps[0]
        if span <= 0:
            return 1.0
        steps_per_s = (len(self._step_stamps) - 1) / span
        lanes = max(1, self.engine.config.slots)
        work_steps = (self.queue_depth + extra_requests) * self._avg_request_steps
        eta = work_steps / (steps_per_s * lanes)
        return min(120.0, max(1.0, eta))

    async def submit(
        self,
        req: GenRequest,
        *,
        timeout_s: float | None = None,
        deadline: float | None = None,
    ) -> GenResult:
        """Queue a request and await its result (raises :class:`QueueFull`
        immediately at capacity).  ``deadline`` is an absolute
        ``time.monotonic`` instant that wins over ``timeout_s`` — failover
        re-enqueues pass the ORIGINAL deadline through it."""
        if self._draining:
            raise ReplicaUnavailable("replica is draining")
        if self._closed:
            raise QueueFull("batcher is closed")
        if self.queue_depth >= self.max_queue:
            self.rejected_total += 1
            raise QueueFull(
                f"admission queue at capacity ({self.max_queue}); retry later",
                retry_after_s=self.retry_after_s(),
            )
        now = time.monotonic()
        if deadline is None:
            timeout = self.default_timeout_s if timeout_s is None else timeout_s
            deadline = None if timeout <= 0 else now + timeout
        pending = _Pending(
            req=req,
            future=asyncio.get_running_loop().create_future(),
            enqueued_at=now,
            deadline=deadline,
        )
        tenant = req.adapter_id or ""
        if tenant not in self._queues:
            self._queues[tenant] = collections.deque()
        self._queues[tenant].append(pending)
        self.start()
        self._wake.set()
        return await pending.future

    # ---- drive loop -------------------------------------------------------

    def _drop_expired(self) -> None:
        now = time.monotonic()
        for tenant, q in list(self._queues.items()):
            keep = collections.deque()
            for p in q:
                if p.deadline is not None and now > p.deadline:
                    self.deadline_drops_total += 1
                    if not p.future.done():
                        p.future.set_exception(DeadlineExceeded(
                            f"request {p.req.request_id} spent its deadline "
                            "queued"
                        ))
                else:
                    keep.append(p)
            if keep:
                self._queues[tenant] = keep
            else:
                self._queues.pop(tenant, None)
                self._deficit.pop(tenant, None)
        for rid, p in list(self._inflight.items()):
            if p.deadline is not None and now > p.deadline:
                result = self.engine.evict(rid)
                self._inflight.pop(rid, None)
                self.deadline_drops_total += 1
                if not p.future.done():
                    p.future.set_exception(DeadlineExceeded(
                        f"request {rid} exceeded its deadline mid-decode"
                    ))
                if result is not None:
                    logger.info("evicted %s after %d tokens", rid, result.steps)

    @staticmethod
    def _cost(req: GenRequest) -> float:
        """DRR token cost: the work a request buys (prompt prefill + decode
        budget)."""
        return float(len(req.tokens) + req.max_new_tokens)

    def _select_admissions(self, budget: int) -> list[_Pending]:
        """Deficit-round-robin pick of up to ``budget`` admittable requests.

        Every round, each tenant with queued work earns ``drr_quantum_tokens``
        of credit and admits head-of-line requests while the credit covers
        their cost AND the engine can take them (free lane + paged-pool
        slack) — a blocked head (pool pressure) stays queued without
        consuming credit, and the rotation moves on so other tenants keep
        flowing.  A tenant's credit resets when its queue empties: deficits
        only ever accumulate toward the NEXT request in line, never into a
        burst allowance.
        """
        picked: list[_Pending] = []
        if budget <= 0:
            return picked
        quantum = self.drr_quantum_tokens
        # pages already promised to this batch: the engine only RESERVES at
        # admit time (in the worker thread), so the gate must account for
        # the whole batch, not each request against the same free pool
        planned_pages = 0
        while len(picked) < budget:
            progress = False
            blocked_only = True
            for tenant in list(self._queues.keys()):
                q = self._queues.get(tenant)
                if not q:
                    continue
                served = False
                self._deficit[tenant] = self._deficit.get(tenant, 0.0) + quantum
                while q and len(picked) < budget:
                    head = q[0]
                    cost = self._cost(head.req)
                    if self._deficit[tenant] < cost:
                        blocked_only = False  # still earning credit
                        break
                    if not self.engine.can_admit(head.req, planned_pages):
                        # pool/lane pressure: stays queued, credit capped to
                        # the head's cost so waiting never banks a burst
                        self._deficit[tenant] = min(self._deficit[tenant], cost)
                        break
                    q.popleft()
                    self._deficit[tenant] -= cost
                    planned_pages += self.engine.admission_pages(head.req)
                    picked.append(head)
                    progress = True
                    served = True
                if not q:
                    self._queues.pop(tenant, None)
                    self._deficit.pop(tenant, None)
                elif served:
                    # rotate a served tenant to the tail so the round robin
                    # PERSISTS across drive iterations — with a small slot
                    # budget per iteration, restarting the rotation from the
                    # same tenant every time would starve the rest
                    self._queues.move_to_end(tenant)
            if not self._queues:
                break
            if not progress and blocked_only:
                break  # every head is engine-blocked; wait for a step
        return picked

    def _admit_and_step(self, to_admit: list[_Pending]):
        """Worker-thread body: admissions (prefill — a first-use XLA compile
        plus a device forward, far too heavy for the event loop) and one
        decode step.  Exceptions are RETURNED, never raised: the drive loop
        must outlive any engine fault."""
        admitted: list[tuple[_Pending, Any, BaseException | None]] = []
        for p in to_admit:
            try:
                admitted.append((p, self.engine.admit(p.req), None))
            # ftc: ignore[silent-except] -- not swallowed: the failure is forwarded to the submitting caller via future.set_exception
            except Exception as e:  # PromptTooLong / bad request params
                admitted.append((p, None, e))
        step_err: BaseException | None = None
        finished: list[GenResult] = []
        if self.engine.active_requests:
            try:
                finished = self.engine.step()
            # ftc: ignore[silent-except] -- not swallowed: returned to the drive loop, which fails every in-flight future with it and logs
            except Exception as e:
                step_err = e
        return admitted, finished, step_err

    async def _drive(self) -> None:
        """Admit → step → resolve, forever; parks when fully idle.  All
        engine work (prefill admissions AND the decode step) runs in a
        worker thread so the control plane's event loop stays responsive."""
        while not self._closed:
            self._drop_expired()
            to_admit = self._select_admissions(self.engine.free_slots)
            if not to_admit and not self._inflight:
                self._wake.clear()
                try:
                    await asyncio.wait_for(
                        self._wake.wait(), timeout=self._park_timeout_s
                    )
                except asyncio.TimeoutError:
                    continue
                continue
            # register admissions as IN-FLIGHT before the worker thread runs:
            # while the thread admits them they are in neither _queue nor
            # _inflight otherwise, and a concurrent drain()/close() would
            # see an idle batcher and strand their futures forever
            for p in to_admit:
                self._inflight[p.req.request_id] = p
            steps_before = self.engine.steps_total
            admitted, finished, step_err = await asyncio.to_thread(
                self._admit_and_step, to_admit
            )
            if self.engine.steps_total > steps_before:
                self._step_stamps.append(time.monotonic())
            if self.ttft_observe is not None:
                now = time.monotonic()
                for p, _done, exc in admitted:
                    if exc is None:
                        try:
                            self.ttft_observe(now - p.enqueued_at)
                        except Exception:
                            logger.debug("ttft observe failed", exc_info=True)
            bounced: list[_Pending] = []
            for p, done, exc in admitted:
                rid = p.req.request_id
                if isinstance(exc, PoolExhausted):
                    # defense in depth: the selection gate should prevent
                    # this, but a transient exhaustion is BACKPRESSURE, not
                    # a request failure — put it back at the head of its
                    # tenant's queue and let pages free up
                    self._inflight.pop(rid, None)
                    if not p.future.done():
                        bounced.append(p)
                elif exc is not None:
                    self._inflight.pop(rid, None)
                    if not p.future.done():
                        p.future.set_exception(exc)
                elif done is not None:  # finished on admission (eos/max_new=1)
                    self._inflight.pop(rid, None)
                    self.completed_total += 1
                    self._observe_request_steps(done)
                    if not p.future.done():
                        p.future.set_result(done)
                elif p.future.done():
                    # resolved while the thread was admitting it (deadline
                    # drop or shutdown): free the lane the thread just
                    # filled — nobody is waiting on it
                    self._inflight.pop(rid, None)
                    self.engine.evict(rid)
            # reinsert pool-bounced requests at the head of their tenant
            # queues IN ARRIVAL ORDER (reversed appendleft: the first
            # bounced request must end up first in line again)
            for p in reversed(bounced):
                tenant = p.req.adapter_id or ""
                if tenant not in self._queues:
                    self._queues[tenant] = collections.deque()
                self._queues[tenant].appendleft(p)
            for result in finished:
                p = self._inflight.pop(result.request_id, None)
                self.completed_total += 1
                self._observe_request_steps(result)
                if p is not None and not p.future.done():
                    p.future.set_result(result)
            if step_err is not None:
                # the decode step died (OOM, XLA fault, recompile budget):
                # every in-flight request is lost — fail them LOUDLY instead
                # of hanging clients, free the lanes, keep serving.  The
                # error is also counted: a fleet health check treats a
                # faulting replica as crashed (teardown + restart with
                # backoff, docs/serving.md §Fleet).
                self.step_errors_total += 1
                self.last_step_error = step_err
                logger.exception("decode step failed; failing %d in-flight "
                                 "request(s)", len(self._inflight),
                                 exc_info=step_err)
                for rid, p in list(self._inflight.items()):
                    self.engine.evict(rid)
                    if not p.future.done():
                        p.future.set_exception(step_err)
                self._inflight.clear()

    # ---- observability ----------------------------------------------------

    def _observe_request_steps(self, result: GenResult) -> None:
        """EMA of decode steps per completed request (Retry-After input)."""
        steps = max(1, result.steps)
        if self._avg_request_steps is None:
            self._avg_request_steps = float(steps)
        else:
            self._avg_request_steps = (
                0.8 * self._avg_request_steps + 0.2 * steps
            )

    def stats(self) -> dict[str, Any]:
        pages = self.engine.kv_page_stats()
        return {
            "queue_depth": self.queue_depth,
            "slots_busy": self.slots_busy,
            "slots_total": self.engine.config.slots,
            "steps_total": self.engine.steps_total,
            "tokens_generated_total": self.engine.tokens_generated_total,
            "requests_completed_total": self.completed_total,
            "requests_rejected_total": self.rejected_total,
            "deadline_drops_total": self.deadline_drops_total,
            "step_errors_total": self.step_errors_total,
            "compilations": self.engine.compilations,
            # prefix-reuse KV cache (docs/serving.md) — all zeros when off
            "prefix_hits_total": self.engine.prefix_hits_total,
            "prefix_misses_total": self.engine.prefix_misses_total,
            "prefill_tokens_saved_total": self.engine.prefill_tokens_saved_total,
            "prefix_cache_bytes": self.engine.prefix_cache_bytes,
            "prefix_cache_entries": self.engine.prefix_cache_entries,
            # paged KV pool (docs/serving.md §Paged KV) — zeros when unpaged
            "kv_pages_total": pages.get("pages_total", 0),
            "kv_pages_free": pages.get("pages_free", 0),
            "kv_pages_used": pages.get("pages_used", 0),
            "kv_pages_shared": pages.get("pages_shared", 0),
            "kv_page_bytes": pages.get("page_bytes", 0),
            "kv_cow_copies_total": pages.get("cow_copies_total", 0),
            "kv_pool_exhaustions_total": pages.get(
                "pool_exhaustions_total", 0),
            # host KV tier (docs/serving.md §KV tiering) — zeros when off
            "kv_tier_host_pages_total": pages.get(
                "tier_host_pages_total", 0),
            "kv_tier_host_pages_used": pages.get("tier_host_pages_used", 0),
            "kv_tier_host_bytes": pages.get("tier_host_bytes", 0),
            "kv_demotions_total": pages.get("demotions_total", 0),
            "kv_restores_total": pages.get("restores_total", 0),
            # multi-tenant adapters (docs/serving.md §Multi-tenant adapters)
            "adapters_loaded": (
                len(self.engine.adapters)
                if self.engine.adapters is not None else 0
            ),
            "queue_depth_by_tenant": self.queue_depth_by_tenant(),
            "lanes_by_tenant": self.engine.active_by_tenant(),
            "tokens_by_tenant": dict(self.engine.tokens_by_tenant),
        }
