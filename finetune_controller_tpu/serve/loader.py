"""Resolve and load a promoted job's checkpoint into serving weights.

The deploy-bucket prefix a promotion copies (``controller/promotion.py``) is
the artifact layout the trainer produced: ``resolved_config.json`` (the job
spec — model preset + overrides + LoRA rank + training knobs),
``checkpoints/step_N/`` (trainable tree + opt state), plus adapter/merged
exports.  This module closes the loop the reference leaves open: it turns
that prefix back into ``(model, variables)`` the serving engine can decode
with.

Load path: rebuild the model from ``resolved_config.json`` exactly as the
trainer did (same preset, same seed ⇒ same frozen base for from-scratch test
jobs; same ``pretrained_weights_dir`` for real ones), restore the latest
checkpoint's trainable tree into it, then — for LoRA jobs — optionally fold
the adapter deltas into the base kernels so the serving matmul count drops to
the dense model's.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
from pathlib import Path
from typing import Any

from ..controller.objectstore import ObjectStore
from ..controller.schemas import JobRecord, PromotionStatus
from ..controller.statestore import StateStore

logger = logging.getLogger(__name__)


class ServeLoadError(RuntimeError):
    """A job cannot be served; ``status`` maps to the HTTP response."""

    def __init__(self, message: str, status: int = 409):
        super().__init__(message)
        self.status = status


async def resolve_promoted(state: StateStore, job_id: str) -> JobRecord:
    """The serve-side gate: only a COMPLETED promotion is servable.

    IN_PROGRESS/DELETING would read a half-copied prefix; FAILED and
    NOT_PROMOTED have no (trustworthy) deploy copy at all.  The error names
    the observed state so operators see *why*, not just a 409.
    """
    job = await state.get_job(job_id)
    if job is None:
        raise ServeLoadError(f"job {job_id!r} not found", status=404)
    if job.promotion_status is not PromotionStatus.COMPLETED:
        raise ServeLoadError(
            f"job {job_id!r} is not servable: promotion_status is "
            f"{job.promotion_status.value!r} (serving requires 'completed' — "
            "promote the job and wait for the copy to finish)"
        )
    if not job.promotion_uri:
        raise ServeLoadError(
            f"job {job_id!r} has promotion_status=completed but no "
            "promotion_uri recorded — re-promote it"
        )
    return job


async def fetch_promoted(
    store: ObjectStore, promotion_uri: str, dest_dir: Path | str
) -> Path:
    """Stage the servable slice of the deploy prefix to a local directory:
    the resolved job spec + the checkpoints tree (adapter/merged exports and
    metrics are not needed to serve)."""
    import shutil

    dest = Path(dest_dir)
    # stage FRESH: leftovers from a previous load (e.g. a higher step_N from
    # a promotion that was since rolled back and re-promoted) would win the
    # latest-checkpoint pick and silently serve stale weights
    if dest.exists():
        await asyncio.to_thread(shutil.rmtree, dest, ignore_errors=True)
    prefix = promotion_uri.rstrip("/") + "/"
    objs = await store.list_prefix(promotion_uri)
    if not objs:
        raise ServeLoadError(f"no objects under promotion uri {promotion_uri}")
    n = 0
    for obj in objs:
        rel = obj["uri"][len(prefix):]
        if rel != "resolved_config.json" and not rel.startswith("checkpoints/"):
            continue
        await store.get_file(obj["uri"], dest / rel)
        n += 1
    if n == 0:
        raise ServeLoadError(
            f"promotion prefix {promotion_uri} holds no resolved_config.json/"
            "checkpoints — was this job trained by this stack?"
        )
    logger.info("staged %d promoted objects <- %s", n, promotion_uri)
    return dest


def merge_lora_variables(model_cfg: Any, variables: dict) -> tuple[Any, dict]:
    """Fold LoRA deltas into the base kernels: ``W' = W + (α/r)·A·B``.

    Returns a rank-0 config and a variables tree without the ``lora``
    collection — the serving forward then runs the dense matmul count.  The
    merge happens in the param dtype (f32), matching ``hf_export``'s merged
    checkpoint math.  Quantized bases refuse (int4 kernels cannot absorb a
    dense delta); serve those unmerged.
    """
    import jax.numpy as jnp

    if "lora" not in variables:
        return model_cfg, variables
    if getattr(model_cfg, "quantize_base", False):
        raise ServeLoadError(
            "cannot merge LoRA into an int4-quantized base; serve unmerged"
        )
    scale = model_cfg.lora.alpha / model_cfg.lora.rank

    def merge(params: dict, lora: dict) -> dict:
        out = {}
        for key, sub in params.items():
            if key in lora and isinstance(lora[key], dict) \
                    and "lora_a" in lora[key]:
                a, b = lora[key]["lora_a"], lora[key]["lora_b"]
                kernel = sub["kernel"]
                # jnp.matmul batches over the leading layer axis of scanned
                # models ((L, in, r) @ (L, r, out)) and is a plain matmul on
                # unscanned ones
                delta = jnp.matmul(
                    a.astype(jnp.float32), b.astype(jnp.float32)
                ) * scale
                out[key] = {
                    **sub, "kernel": (
                        kernel.astype(jnp.float32) + delta
                    ).astype(kernel.dtype),
                }
            elif key in lora and isinstance(sub, dict):
                out[key] = merge(sub, lora[key])
            else:
                out[key] = sub
        return out

    merged = dict(variables)
    lora = merged.pop("lora")
    merged["params"] = merge(dict(merged["params"]), dict(lora))
    from ..models.lora import LoRAConfig

    merged_cfg = model_cfg.replace(
        lora=LoRAConfig(rank=0, alpha=model_cfg.lora.alpha,
                        targets=model_cfg.lora.targets)
    )
    return merged_cfg, merged


def _resolve_staged_spec(local_dir: Path) -> tuple[dict, Any, int]:
    """Shared validation for a staged promoted prefix: spec read,
    serving-eligibility guards, latest committed checkpoint step.  BOTH
    serve-side load paths run it — the in-process weight load below and the
    weights-free :func:`stage_meta` the cross-process transport uses — so a
    checkpoint is servable (or refused, with the same error) regardless of
    ``serve_transport``."""
    spec_path = local_dir / "resolved_config.json"
    if not spec_path.exists():
        raise ServeLoadError(
            f"{spec_path} missing: the promoted prefix carries no job spec"
        )
    with open(spec_path) as f:
        spec = json.load(f)

    from ..train.checkpoint import CheckpointManager
    from ..train.cli import build_model_config

    model_cfg = build_model_config(spec)
    if getattr(model_cfg, "vision", None) is not None:
        raise ServeLoadError("serving multimodal checkpoints is not supported yet")
    if getattr(model_cfg, "n_experts", 0):
        raise ServeLoadError(
            "serving MoE checkpoints is not supported (batching invariance "
            "does not hold under capacity routing)"
        )
    ckpt_dir = local_dir / "checkpoints"
    if not ckpt_dir.is_dir() or not os.listdir(ckpt_dir):
        raise ServeLoadError(
            f"no checkpoints under {ckpt_dir} — the job produced none"
        )
    latest = CheckpointManager(str(ckpt_dir)).latest_step()
    if latest is None:
        raise ServeLoadError(f"no committed checkpoint steps under {ckpt_dir}")
    return spec, model_cfg, latest


def load_serving_model(
    local_dir: Path | str, *, merge_lora: bool = True
) -> tuple[Any, dict, dict]:
    """Build ``(model, variables, meta)`` from a staged promoted prefix.

    Heavy (JAX init + checkpoint IO) and synchronous — callers run it in a
    thread (``asyncio.to_thread``) off the event loop.
    """
    local_dir = Path(local_dir)
    spec, model_cfg, latest = _resolve_staged_spec(local_dir)

    from ..train.checkpoint import CheckpointManager
    from ..train.cli import build_train_config
    from ..train.trainer import Trainer

    train_cfg = build_train_config(spec)
    if train_cfg.task == "reward":
        # a reward job's checkpoints carry {"lora", "head"} as the trainable
        # tree; the plain Trainer's restore template (lora only) would
        # refuse them — the reward trainer's template matches and its
        # _assemble drops the head (which serves via the reward_score RPC,
        # not through model.apply)
        from ..prefs.reward_trainer import RewardModelTrainer

        trainer = RewardModelTrainer(model_cfg, train_cfg)
    else:
        trainer = Trainer(model_cfg, train_cfg)
    state = trainer.init_state()
    ckpt = CheckpointManager(str(local_dir / "checkpoints"))
    template = trainer.state_to_host(state)
    host = ckpt.restore(latest, like=template)

    pretrained = spec.get("model", {}).get("weights_dir")
    if pretrained:
        state = trainer.load_pretrained(state, pretrained)
    variables = trainer._assemble(state.frozen, host["trainable"])

    # shard-audit trap (analysis/shard_audit.py, FTC_SHARD_AUDIT): the
    # assembled serving tree's device leaves must carry the rule table's
    # shardings — a restore path that landed the base replicated would make
    # every decode pay a silent GSPMD reshard (host-side numpy leaves carry
    # no sharding and are skipped)
    from ..analysis.shard_audit import ShardAuditor

    auditor = ShardAuditor.from_env(name="serve-load")
    if auditor is not None:
        from ..parallel.sharding import sharding_for_tree

        expected = sharding_for_tree(variables, trainer.mesh, trainer.rules)
        auditor.audit(variables, expected, label=f"serve-load:step_{latest}")

    model = trainer.model
    merged = False
    if merge_lora and "lora" in variables \
            and not getattr(model_cfg, "quantize_base", False):
        model_cfg, variables = merge_lora_variables(model_cfg, variables)
        model = type(model)(cfg=model_cfg)
        merged = True

    meta = {
        "preset": spec.get("model", {}).get("preset"),
        "task": train_cfg.task,
        "checkpoint_step": latest,
        "lora_merged": merged,
        "vocab_size": model_cfg.vocab_size,
        "max_seq_len": model_cfg.max_seq_len,
        "weights_dir": pretrained or None,
    }
    logger.info("serving model ready: %s", meta)
    return model, variables, meta


def strip_lora_for_multitenant(
    model: Any, variables: dict
) -> tuple[Any, dict, Any | None, float, int]:
    """Split a loaded (unmerged) serving model into the pristine base plus
    its own adapter, for multi-tenant serving (docs/serving.md §Multi-tenant
    adapters): returns ``(base_model, base_variables, lora_tree | None,
    alpha, rank)``.  The base model's config drops to rank 0 — per-lane
    adapters apply through the ``"tenants"`` stacks instead, so the job's
    own fine-tune becomes tenant #1 and slot 0 stays the untouched base."""
    if "lora" not in variables:
        return model, variables, None, 0.0, 0
    variables = dict(variables)
    lora_tree = variables.pop("lora")
    cfg = model.cfg
    alpha, rank = cfg.lora.alpha, cfg.lora.rank
    from ..models.lora import LoRAConfig

    base_cfg = cfg.replace(
        lora=LoRAConfig(rank=0, alpha=alpha, targets=cfg.lora.targets)
    )
    return type(model)(cfg=base_cfg), variables, lora_tree, alpha, rank


def _load_adapter_tree(local_dir: Path | str) -> tuple[Any, dict]:
    """Worker-thread body of :func:`load_adapter`: the staged prefix →
    ``(lora_tree, adapter_meta)``.  Unlike :func:`load_serving_model` this
    never builds the model or touches base weights — the checkpoint's
    trainable tree IS the adapter for a LoRA job (``Trainer._assemble``), so
    the whole load is one spec read plus one (small) msgpack restore."""
    local_dir = Path(local_dir)
    spec_path = local_dir / "resolved_config.json"
    if not spec_path.exists():
        raise ServeLoadError(
            f"{spec_path} missing: the promoted prefix carries no job spec"
        )
    with open(spec_path) as f:
        spec = json.load(f)

    from ..train.checkpoint import CheckpointManager
    from ..train.cli import build_model_config

    model_cfg = build_model_config(spec)
    if getattr(model_cfg, "vision", None) is not None:
        raise ServeLoadError("multimodal adapters are not servable yet")
    if model_cfg.lora.rank < 1:
        raise ServeLoadError(
            "job is not a LoRA job (lora.rank == 0): only LoRA deltas can "
            "be multiplexed onto a shared base fleet — serve it as its own "
            "model instead"
        )
    ckpt_dir = local_dir / "checkpoints"
    if not ckpt_dir.is_dir() or not os.listdir(ckpt_dir):
        raise ServeLoadError(
            f"no checkpoints under {ckpt_dir} — the job produced none"
        )
    ckpt = CheckpointManager(str(ckpt_dir))
    latest = ckpt.latest_step()
    if latest is None:
        raise ServeLoadError(f"no committed checkpoint steps under {ckpt_dir}")
    host = ckpt.restore(latest)  # raw state dict: no template needed
    lora_tree = host.get("trainable") if isinstance(host, dict) else None
    if not isinstance(lora_tree, dict) or not lora_tree:
        raise ServeLoadError(
            "checkpoint carries no trainable (LoRA) tree — was this job "
            "trained by this stack in LoRA mode?"
        )
    meta = {
        "preset": spec.get("model", {}).get("preset"),
        "weights_dir": spec.get("model", {}).get("weights_dir") or None,
        "checkpoint_step": latest,
        "lora_rank": model_cfg.lora.rank,
        "lora_alpha": model_cfg.lora.alpha,
    }
    return lora_tree, meta


async def load_adapter(
    state: StateStore,
    store: ObjectStore,
    job_id: str,
    work_dir: Path | str,
    *,
    base_meta: dict | None = None,
) -> tuple[Any, dict]:
    """Stage ONLY a promoted LoRA job's adapter deltas for multi-tenant
    serving (docs/serving.md §Multi-tenant adapters).

    The base fleet already holds the model weights; this path resolves the
    tenant job's promotion, stages its spec + checkpoints (the trainable
    tree of a LoRA job is just the adapter — megabytes, not the gigabytes a
    full model load moves), and returns ``(lora_tree, meta)`` ready for
    :meth:`~finetune_controller_tpu.serve.adapters.AdapterRegistry.register`.

    ``base_meta`` (the serving session's model meta) guards against serving
    an adapter on the wrong base: preset and pretrained weights must match —
    KV and deltas computed against different bases are silently wrong, the
    worst failure mode a 409 can prevent.
    """
    import shutil
    import uuid

    job = await resolve_promoted(state, job_id)
    job_dir = Path(work_dir) / job_id
    local = await fetch_promoted(
        store, job.promotion_uri, job_dir / f"adapter-{uuid.uuid4().hex[:8]}"
    )
    try:
        lora_tree, meta = await asyncio.to_thread(_load_adapter_tree, local)
    finally:
        await asyncio.to_thread(shutil.rmtree, local, ignore_errors=True)
    if base_meta is not None:
        for field in ("preset", "weights_dir"):
            if meta.get(field) != base_meta.get(field):
                raise ServeLoadError(
                    f"adapter job {job_id!r} was trained on "
                    f"{field}={meta.get(field)!r} but the base fleet serves "
                    f"{field}={base_meta.get(field)!r} — an adapter only "
                    "composes with the exact base it was trained against"
                )
        if base_meta.get("lora_merged"):
            raise ServeLoadError(
                "the base fleet serves MERGED weights; multi-tenant "
                "adapters need the pristine base — reload it with "
                "serve_merge_lora=false"
            )
    meta["job_id"] = job_id
    meta["promotion_uri"] = job.promotion_uri
    return lora_tree, meta


def stage_meta(local_dir: Path | str, *, merge_lora: bool = True) -> dict:
    """Serving meta from a STAGED promoted prefix without building the model
    or touching weights — the process-transport path (docs/serving.md
    §Cross-process transport): the control plane stages the prefix once and
    the worker processes rebuild the weights themselves
    (``transport/builders.py::deploy_dir``), so the API process only ever
    reads the spec + the checkpoint directory listing.  Eligibility guards
    are :func:`_resolve_staged_spec`, shared with the in-process load path
    — both transports accept and refuse exactly the same checkpoints."""
    local_dir = Path(local_dir)
    spec, model_cfg, latest = _resolve_staged_spec(local_dir)
    # predicts what load_serving_model's merge does in the worker: a LoRA
    # checkpoint ("lora" in the assembled variables ⇔ rank > 0) folds into
    # the base unless quantized
    merged = bool(
        merge_lora and model_cfg.lora.rank > 0
        and not getattr(model_cfg, "quantize_base", False)
    )
    pretrained = spec.get("model", {}).get("weights_dir")
    return {
        "preset": spec.get("model", {}).get("preset"),
        "task": spec.get("training", {}).get("task", "sft"),
        "checkpoint_step": latest,
        "lora_merged": merged,
        "lora_rank": model_cfg.lora.rank,
        "lora_alpha": model_cfg.lora.alpha,
        "vocab_size": model_cfg.vocab_size,
        "max_seq_len": model_cfg.max_seq_len,
        "weights_dir": pretrained or None,
    }


async def stage_for_workers(
    state: StateStore,
    store: ObjectStore,
    job_id: str,
    work_dir: Path | str,
    *,
    merge_lora: bool = True,
) -> tuple[Path, dict]:
    """resolve → stage → meta, WITHOUT loading weights into this process —
    the serve-side path when replicas are worker processes.  The staged dir
    is returned (NOT removed: workers read it for as long as the generation
    serves) along with the same meta shape :func:`load_promoted` produces."""
    import uuid

    job = await resolve_promoted(state, job_id)
    job_dir = Path(work_dir) / job_id
    local = await fetch_promoted(
        store, job.promotion_uri, job_dir / f"workers-{uuid.uuid4().hex[:8]}"
    )
    meta = await asyncio.to_thread(stage_meta, local, merge_lora=merge_lora)
    meta["job_id"] = job_id
    meta["promotion_uri"] = job.promotion_uri
    return local, meta


async def load_promoted(
    state: StateStore,
    store: ObjectStore,
    job_id: str,
    work_dir: Path | str,
    *,
    merge_lora: bool = True,
) -> tuple[Any, dict, dict]:
    """resolve → stage → load, the whole serve-side path for one job.

    Each load stages into its OWN ``stage-<nonce>`` directory and removes it
    once the weights are in memory: two racing loads for the same job (or a
    load racing a rollover) can no longer interleave writes under one shared
    prefix — the last-writer-wins corruption ISSUE 10 names.  Winner
    selection between racing callers happens one level up
    (``ServeManager.load``'s per-job single-flight CAS); this layer just
    guarantees that even uncoordinated concurrent loads are each internally
    consistent.  (A crashed load can leak its stage dir; no sweep happens
    here on purpose — a sweep would race a concurrent load's live staging,
    which is the exact bug being fixed.)
    """
    import shutil
    import uuid

    job = await resolve_promoted(state, job_id)
    job_dir = Path(work_dir) / job_id
    local = await fetch_promoted(
        store, job.promotion_uri, job_dir / f"stage-{uuid.uuid4().hex[:8]}"
    )
    try:
        model, variables, meta = await asyncio.to_thread(
            load_serving_model, local, merge_lora=merge_lora
        )
    finally:
        await asyncio.to_thread(shutil.rmtree, local, ignore_errors=True)
    meta["job_id"] = job_id
    meta["promotion_uri"] = job.promotion_uri
    return model, variables, meta
