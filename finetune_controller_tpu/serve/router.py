"""Request router over a replica fleet: spread, fail over, shed.

One router per served job (docs/serving.md §Fleet).  Every request flows

    submit → pick replica (healthy, newest generation, least loaded)
           → replica batcher → result

with three robustness layers the single-engine plane never had:

* **failover**: a request whose replica dies mid-decode (or is draining)
  comes back as :class:`ReplicaUnavailable` — the router re-enqueues it on a
  survivor, excluding replicas it already failed on, up to a bounded retry
  budget and always under the request's ORIGINAL deadline (a failover must
  not silently extend an SLO).  Decode-step faults are classified with the
  resilience layer's :func:`classify_failure` — retryable classes fail over,
  deterministic per-request errors surface immediately;
* **exactly-once**: the per-request id is idempotent.  A duplicate submit of
  an id already in flight ATTACHES to the running attempt (one decode, one
  result); an id that already completed returns the cached result without
  touching an engine.  A failed attempt never produced a result (the dead
  replica evicted its lanes), so a retry can never double-complete;
* **load shedding**: when every healthy replica's queue is full — or a
  request's deadline provably cannot survive the current queue — the router
  sheds with :class:`QueueFull` carrying a ``Retry-After`` estimate derived
  from observed queue depth and decode rate, instead of letting doomed work
  pile onto the fleet.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import time
from typing import Any

from ..resilience.policy import RETRYABLE, classify_failure
from .batcher import DeadlineExceeded, QueueFull, ReplicaUnavailable
from .engine import GenRequest, GenResult
from .fleet import Replica, ReplicaFleet

logger = logging.getLogger(__name__)


class FleetUnavailable(RuntimeError):
    """No healthy replica can take the request (HTTP 503 + Retry-After)."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class ReplicaRouter:
    """Routes generate requests over a :class:`ReplicaFleet`."""

    def __init__(
        self,
        fleet: ReplicaFleet,
        *,
        default_timeout_s: float = 60.0,
        failover_retries: int = 2,
        completed_cache: int = 1024,
    ):
        self.fleet = fleet
        self.default_timeout_s = default_timeout_s
        #: extra attempts after the first (each on a replica not yet tried)
        self.failover_retries = max(0, failover_retries)
        #: request_id -> GenResult, bounded LRU — the double-completion fence
        self._completed: collections.OrderedDict[str, GenResult] = (
            collections.OrderedDict()
        )
        self._completed_cache = max(1, completed_cache)
        #: request_id -> future of the in-flight attempt (duplicate ids attach)
        self._inflight: dict[str, asyncio.Future] = {}
        # counters (/metrics + GET /admin/serve)
        self.routed_total = 0
        self.failovers_total = 0
        self.duplicates_suppressed_total = 0
        self.shed_total = 0
        self.completed_total = 0

    # ---- picking -----------------------------------------------------------

    def _pick(self, exclude: set[str],
              req: GenRequest | None = None) -> Replica | None:
        """Healthy, not yet tried, newest generation first (rollover traffic
        shift), then replicas whose KV page pool can host the request NOW
        (paged engines, docs/serving.md §Paged KV — a replica with slack
        decodes immediately where a page-starved one would queue), then
        least loaded."""
        candidates = [
            r for r in self.fleet.healthy_replicas()
            if r.replica_id not in exclude
        ]
        if not candidates:
            return None
        newest = max(r.generation for r in candidates)
        preferred = [r for r in candidates if r.generation == newest]

        def starved(r: Replica) -> int:
            if req is None:
                return 0
            slack = r.engine.kv_slack_pages()
            if slack is None:
                return 0
            return 0 if r.engine.admission_pages(req) <= slack else 1

        return min(preferred,
                   key=lambda r: (starved(r), r.load(), r.replica_id))

    def retry_after_s(self) -> float:
        """The fleet-wide backoff hint: the LEAST loaded healthy replica's
        drain estimate (that is where the retried request would land)."""
        healthy = self.fleet.healthy_replicas()
        if not healthy:
            return 1.0
        return min(r.batcher.retry_after_s() for r in healthy)

    # ---- submission --------------------------------------------------------

    def _record_completed(self, result: GenResult) -> None:
        self._completed[result.request_id] = result
        self._completed.move_to_end(result.request_id)
        while len(self._completed) > self._completed_cache:
            self._completed.popitem(last=False)

    async def submit(
        self, req: GenRequest, *, timeout_s: float | None = None
    ) -> GenResult:
        done = self._completed.get(req.request_id)
        if done is not None:
            # idempotent replay: the request already completed — never
            # decode it twice
            self.duplicates_suppressed_total += 1
            return done
        racing = self._inflight.get(req.request_id)
        if racing is not None:
            # same id already decoding: attach to the in-flight attempt
            self.duplicates_suppressed_total += 1
            return await asyncio.shield(racing)
        timeout = self.default_timeout_s if timeout_s is None else timeout_s
        deadline = None if timeout <= 0 else time.monotonic() + timeout
        future = asyncio.get_running_loop().create_future()
        self._inflight[req.request_id] = future
        try:
            result = await self._run(req, deadline)
            self._record_completed(result)
            self.completed_total += 1
            if not future.done():
                future.set_result(result)
            return result
        except BaseException as exc:
            if not future.done():
                future.set_exception(exc)
                future.exception()  # attached waiters or nobody: mark seen
            raise
        finally:
            self._inflight.pop(req.request_id, None)

    async def _run(self, req: GenRequest, deadline: float | None) -> GenResult:
        tried: set[str] = set()
        attempts = 0
        while True:
            if deadline is not None and time.monotonic() >= deadline:
                raise DeadlineExceeded(
                    f"request {req.request_id} spent its deadline failing over"
                )
            replica = self._pick(tried, req)
            if replica is None:
                if tried:
                    # every healthy replica was tried and refused/died
                    self.shed_total += 1
                    raise QueueFull(
                        "all healthy replicas are at capacity; retry later",
                        retry_after_s=self.retry_after_s(),
                    )
                raise FleetUnavailable(
                    f"no healthy replica for job {self.fleet.job_id!r}",
                    retry_after_s=2.0,
                )
            # early shed: with a queue already formed and a measured decode
            # rate, a request whose deadline cannot survive the wait is
            # doomed work — bounce it NOW with a useful Retry-After instead
            # of letting it time out in line
            if deadline is not None and replica.batcher.queue_depth > 0:
                eta = replica.batcher.retry_after_s()
                if eta > 1.0 and time.monotonic() + eta > deadline:
                    self.shed_total += 1
                    raise QueueFull(
                        f"estimated queue wait {eta:.1f}s exceeds the "
                        "request deadline; shedding", retry_after_s=eta,
                    )
            self.routed_total += 1
            try:
                # timeout_s=0 when deadline is None: an explicitly
                # unlimited request must not have the batcher re-mint its
                # default deadline
                result = await replica.batcher.submit(
                    req, deadline=deadline,
                    timeout_s=0 if deadline is None else None,
                )
                result.replica_id = replica.replica_id
                return result
            except (ReplicaUnavailable, ConnectionError) as exc:
                # ConnectionError defends the cross-process transport seam:
                # RemoteReplica maps socket loss to ReplicaUnavailable, but a
                # raw OS-level error escaping that mapping is the same shape
                # — the worker never delivered a result, so failover is safe
                tried.add(replica.replica_id)
                attempts += 1
                if attempts > self.failover_retries:
                    raise
                self.failovers_total += 1
                logger.warning(
                    "request %s failing over (attempt %d/%d): %s",
                    req.request_id, attempts, self.failover_retries, exc,
                )
                continue
            except QueueFull:
                # this replica is full — try a less loaded survivor; the
                # all-full case surfaces via the _pick(None)+tried branch
                tried.add(replica.replica_id)
                continue
            except (DeadlineExceeded, ValueError):
                raise  # per-request: a retry would fail identically
            except Exception as exc:
                # decode-step fault delivered to this request's future —
                # classify like any other failure: retryable classes fail
                # over (the work is fine, the replica was not), terminal
                # ones surface
                tried.add(replica.replica_id)
                attempts += 1
                failure = classify_failure(None, str(exc))
                if failure in RETRYABLE and attempts <= self.failover_retries:
                    self.failovers_total += 1
                    logger.warning(
                        "request %s failing over after %s fault (attempt "
                        "%d/%d): %s", req.request_id, failure.value,
                        attempts, self.failover_retries, exc,
                    )
                    continue
                raise

    # ---- observability -----------------------------------------------------

    def stats(self) -> dict[str, Any]:
        return {
            "routed_total": self.routed_total,
            "failovers_total": self.failovers_total,
            "duplicates_suppressed_total": self.duplicates_suppressed_total,
            "shed_total": self.shed_total,
            "router_completed_total": self.completed_total,
        }
