// Native JSONL → packed-token pipeline (the data-loader hot path).
//
// The reference delegates all native concerns to external systems (SURVEY.md
// §2.2 — "no C++/Rust/CUDA code in-repo"); this framework keeps the training
// loop in JAX and the IO-bound preprocessing here: parse a JSONL dataset,
// tokenize byte-level (exact parity with
// finetune_controller_tpu/data/loader.py::_byte_tokenize, including \uXXXX
// escapes decoded to UTF-8), and pack everything into (n_blocks, seq_len)
// int32 token/segment/loss-flag arrays with per-document segment ids.
//
// Row schemas (parity with loader.load_token_documents, same precedence):
//   {"tokens": [...]}                        flags all 1
//   {"text": "..."}                          flags all 1
//   {"prompt_tokens": [], "completion_tokens": []}  completion-only flags
//   {"prompt": "...", "completion": "..."}   completion-only flags
//   {"messages": [{"role","content"}, ...]}  chat template <|role|>\ncontent\n,
//                                            assistant content (+\n) flagged
//
// Exposed as a tiny C ABI for ctypes (no pybind11 in the image):
//   ftc_pack_file(path, seq_len, &handle)        -> n_blocks (<0 = error)
//   ftc_copy_packed(handle, tokens, segs, flags) -> 0 on success
//   ftc_last_error()                             -> static error string
//   ftc_free(handle)
//
// Build: finetune_controller_tpu/native/build.py (g++ -O3 -shared -fPIC).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

thread_local std::string g_error;

struct Packed {
  std::vector<int32_t> tokens;
  std::vector<int32_t> segments;
  std::vector<int32_t> flags;  // 1 = position counts toward the loss
  int64_t n_blocks = 0;
  int64_t seq_len = 0;
};

// ---------------------------------------------------------------------------
// Minimal JSON value scanning (only what the row schema needs)
// ---------------------------------------------------------------------------

void append_utf8(std::string* out, uint32_t cp) {
  if (cp < 0x80) {
    out->push_back(static_cast<char>(cp));
  } else if (cp < 0x800) {
    out->push_back(static_cast<char>(0xC0 | (cp >> 6)));
    out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  } else if (cp < 0x10000) {
    out->push_back(static_cast<char>(0xE0 | (cp >> 12)));
    out->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
    out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  } else {
    out->push_back(static_cast<char>(0xF0 | (cp >> 18)));
    out->push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
    out->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
    out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  }
}

int hex_val(char c) {
  if (c >= '0' && c <= '9') return c - '0';
  if (c >= 'a' && c <= 'f') return c - 'a' + 10;
  if (c >= 'A' && c <= 'F') return c - 'A' + 10;
  return -1;
}

// Parse the JSON string starting at s[i] == '"'; returns decoded UTF-8 bytes
// and advances i past the closing quote. False on malformed input.
bool parse_json_string(const std::string& s, size_t* i, std::string* out) {
  if (s[*i] != '"') return false;
  ++*i;
  out->clear();
  while (*i < s.size()) {
    char c = s[*i];
    if (c == '"') {
      ++*i;
      return true;
    }
    if (c == '\\') {
      if (*i + 1 >= s.size()) return false;
      char e = s[*i + 1];
      *i += 2;
      switch (e) {
        case '"': out->push_back('"'); break;
        case '\\': out->push_back('\\'); break;
        case '/': out->push_back('/'); break;
        case 'b': out->push_back('\b'); break;
        case 'f': out->push_back('\f'); break;
        case 'n': out->push_back('\n'); break;
        case 'r': out->push_back('\r'); break;
        case 't': out->push_back('\t'); break;
        case 'u': {
          if (*i + 4 > s.size()) return false;
          uint32_t cp = 0;
          for (int k = 0; k < 4; ++k) {
            int h = hex_val(s[*i + k]);
            if (h < 0) return false;
            cp = (cp << 4) | static_cast<uint32_t>(h);
          }
          *i += 4;
          if (cp >= 0xD800 && cp <= 0xDBFF && *i + 6 <= s.size() &&
              s[*i] == '\\' && s[*i + 1] == 'u') {
            uint32_t lo = 0;
            bool ok = true;
            for (int k = 0; k < 4; ++k) {
              int h = hex_val(s[*i + 2 + k]);
              if (h < 0) { ok = false; break; }
              lo = (lo << 4) | static_cast<uint32_t>(h);
            }
            if (ok && lo >= 0xDC00 && lo <= 0xDFFF) {
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
              *i += 6;
            }
          }
          append_utf8(out, cp);
          break;
        }
        default:
          return false;
      }
      continue;
    }
    out->push_back(c);
    ++*i;
  }
  return false;  // unterminated
}

// Find `"key"` at the object TOP LEVEL only and return the index just past
// the ':'. Depth is tracked so nested objects/arrays can't shadow the row
// schema (parity with the Python loader's `"tokens" in row` check, which is
// top-level dict membership).
bool find_key(const std::string& s, const char* key, size_t* value_start) {
  size_t i = 0;
  int depth = 0;
  while (i < s.size()) {
    char c = s[i];
    if (c == '{' || c == '[') {
      ++depth;
      ++i;
      continue;
    }
    if (c == '}' || c == ']') {
      --depth;
      ++i;
      continue;
    }
    if (c == '"') {
      std::string tmp;
      if (!parse_json_string(s, &i, &tmp)) return false;
      if (depth == 1 && tmp == key) {
        while (i < s.size() && (s[i] == ' ' || s[i] == '\t')) ++i;
        if (i < s.size() && s[i] == ':') {
          *value_start = i + 1;
          return true;
        }
      }
      continue;
    }
    ++i;
  }
  return false;
}

// Skip any JSON value starting at s[i] (string, number, object, array,
// literal); advances i past it. Used to pass over message keys we ignore.
bool skip_value(const std::string& s, size_t* i) {
  while (*i < s.size() && (s[*i] == ' ' || s[*i] == '\t')) ++*i;
  if (*i >= s.size()) return false;
  char c = s[*i];
  if (c == '"') {
    std::string tmp;
    return parse_json_string(s, i, &tmp);
  }
  if (c == '{' || c == '[') {
    int depth = 0;
    while (*i < s.size()) {
      char d = s[*i];
      if (d == '"') {
        std::string tmp;
        if (!parse_json_string(s, i, &tmp)) return false;
        continue;
      }
      if (d == '{' || d == '[') ++depth;
      if (d == '}' || d == ']') {
        --depth;
        if (depth == 0) { ++*i; return true; }
      }
      ++*i;
    }
    return false;
  }
  // number / true / false / null: scan to a structural delimiter
  while (*i < s.size() && s[*i] != ',' && s[*i] != '}' && s[*i] != ']' &&
         s[*i] != ' ' && s[*i] != '\t') {
    ++*i;
  }
  return true;
}

// Parse {"messages": [...]} starting at the array and render the fixed chat
// template (loader._render_chat): header "<|role|>\n" (masked) + content
// "\n" (flagged iff role == "assistant"). Byte-level tokens.
bool parse_messages(const std::string& s, size_t i,
                    std::vector<int32_t>* toks, std::vector<int32_t>* flags) {
  while (i < s.size() && (s[i] == ' ' || s[i] == '\t')) ++i;
  if (i >= s.size() || s[i] != '[') return false;
  ++i;
  toks->clear();
  flags->clear();
  // the closing ']' is REQUIRED: a truncated row (interrupted download cut
  // mid-array) must error like the Python loader's json.loads, not train
  bool closed = false;
  while (i < s.size()) {
    while (i < s.size() &&
           (s[i] == ' ' || s[i] == '\t' || s[i] == ',')) {
      ++i;
    }
    if (i < s.size() && s[i] == ']') {
      closed = true;
      break;
    }
    if (i >= s.size() || s[i] != '{') return false;  // must be an object
    ++i;
    std::string role = "user";  // loader default: msg.get("role", "user")
    std::string content;        // loader default: ""
    bool in_obj = true;
    while (in_obj && i < s.size()) {
      while (i < s.size() && (s[i] == ' ' || s[i] == '\t' || s[i] == ',')) ++i;
      if (i < s.size() && s[i] == '}') {
        ++i;
        in_obj = false;
        break;
      }
      std::string key;
      if (i >= s.size() || !parse_json_string(s, &i, &key)) return false;
      while (i < s.size() && (s[i] == ' ' || s[i] == '\t')) ++i;
      if (i >= s.size() || s[i] != ':') return false;
      ++i;
      while (i < s.size() && (s[i] == ' ' || s[i] == '\t')) ++i;
      if ((key == "role" || key == "content") && i < s.size() && s[i] == '"') {
        std::string val;
        if (!parse_json_string(s, &i, &val)) return false;
        if (key == "role") role = val;
        else content = val;
      } else {
        // non-string role/content (loader stringifies) or extra keys: the
        // Python path owns those rows
        if (key == "role" || key == "content") return false;
        if (!skip_value(s, &i)) return false;
      }
    }
    if (in_obj) return false;  // unterminated object
    std::string header = "<|" + role + "|>\n";
    for (unsigned char ch : header) {
      toks->push_back(ch);
      flags->push_back(0);
    }
    int32_t body_flag = role == "assistant" ? 1 : 0;
    content.push_back('\n');
    for (unsigned char ch : content) {
      toks->push_back(ch);
      flags->push_back(body_flag);
    }
  }
  return closed && !toks->empty();
}

bool parse_int_array(const std::string& s, size_t i, std::vector<int32_t>* out) {
  while (i < s.size() && (s[i] == ' ' || s[i] == '\t')) ++i;
  if (i >= s.size() || s[i] != '[') return false;
  ++i;
  out->clear();
  while (i < s.size()) {
    while (i < s.size() && (s[i] == ' ' || s[i] == '\t' || s[i] == ',')) ++i;
    if (i < s.size() && s[i] == ']') return true;
    bool neg = false;
    if (i < s.size() && s[i] == '-') { neg = true; ++i; }
    if (i >= s.size() || s[i] < '0' || s[i] > '9') return false;
    int64_t v = 0;
    while (i < s.size() && s[i] >= '0' && s[i] <= '9') {
      v = v * 10 + (s[i] - '0');
      ++i;
    }
    out->push_back(static_cast<int32_t>(neg ? -v : v));
  }
  return false;
}

}  // namespace

extern "C" {

const char* ftc_last_error() { return g_error.c_str(); }

// Returns n_blocks >= 1 on success and sets *out_handle; negative on error.
int64_t ftc_pack_file(const char* path, int64_t seq_len, void** out_handle) {
  g_error.clear();
  if (seq_len <= 0) {
    g_error = "seq_len must be positive";
    return -1;
  }
  FILE* f = std::fopen(path, "rb");
  if (!f) {
    g_error = std::string("cannot open ") + path;
    return -2;
  }
  auto* packed = new Packed();
  packed->seq_len = seq_len;
  std::vector<int32_t>& stream = packed->tokens;
  std::vector<int32_t>& segs = packed->segments;

  std::vector<int32_t>& lflags = packed->flags;
  std::string line;
  std::vector<int32_t> tok_buf;
  std::vector<int32_t> tok_buf2;
  std::vector<int32_t> flag_buf;
  std::string text_buf;
  std::string text_buf2;
  int32_t doc_id = 0;
  char buf[1 << 16];
  line.reserve(1 << 16);
  bool pending = false;
  // all-ones flags (plain LM rows)
  auto flush_doc = [&](const std::vector<int32_t>& toks) {
    ++doc_id;
    stream.insert(stream.end(), toks.begin(), toks.end());
    segs.insert(segs.end(), toks.size(), doc_id);
    lflags.insert(lflags.end(), toks.size(), 1);
  };
  // explicit flags (SFT/chat rows)
  auto flush_doc_flags = [&](const std::vector<int32_t>& toks,
                             const std::vector<int32_t>& fl) {
    ++doc_id;
    stream.insert(stream.end(), toks.begin(), toks.end());
    segs.insert(segs.end(), toks.size(), doc_id);
    lflags.insert(lflags.end(), fl.begin(), fl.end());
  };
  auto parse_string_field = [&](const std::string& row, size_t vi,
                                std::string* out) -> bool {
    while (vi < row.size() && (row[vi] == ' ' || row[vi] == '\t')) ++vi;
    return parse_json_string(row, &vi, out);
  };
  auto process_line = [&]() -> bool {
    // trim
    size_t b = line.find_first_not_of(" \t\r\n");
    if (b == std::string::npos) return true;
    size_t e = line.find_last_not_of(" \t\r\n");
    std::string row = line.substr(b, e - b + 1);
    // schema precedence mirrors loader.load_token_documents exactly
    size_t vi = 0;
    if (find_key(row, "tokens", &vi)) {
      if (!parse_int_array(row, vi, &tok_buf)) {
        g_error = "malformed 'tokens' array: " + row.substr(0, 80);
        return false;
      }
      flush_doc(tok_buf);
      return true;
    }
    if (find_key(row, "text", &vi)) {
      while (vi < row.size() && (row[vi] == ' ' || row[vi] == '\t')) ++vi;
      if (!parse_json_string(row, &vi, &text_buf)) {
        g_error = "malformed 'text' string: " + row.substr(0, 80);
        return false;
      }
      tok_buf.clear();
      tok_buf.reserve(text_buf.size());
      for (unsigned char ch : text_buf) tok_buf.push_back(ch);
      flush_doc(tok_buf);
      return true;
    }
    size_t pi = 0, ci = 0;
    if (find_key(row, "prompt_tokens", &pi) &&
        find_key(row, "completion_tokens", &ci)) {
      if (!parse_int_array(row, pi, &tok_buf) ||
          !parse_int_array(row, ci, &tok_buf2)) {
        g_error = "malformed prompt/completion token arrays: " +
                  row.substr(0, 80);
        return false;
      }
      flag_buf.assign(tok_buf.size(), 0);
      flag_buf.insert(flag_buf.end(), tok_buf2.size(), 1);
      tok_buf.insert(tok_buf.end(), tok_buf2.begin(), tok_buf2.end());
      flush_doc_flags(tok_buf, flag_buf);
      return true;
    }
    if (find_key(row, "prompt", &pi) && find_key(row, "completion", &ci)) {
      if (!parse_string_field(row, pi, &text_buf) ||
          !parse_string_field(row, ci, &text_buf2)) {
        g_error = "malformed prompt/completion strings: " + row.substr(0, 80);
        return false;
      }
      tok_buf.clear();
      flag_buf.clear();
      for (unsigned char ch : text_buf) {
        tok_buf.push_back(ch);
        flag_buf.push_back(0);
      }
      for (unsigned char ch : text_buf2) {
        tok_buf.push_back(ch);
        flag_buf.push_back(1);
      }
      flush_doc_flags(tok_buf, flag_buf);
      return true;
    }
    if (find_key(row, "messages", &vi)) {
      if (!parse_messages(row, vi, &tok_buf, &flag_buf)) {
        g_error = "unsupported 'messages' row (the Python loader owns it): " +
                  row.substr(0, 80);
        return false;
      }
      bool any = false;
      for (int32_t f : flag_buf) any |= (f != 0);
      if (!any) {
        // parity with the Python loader's wrong-role footgun check — the
        // caller falls back and the Python path raises the detailed error
        g_error = "chat row produced no assistant-content tokens: " +
                  row.substr(0, 80);
        return false;
      }
      flush_doc_flags(tok_buf, flag_buf);
      return true;
    }
    g_error =
        "jsonl rows must have 'tokens', 'text', 'prompt'/'completion', "
        "or 'messages' fields";
    return false;
  };

  while (std::fgets(buf, sizeof(buf), f)) {
    line.append(buf);
    pending = true;
    if (!line.empty() && line.back() == '\n') {
      if (!process_line()) {
        std::fclose(f);
        delete packed;
        return -3;
      }
      line.clear();
      pending = false;
    }
  }
  std::fclose(f);
  if (pending && !process_line()) {
    delete packed;
    return -3;
  }
  if (doc_id == 0) {
    g_error = "no documents found";
    delete packed;
    return -4;
  }

  // block math identical to data/loader.py::pack_documents
  int64_t n_blocks = static_cast<int64_t>(stream.size()) / seq_len;
  if (n_blocks < 1) n_blocks = 1;
  if (static_cast<int64_t>(stream.size()) < seq_len) {
    stream.resize(seq_len, 0);
    segs.resize(seq_len, 0);
    lflags.resize(seq_len, 0);
  }
  stream.resize(n_blocks * seq_len);
  segs.resize(n_blocks * seq_len);
  lflags.resize(n_blocks * seq_len);
  packed->n_blocks = n_blocks;
  *out_handle = packed;
  return n_blocks;
}

int32_t ftc_copy_packed(void* handle, int32_t* tokens, int32_t* segments,
                        int32_t* flags) {
  auto* p = static_cast<Packed*>(handle);
  if (!p) return -1;
  std::memcpy(tokens, p->tokens.data(), p->tokens.size() * sizeof(int32_t));
  std::memcpy(segments, p->segments.data(), p->segments.size() * sizeof(int32_t));
  std::memcpy(flags, p->flags.data(), p->flags.size() * sizeof(int32_t));
  return 0;
}

void ftc_free(void* handle) { delete static_cast<Packed*>(handle); }

}  // extern "C"
