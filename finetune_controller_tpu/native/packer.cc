// Native JSONL → packed-token pipeline (the data-loader hot path).
//
// The reference delegates all native concerns to external systems (SURVEY.md
// §2.2 — "no C++/Rust/CUDA code in-repo"); this framework keeps the training
// loop in JAX and the IO-bound preprocessing here: parse a JSONL dataset,
// tokenize "text" rows byte-level (exact parity with
// finetune_controller_tpu/data/loader.py::_byte_tokenize, including \uXXXX
// escapes decoded to UTF-8), accept pre-tokenized "tokens" rows, and pack
// everything into (n_blocks, seq_len) int32 token/segment arrays with
// per-document segment ids.
//
// Exposed as a tiny C ABI for ctypes (no pybind11 in the image):
//   ftc_pack_file(path, seq_len, &handle)  -> n_blocks (<0 = error code)
//   ftc_copy_packed(handle, tokens, segs)  -> 0 on success
//   ftc_last_error()                       -> static error string
//   ftc_free(handle)
//
// Build: finetune_controller_tpu/native/build.py (g++ -O3 -shared -fPIC).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

thread_local std::string g_error;

struct Packed {
  std::vector<int32_t> tokens;
  std::vector<int32_t> segments;
  int64_t n_blocks = 0;
  int64_t seq_len = 0;
};

// ---------------------------------------------------------------------------
// Minimal JSON value scanning (only what the row schema needs)
// ---------------------------------------------------------------------------

void append_utf8(std::string* out, uint32_t cp) {
  if (cp < 0x80) {
    out->push_back(static_cast<char>(cp));
  } else if (cp < 0x800) {
    out->push_back(static_cast<char>(0xC0 | (cp >> 6)));
    out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  } else if (cp < 0x10000) {
    out->push_back(static_cast<char>(0xE0 | (cp >> 12)));
    out->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
    out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  } else {
    out->push_back(static_cast<char>(0xF0 | (cp >> 18)));
    out->push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
    out->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
    out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  }
}

int hex_val(char c) {
  if (c >= '0' && c <= '9') return c - '0';
  if (c >= 'a' && c <= 'f') return c - 'a' + 10;
  if (c >= 'A' && c <= 'F') return c - 'A' + 10;
  return -1;
}

// Parse the JSON string starting at s[i] == '"'; returns decoded UTF-8 bytes
// and advances i past the closing quote. False on malformed input.
bool parse_json_string(const std::string& s, size_t* i, std::string* out) {
  if (s[*i] != '"') return false;
  ++*i;
  out->clear();
  while (*i < s.size()) {
    char c = s[*i];
    if (c == '"') {
      ++*i;
      return true;
    }
    if (c == '\\') {
      if (*i + 1 >= s.size()) return false;
      char e = s[*i + 1];
      *i += 2;
      switch (e) {
        case '"': out->push_back('"'); break;
        case '\\': out->push_back('\\'); break;
        case '/': out->push_back('/'); break;
        case 'b': out->push_back('\b'); break;
        case 'f': out->push_back('\f'); break;
        case 'n': out->push_back('\n'); break;
        case 'r': out->push_back('\r'); break;
        case 't': out->push_back('\t'); break;
        case 'u': {
          if (*i + 4 > s.size()) return false;
          uint32_t cp = 0;
          for (int k = 0; k < 4; ++k) {
            int h = hex_val(s[*i + k]);
            if (h < 0) return false;
            cp = (cp << 4) | static_cast<uint32_t>(h);
          }
          *i += 4;
          if (cp >= 0xD800 && cp <= 0xDBFF && *i + 6 <= s.size() &&
              s[*i] == '\\' && s[*i + 1] == 'u') {
            uint32_t lo = 0;
            bool ok = true;
            for (int k = 0; k < 4; ++k) {
              int h = hex_val(s[*i + 2 + k]);
              if (h < 0) { ok = false; break; }
              lo = (lo << 4) | static_cast<uint32_t>(h);
            }
            if (ok && lo >= 0xDC00 && lo <= 0xDFFF) {
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
              *i += 6;
            }
          }
          append_utf8(out, cp);
          break;
        }
        default:
          return false;
      }
      continue;
    }
    out->push_back(c);
    ++*i;
  }
  return false;  // unterminated
}

// Find `"key"` at the object TOP LEVEL only and return the index just past
// the ':'. Depth is tracked so nested objects/arrays can't shadow the row
// schema (parity with the Python loader's `"tokens" in row` check, which is
// top-level dict membership).
bool find_key(const std::string& s, const char* key, size_t* value_start) {
  size_t i = 0;
  int depth = 0;
  while (i < s.size()) {
    char c = s[i];
    if (c == '{' || c == '[') {
      ++depth;
      ++i;
      continue;
    }
    if (c == '}' || c == ']') {
      --depth;
      ++i;
      continue;
    }
    if (c == '"') {
      std::string tmp;
      if (!parse_json_string(s, &i, &tmp)) return false;
      if (depth == 1 && tmp == key) {
        while (i < s.size() && (s[i] == ' ' || s[i] == '\t')) ++i;
        if (i < s.size() && s[i] == ':') {
          *value_start = i + 1;
          return true;
        }
      }
      continue;
    }
    ++i;
  }
  return false;
}

bool parse_int_array(const std::string& s, size_t i, std::vector<int32_t>* out) {
  while (i < s.size() && (s[i] == ' ' || s[i] == '\t')) ++i;
  if (i >= s.size() || s[i] != '[') return false;
  ++i;
  out->clear();
  while (i < s.size()) {
    while (i < s.size() && (s[i] == ' ' || s[i] == '\t' || s[i] == ',')) ++i;
    if (i < s.size() && s[i] == ']') return true;
    bool neg = false;
    if (i < s.size() && s[i] == '-') { neg = true; ++i; }
    if (i >= s.size() || s[i] < '0' || s[i] > '9') return false;
    int64_t v = 0;
    while (i < s.size() && s[i] >= '0' && s[i] <= '9') {
      v = v * 10 + (s[i] - '0');
      ++i;
    }
    out->push_back(static_cast<int32_t>(neg ? -v : v));
  }
  return false;
}

}  // namespace

extern "C" {

const char* ftc_last_error() { return g_error.c_str(); }

// Returns n_blocks >= 1 on success and sets *out_handle; negative on error.
int64_t ftc_pack_file(const char* path, int64_t seq_len, void** out_handle) {
  g_error.clear();
  if (seq_len <= 0) {
    g_error = "seq_len must be positive";
    return -1;
  }
  FILE* f = std::fopen(path, "rb");
  if (!f) {
    g_error = std::string("cannot open ") + path;
    return -2;
  }
  auto* packed = new Packed();
  packed->seq_len = seq_len;
  std::vector<int32_t>& stream = packed->tokens;
  std::vector<int32_t>& segs = packed->segments;

  std::string line;
  std::vector<int32_t> tok_buf;
  std::string text_buf;
  int32_t doc_id = 0;
  char buf[1 << 16];
  line.reserve(1 << 16);
  bool pending = false;
  auto flush_doc = [&](const std::vector<int32_t>& toks) {
    ++doc_id;
    stream.insert(stream.end(), toks.begin(), toks.end());
    segs.insert(segs.end(), toks.size(), doc_id);
  };
  auto process_line = [&]() -> bool {
    // trim
    size_t b = line.find_first_not_of(" \t\r\n");
    if (b == std::string::npos) return true;
    size_t e = line.find_last_not_of(" \t\r\n");
    std::string row = line.substr(b, e - b + 1);
    size_t vi = 0;
    if (find_key(row, "tokens", &vi)) {
      if (!parse_int_array(row, vi, &tok_buf)) {
        g_error = "malformed 'tokens' array: " + row.substr(0, 80);
        return false;
      }
      flush_doc(tok_buf);
      return true;
    }
    if (find_key(row, "text", &vi)) {
      while (vi < row.size() && (row[vi] == ' ' || row[vi] == '\t')) ++vi;
      if (!parse_json_string(row, &vi, &text_buf)) {
        g_error = "malformed 'text' string: " + row.substr(0, 80);
        return false;
      }
      tok_buf.clear();
      tok_buf.reserve(text_buf.size());
      for (unsigned char ch : text_buf) tok_buf.push_back(ch);
      flush_doc(tok_buf);
      return true;
    }
    g_error = "jsonl rows must have a 'tokens' or 'text' field";
    return false;
  };

  while (std::fgets(buf, sizeof(buf), f)) {
    line.append(buf);
    pending = true;
    if (!line.empty() && line.back() == '\n') {
      if (!process_line()) {
        std::fclose(f);
        delete packed;
        return -3;
      }
      line.clear();
      pending = false;
    }
  }
  std::fclose(f);
  if (pending && !process_line()) {
    delete packed;
    return -3;
  }
  if (doc_id == 0) {
    g_error = "no documents found";
    delete packed;
    return -4;
  }

  // block math identical to data/loader.py::pack_documents
  int64_t n_blocks = static_cast<int64_t>(stream.size()) / seq_len;
  if (n_blocks < 1) n_blocks = 1;
  if (static_cast<int64_t>(stream.size()) < seq_len) {
    stream.resize(seq_len, 0);
    segs.resize(seq_len, 0);
  }
  stream.resize(n_blocks * seq_len);
  segs.resize(n_blocks * seq_len);
  packed->n_blocks = n_blocks;
  *out_handle = packed;
  return n_blocks;
}

int32_t ftc_copy_packed(void* handle, int32_t* tokens, int32_t* segments) {
  auto* p = static_cast<Packed*>(handle);
  if (!p) return -1;
  std::memcpy(tokens, p->tokens.data(), p->tokens.size() * sizeof(int32_t));
  std::memcpy(segments, p->segments.data(), p->segments.size() * sizeof(int32_t));
  return 0;
}

void ftc_free(void* handle) { delete static_cast<Packed*>(handle); }

}  // extern "C"
