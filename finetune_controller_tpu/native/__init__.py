"""Native (C++) runtime components, built on demand with the system toolchain.

See :mod:`finetune_controller_tpu.native.build` for the build entry point and
:mod:`finetune_controller_tpu.data.native_loader` for the ctypes bindings.
"""
