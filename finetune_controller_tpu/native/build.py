"""Build the native shared library with the system C++ toolchain.

No pybind11 in the image (build brief), so the ABI is plain C consumed via
ctypes; no cmake project needed for a single translation unit — one g++
invocation, cached next to the source and rebuilt when the source changes.
"""

from __future__ import annotations

import hashlib
import logging
import os
import shutil
import subprocess
from pathlib import Path

logger = logging.getLogger(__name__)

SRC = Path(__file__).with_name("packer.cc")


def _cache_dir() -> Path:
    root = os.environ.get("FTC_NATIVE_CACHE", "")
    base = Path(root) if root else Path.home() / ".cache" / "finetune_controller_tpu"
    base.mkdir(parents=True, exist_ok=True)
    return base


def lib_path() -> Path:
    digest = hashlib.sha256(SRC.read_bytes()).hexdigest()[:16]
    return _cache_dir() / f"_ftc_native_{digest}.so"


def compiler() -> str | None:
    for cc in (os.environ.get("CXX"), "g++", "clang++", "c++"):
        if cc and shutil.which(cc):
            return cc
    return None


def ensure_built(*, quiet: bool = True) -> Path | None:
    """Compile (once per source hash) and return the .so path; None when no
    toolchain is available — callers fall back to the pure-Python path."""
    out = lib_path()
    if out.exists():
        return out
    cc = compiler()
    if cc is None:
        if not quiet:
            logger.warning("no C++ compiler found; native loader disabled")
        return None
    # compile to a process-unique temp path, then atomically rename: two
    # concurrent cold-cache builds must never leave a half-written .so where
    # another process will dlopen it
    tmp = out.with_suffix(f".tmp{os.getpid()}")
    cmd = [
        cc, "-O3", "-std=c++17", "-shared", "-fPIC",
        str(SRC), "-o", str(tmp),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, out)
    except subprocess.CalledProcessError as e:
        logger.warning("native build failed (%s); falling back to Python:\n%s",
                       " ".join(cmd), e.stderr[-2000:])
        tmp.unlink(missing_ok=True)
        return None
    logger.info("built native library: %s", out)
    return out
