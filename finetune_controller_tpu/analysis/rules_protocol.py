"""Protocol-conformance rules: wire tables that nothing verified before.

The repo grew two hand-rolled RPC protocols — the serve transport
(``transport/worker.py`` dispatches ``_op_<name>`` methods; the fleet-side
``transport/client.py`` sends ``conn.call("<name>", payload)``) and the
shared state service (``controller/statestore_service.py`` registers
``@_rpc("<name>")`` handlers; ``RemoteStateStore._call("<name>",
**payload)`` is the client).  PR 12 shipped them with nothing but tests
pinning a few ops; a renamed handler or a dropped payload key compiles
fine and fails at runtime, on a worker, mid-request.

``rpc-conformance`` statically extracts BOTH halves of each protocol and
fails the lint on:

* **client-without-handler** — an op sent on the wire that no handler
  dispatches (the rename/delete case; proven by mutation tests:
  ``tests/test_project_analysis.py`` deletes a worker handler and watches
  this rule turn red);
* **handler-without-client** — a dead op nothing ever sends (this rule
  found and deleted two on landing: ``drop_namespace`` and ``shutdown``);
* **payload-key mismatch** — a key a handler requires (``payload["k"]``)
  that some client call site provably never sends, or a key a client sends
  that the handler never reads.  A payload passed wholesale to another
  function (``entry_from_wire(payload)``) makes that handler *opaque* and
  exempts it from key checks, as does a client literal with ``**spread``.

``metric-doc-drift`` is the same conformance idea for observability:
every ``ftc_*`` Prometheus family emitted in code must appear in
``docs/observability.md``'s "Metric catalog" section, and every catalogued
name must still be emitted — the catalog can neither rot nor lie.
"""

from __future__ import annotations

import ast
import re
from typing import Any, Iterator

from ._astutil import dotted_name, terminal_name
from .engine import register_project

# ---------------------------------------------------------------------------
# shared payload-shape extraction
# ---------------------------------------------------------------------------


def _payload_reads(fn_node, param: str) -> tuple[set[str], set[str], bool]:
    """(required, optional, opaque) keys a handler reads from ``param``.

    ``param["k"]`` is required, ``param.get("k")`` optional; passing the
    whole ``param`` anywhere else (bare argument, ``**param``, iteration)
    makes the handler opaque — key checks are skipped for it."""
    required: set[str] = set()
    optional: set[str] = set()
    opaque = False
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Subscript) and isinstance(node.value, ast.Name) \
                and node.value.id == param:
            if isinstance(node.slice, ast.Constant) and \
                    isinstance(node.slice.value, str):
                required.add(node.slice.value)
            else:
                opaque = True
        elif isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr == "get" and \
                    isinstance(func.value, ast.Name) and func.value.id == param:
                if node.args and isinstance(node.args[0], ast.Constant) and \
                        isinstance(node.args[0].value, str):
                    optional.add(node.args[0].value)
                else:
                    opaque = True
            else:
                # the payload handed WHOLE to another callable (positional
                # or **spread): its reads are out of this rule's sight
                if any(isinstance(a, ast.Name) and a.id == param
                       for a in node.args):
                    opaque = True
                if any(kw.arg is None and isinstance(kw.value, ast.Name)
                       and kw.value.id == param for kw in node.keywords):
                    opaque = True
    return required, optional, opaque


def _dict_literal_keys(node: ast.Dict) -> tuple[set[str], bool]:
    keys: set[str] = set()
    opaque = False
    for k in node.keys:
        if k is None:  # **spread
            opaque = True
        elif isinstance(k, ast.Constant) and isinstance(k.value, str):
            keys.add(k.value)
        else:
            opaque = True
    return keys, opaque


def _client_payload_keys(fn_node, expr: ast.AST) -> tuple[set[str], bool]:
    """Keys a client call site sends: a dict literal's constant keys, or —
    when the payload is a variable — the keys of its dict-literal binding
    plus every ``var["k"] = ...`` store in the enclosing function."""
    if isinstance(expr, ast.Dict):
        return _dict_literal_keys(expr)
    if isinstance(expr, ast.Name):
        keys: set[str] = set()
        opaque = False
        bound = False
        for node in ast.walk(fn_node):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                if any(isinstance(t, ast.Name) and t.id == expr.id
                       for t in targets):
                    if isinstance(node.value, ast.Dict):
                        bound = True
                        k, o = _dict_literal_keys(node.value)
                        keys |= k
                        opaque = opaque or o
                    else:
                        opaque = True
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == expr.id and \
                    isinstance(node.ctx, ast.Store):
                if isinstance(node.slice, ast.Constant) and \
                        isinstance(node.slice.value, str):
                    keys.add(node.slice.value)
                else:
                    opaque = True
        return keys, opaque or not bound
    return set(), True


# ---------------------------------------------------------------------------
# family 1: the serve transport worker protocol
# ---------------------------------------------------------------------------


def _worker_op_tables(project) -> Iterator[tuple[Any, dict[str, Any]]]:
    """Classes dispatching ``_op_<name>`` methods via a ``_dispatch`` that
    builds the attribute name from the op string."""
    for ci in project.classes.values():
        dispatch = ci.methods.get("_dispatch")
        if dispatch is None:
            continue
        if not any(isinstance(n, ast.Constant) and isinstance(n.value, str)
                   and "_op_" in n.value
                   for n in ast.walk(dispatch.node)):
            continue
        handlers = {
            m.name[len("_op_"):]: m
            for m in ci.methods.values() if m.name.startswith("_op_")
        }
        if handlers:
            yield ci, handlers


def _conn_call_sites(project) -> Iterator[tuple[Any, ast.Call, str, ast.AST]]:
    """``<...conn>.call("op", payload)`` sites anywhere in the project —
    the transport client convention (``self._conn.call`` / ``conn.call``)."""
    for fn in project.functions.values():
        for node in ast.walk(fn.node):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "call"):
                continue
            recv = terminal_name(node.func.value)
            if "conn" not in recv:
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            payload = node.args[1] if len(node.args) > 1 else None
            yield fn, node, node.args[0].value, payload


def _check_worker_protocol(project):
    tables = list(_worker_op_tables(project))
    if not tables:
        return
    handlers: dict[str, Any] = {}
    for _ci, table in tables:
        handlers.update(table)
    called: set[str] = set()
    for fn, call, op, payload_expr in _conn_call_sites(project):
        called.add(op)
        handler = handlers.get(op)
        if handler is None:
            yield (
                fn.path, call.lineno, call.col_offset,
                f"client sends transport op {op!r} but no worker handler "
                f"`_op_{op}` exists — the RPC fails at dispatch "
                f"(known ops: {', '.join(sorted(handlers))})",
            )
            continue
        hparam = _handler_payload_param(handler.node)
        if hparam is None:
            continue
        required, optional, opaque = _payload_reads(handler.node, hparam)
        if opaque:
            continue
        sent, client_opaque = (
            _client_payload_keys(fn.node, payload_expr)
            if payload_expr is not None else (set(), False)
        )
        if client_opaque:
            continue
        for key in sorted(required - sent):
            yield (
                fn.path, call.lineno, call.col_offset,
                f"transport op {op!r}: handler `_op_{op}` requires payload "
                f"key {key!r} (subscript read) but this call site never "
                "sends it",
            )
        for key in sorted(sent - required - optional):
            yield (
                fn.path, call.lineno, call.col_offset,
                f"transport op {op!r}: payload key {key!r} is sent but "
                f"`_op_{op}` never reads it — dead weight or a renamed "
                "field",
            )
    for op, handler in sorted(handlers.items()):
        if op not in called:
            yield (
                handler.path, handler.node.lineno, handler.node.col_offset,
                f"worker handler `_op_{op}` has no client call site "
                "anywhere in the project — dead op (delete it, or wire the "
                "client that should be using it)",
            )


def _handler_payload_param(fn_node) -> str | None:
    args = [a.arg for a in fn_node.args.args if a.arg != "self"]
    return args[0] if args else None


# ---------------------------------------------------------------------------
# family 2: the shared state service (@_rpc handlers vs RemoteStateStore)
# ---------------------------------------------------------------------------


def _rpc_handler_tables(project) -> dict[str, Any]:
    out: dict[str, Any] = {}
    for fn in project.functions.values():
        for dec in getattr(fn.node, "decorator_list", ()):
            if isinstance(dec, ast.Call) and \
                    terminal_name(dec.func) == "_rpc" and dec.args and \
                    isinstance(dec.args[0], ast.Constant) and \
                    isinstance(dec.args[0].value, str):
                out[dec.args[0].value] = fn
    return out


def _is_state_rpc_call(call_method) -> bool:
    """Is this ``_call`` the state-service client (posts to ``/rpc/<m>``)?
    Object stores (gcs/s3) have their own HTTP ``_call`` helpers whose
    first argument is an HTTP verb, not an op name — the route marker
    disambiguates."""
    return any(
        isinstance(n, ast.Constant) and isinstance(n.value, str)
        and "/rpc/" in n.value
        for n in ast.walk(call_method.node)
    )


def _rpc_client_sites(project):
    """``self._call("name", **payload)`` sites on classes whose ``_call``
    posts to the state service's ``/rpc/{method}`` route; the ``_call``
    signature's own named params (e.g. ``retry_reads``) are client-side
    knobs, not payload keys."""
    for fn in project.functions.values():
        if fn.cls is None or "_call" not in fn.cls.methods:
            continue
        if not _is_state_rpc_call(fn.cls.methods["_call"]):
            continue
        own_params = {
            a.arg
            for a in fn.cls.methods["_call"].node.args.args
            if a.arg != "self"
        }
        for node in ast.walk(fn.node):
            if not (isinstance(node, ast.Call)
                    and dotted_name(node.func) == "self._call"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            keys: set[str] = set()
            opaque = False
            for kw in node.keywords:
                if kw.arg is None:
                    opaque = True
                elif kw.arg not in own_params:
                    keys.add(kw.arg)
            yield fn, node, node.args[0].value, keys, opaque


def _check_statestore_protocol(project):
    handlers = _rpc_handler_tables(project)
    if not handlers:
        return
    called: set[str] = set()
    for fn, call, method, sent, opaque in _rpc_client_sites(project):
        called.add(method)
        handler = handlers.get(method)
        if handler is None:
            yield (
                fn.path, call.lineno, call.col_offset,
                f"client calls state rpc {method!r} but no @_rpc handler "
                "registers it — the service answers 404",
            )
            continue
        hparam = _payload_param_of_rpc(handler.node)
        if hparam is None or opaque:
            continue
        required, optional, h_opaque = _payload_reads(handler.node, hparam)
        if h_opaque:
            continue
        for key in sorted(required - sent):
            yield (
                fn.path, call.lineno, call.col_offset,
                f"state rpc {method!r}: handler requires payload key "
                f"{key!r} but this call site never sends it",
            )
        for key in sorted(sent - required - optional):
            yield (
                fn.path, call.lineno, call.col_offset,
                f"state rpc {method!r}: payload key {key!r} is sent but the "
                "handler never reads it",
            )
    for method, handler in sorted(handlers.items()):
        if method not in called:
            yield (
                handler.path, handler.node.lineno, handler.node.col_offset,
                f"state rpc handler {method!r} has no RemoteStateStore call "
                "site — dead op",
            )


def _payload_param_of_rpc(fn_node) -> str | None:
    #: ``async def _handler(store, p)`` — payload is the SECOND param
    args = [a.arg for a in fn_node.args.args]
    return args[1] if len(args) >= 2 else None


@register_project(
    "rpc-conformance",
    "protocol",
    "RPC client op tables, handler tables, and payload keys must agree",
)
def rpc_conformance(project):
    yield from _check_worker_protocol(project)
    yield from _check_statestore_protocol(project)


# ---------------------------------------------------------------------------
# metric-name conformance
# ---------------------------------------------------------------------------

_METRIC_NAME = re.compile(r"^ftc_[a-z0-9_]+$")
_METRIC_IN_TEXT = re.compile(
    r"(?:#\s*TYPE\s+|^|[\s])(ftc_[a-z0-9_]+)(?=[\s{]|$)"
)
_CATALOG_HEADING = re.compile(r"^#+\s.*metric catalog", re.IGNORECASE)


def _emitted_metrics(project) -> dict[str, tuple[str, int]]:
    """``ftc_*`` Prometheus family names emitted anywhere in the package,
    with the first emission site.  Extraction is structural, so non-metric
    ``ftc_``-prefixed strings (cookie names, attribute tags) don't count:

    * string constants shaped like exposition text (``# TYPE <name> ...``,
      ``<name>{...`` / ``<name> <value>`` at the start of the constant —
      f-string fragments included);
    * the first element of a string tuple (the gauge/counter tables the
      ``/metrics`` handlers iterate);
    * the first argument of a ``Histogram(...)`` construction.
    """
    out: dict[str, tuple[str, int]] = {}

    def add(name: str, path: str, line: int) -> None:
        out.setdefault(name, (path, line))

    for module in project.modules.values():
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                text = node.value
                if "ftc_" not in text:
                    continue
                for m in _METRIC_IN_TEXT.finditer(text):
                    # whole-string identifiers (getattr names, cookie
                    # names) don't look like exposition text: require a
                    # TYPE prefix or a trailing label-brace/value
                    start, end = m.start(1), m.end(1)
                    shaped = (
                        "# TYPE" in text[:start]
                        or end < len(text) and text[end] in " {"
                    )
                    if shaped:
                        add(m.group(1), module.path,
                            getattr(node, "lineno", 1))
            elif isinstance(node, ast.Tuple) and node.elts:
                first = node.elts[0]
                if isinstance(first, ast.Constant) and \
                        isinstance(first.value, str) and \
                        _METRIC_NAME.match(first.value) and \
                        len(node.elts) > 1:
                    add(first.value, module.path, first.lineno)
            elif isinstance(node, ast.Call) and \
                    terminal_name(node.func) == "Histogram" and node.args:
                first = node.args[0]
                if isinstance(first, ast.Constant) and \
                        isinstance(first.value, str) and \
                        _METRIC_NAME.match(first.value):
                    add(first.value, module.path, first.lineno)
    return out


def _catalog_metrics(docs_path) -> dict[str, int]:
    """Names listed in the "Metric catalog" section of observability.md."""
    out: dict[str, int] = {}
    in_section = False
    section_level = 0
    for i, line in enumerate(
        docs_path.read_text(encoding="utf-8").splitlines(), start=1
    ):
        if line.startswith("#"):
            level = len(line) - len(line.lstrip("#"))
            if _CATALOG_HEADING.match(line):
                in_section = True
                section_level = level
                continue
            if in_section and level <= section_level:
                in_section = False
        if in_section:
            for name in re.findall(r"ftc_[a-z0-9_]+", line):
                out.setdefault(name, i)
    return out


@register_project(
    "metric-doc-drift",
    "protocol",
    "every emitted ftc_* metric is catalogued in docs/observability.md, and vice versa",
)
def metric_doc_drift(project):
    docs = project.docs_file("observability.md")
    if docs is None:
        return  # fixture trees without docs opt out by construction
    emitted = _emitted_metrics(project)
    catalogued = _catalog_metrics(docs)
    if not catalogued:
        return  # no catalog section yet: nothing to conform to
    for name in sorted(emitted.keys() - catalogued.keys()):
        path, line = emitted[name]
        yield (
            path, line, 0,
            f"metric `{name}` is emitted here but missing from "
            f"{docs}'s Metric catalog — document it",
        )
    for name in sorted(catalogued.keys() - emitted.keys()):
        yield (
            str(docs), catalogued[name], 0,
            f"metric `{name}` is catalogued but no code emits it — stale "
            "docs or a renamed family",
        )
