"""Compute-plane rules: the JAX performance/correctness contract.

These rules encode the hazards that only surface on a TPU profile (or as a
silently wrong run): host-device syncs traced into a jitted body, PRNG keys
consumed twice, recompilation traps, and un-donated training state.  They
are heuristics over the AST — interprocedural data flow is out of scope —
so each carries a suppression escape hatch for the intentional cases
(``docs/static_analysis.md`` has the catalog with before/after examples).
"""

from __future__ import annotations

import ast

from ._astutil import (
    FuncDef,
    ancestors,
    dotted_name,
    jit_call_target,
    jitted_functions,
    parent_map,
    terminal_name,
    walk_in_order,
)
from .engine import register

# ---------------------------------------------------------------------------
# host-sync-in-jit
# ---------------------------------------------------------------------------

#: attribute calls that force a device->host transfer of their receiver
_SYNC_METHODS = {"item", "tolist"}
#: numpy entry points that materialise a traced value on the host
_NP_CONVERTERS = {"asarray", "array", "copyto", "save", "savez"}
#: builtins that concretise a tracer when applied to one
_CONCRETISERS = {"float", "int", "bool"}


def _references_param(expr: ast.AST, params: set[str]) -> bool:
    return any(
        isinstance(n, ast.Name) and n.id in params for n in ast.walk(expr)
    )


def _param_names(fn: FuncDef) -> set[str]:
    a = fn.args
    names = [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return {n for n in names if n != "self"}


@register(
    "host-sync-in-jit",
    "compute",
    "device->host sync (.item()/float()/np.asarray/print) inside a jitted body",
)
def host_sync_in_jit(module: ast.Module, src: str, path: str):
    for fn, how in jitted_functions(module).items():
        params = _param_names(fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            msg = None
            if isinstance(node.func, ast.Attribute) and node.func.attr in _SYNC_METHODS:
                msg = f".{node.func.attr}() forces a device->host transfer"
            elif name == "print":
                msg = (
                    "print() in a traced body runs at TRACE time only (or "
                    "syncs, if the value escapes) — use jax.debug.print"
                )
            elif name in ("jax.device_get", "device_get"):
                msg = "jax.device_get blocks on the device inside the traced body"
            elif (
                name.split(".", 1)[0] in ("np", "numpy", "onp")
                and name.split(".")[-1] in _NP_CONVERTERS
                and node.args
                and _references_param(node.args[0], params)
            ):
                msg = f"{name} materialises a traced value on the host"
            elif (
                name in _CONCRETISERS
                and node.args
                and _references_param(node.args[0], params)
            ):
                msg = f"{name}() concretises a traced value (host sync at best)"
            if msg:
                yield (
                    node.lineno, node.col_offset,
                    f"in jitted fn `{fn.name}` ({how}): {msg}",
                )


# ---------------------------------------------------------------------------
# prng-key-reuse
# ---------------------------------------------------------------------------

#: jax.random functions that mint/derive keys rather than consume them
_KEY_PRODUCERS = {
    "PRNGKey", "key", "split", "fold_in", "clone", "wrap_key_data",
}


def _is_random_call(node: ast.AST, kinds: set[str] | None = None) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func)
    if not (name.startswith("jax.random.") or name.startswith("random.")
            or name.startswith("jrandom.") or name.startswith("jr.")):
        return False
    leaf = name.split(".")[-1]
    if kinds is None:
        return True
    return leaf in kinds


def _contains_key_producer(expr: ast.AST) -> bool:
    return any(
        _is_random_call(n, _KEY_PRODUCERS) for n in ast.walk(expr)
    )


def _assign_targets(node: ast.AST) -> list[str]:
    if not isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        return []
    targets = node.targets if isinstance(node, ast.Assign) else [node.target]
    names: list[str] = []
    for t in targets:
        if isinstance(t, ast.Name):
            names.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            names.extend(e.id for e in t.elts if isinstance(e, ast.Name))
    return names


@register(
    "prng-key-reuse",
    "compute",
    "the same PRNG key flows into two consumers without split/fold_in",
)
def prng_key_reuse(module: ast.Module, src: str, path: str):
    """Linear-scan heuristic per function: a name bound from a key producer
    (PRNGKey/split/fold_in/...) that is passed to MORE than one jax.random
    consumer without being rebound in between is flagged at the second use.
    Control flow is ignored (branches that each use the key once can FP —
    suppress with a reason)."""
    from ._astutil import functions

    for fn in functions(module):
        keys: dict[str, int] = {}  # live key name -> consumer uses so far
        for node in walk_in_order(fn):
            names = _assign_targets(node)
            if names:
                value = getattr(node, "value", None)
                if value is not None and _contains_key_producer(value):
                    for n in names:
                        keys[n] = 0  # fresh key material
                else:
                    for n in names:
                        keys.pop(n, None)  # rebound to something else
                continue
            if _is_random_call(node) and not _is_random_call(node, _KEY_PRODUCERS):
                call = node  # a consumer: count key names in its args
                for arg in [*call.args, *[kw.value for kw in call.keywords]]:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Name) and sub.id in keys:
                            keys[sub.id] += 1
                            if keys[sub.id] > 1:
                                yield (
                                    call.lineno, call.col_offset,
                                    f"key `{sub.id}` already consumed once in "
                                    f"`{fn.name}` — jax.random.split it (reusing "
                                    "a key correlates the two draws)",
                                )


# ---------------------------------------------------------------------------
# recompile hazards
# ---------------------------------------------------------------------------


@register(
    "recompile-jit-in-loop",
    "compute",
    "jax.jit called inside a loop body (a fresh wrapper per iteration)",
)
def recompile_jit_in_loop(module: ast.Module, src: str, path: str):
    parents = parent_map(module)
    for node in ast.walk(module):
        if not (isinstance(node, ast.Call) and jit_call_target(node) is not None):
            continue
        for anc in ancestors(node, parents):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                break  # deferred: the loop doesn't run this jit per iteration
            if isinstance(anc, (ast.For, ast.While, ast.AsyncFor)):
                yield (
                    node.lineno, node.col_offset,
                    "jax.jit inside a loop builds a fresh wrapper (and "
                    "usually a fresh compile) every iteration — hoist it, or "
                    "cache per static config",
                )
                break


@register(
    "recompile-fresh-callable",
    "compute",
    "jax.jit over a lambda/partial/grad built at call time (recompiles per call)",
)
def recompile_fresh_callable(module: ast.Module, src: str, path: str):
    """``jax.jit(lambda ...)`` / ``jax.jit(functools.partial(...))`` /
    ``jax.jit(jax.grad(...))`` inside a function body: the inner callable is
    a NEW object on every call of the enclosing function, so jit's cache
    never hits across calls.  Loop bodies are recompile-jit-in-loop's beat —
    skipped here so one site yields one finding."""
    parents = parent_map(module)
    for node in ast.walk(module):
        if not isinstance(node, ast.Call):
            continue
        target = jit_call_target(node)
        if target is None or not isinstance(target, (ast.Lambda, ast.Call)):
            continue
        in_function = in_loop = False
        for anc in ancestors(node, parents):
            if isinstance(anc, (ast.For, ast.While, ast.AsyncFor)):
                in_loop = True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                in_function = True
                break
        if in_function and not in_loop:
            what = "a lambda" if isinstance(target, ast.Lambda) else (
                f"`{dotted_name(target.func) or 'a fresh callable'}(...)`"
            )
            yield (
                node.lineno, node.col_offset,
                f"jax.jit over {what} built inside a function recompiles on "
                "every call of the enclosing function — hoist the callable "
                "or memoise the jitted fn",
            )


# ---------------------------------------------------------------------------
# missing-donation
# ---------------------------------------------------------------------------

_DONATE_KWARGS = {"donate_argnums", "donate_argnames"}


def _looks_like_train_step(name: str) -> bool:
    low = name.lower()
    if "eval" in low:
        return False
    return ("step" in low or "state" in low) and ("train" in low or "update" in low)


@register(
    "missing-donation",
    "compute",
    "jitted train/update step without donate_argnums (state buffers double-allocate)",
)
def missing_donation(module: ast.Module, src: str, path: str):
    # form 1: jax.jit(<train_step-ish>, ...) without a donate kwarg
    for node in ast.walk(module):
        if not isinstance(node, ast.Call):
            continue
        target = jit_call_target(node)
        if target is None:
            continue
        name = terminal_name(target)
        if not name or not _looks_like_train_step(name):
            continue
        if not any(kw.arg in _DONATE_KWARGS for kw in node.keywords):
            yield (
                node.lineno, node.col_offset,
                f"jax.jit(`{name}`) without donate_argnums/donate_argnames: "
                "the old state stays live across the step, doubling its HBM "
                "footprint",
            )
    # form 2: @jax.jit-decorated train_step def whose decorator carries no
    # donate kwarg (a bare @jax.jit cannot donate anything)
    from ._astutil import is_jit_callable

    for fn, how in jitted_functions(module).items():
        if how != "decorated" or not _looks_like_train_step(fn.name):
            continue
        for dec in fn.decorator_list:
            is_jit_dec = is_jit_callable(dec) or (
                isinstance(dec, ast.Call) and (
                    is_jit_callable(dec.func)
                    or (dotted_name(dec.func) in ("partial", "functools.partial")
                        and dec.args and is_jit_callable(dec.args[0]))
                )
            )
            if not is_jit_dec:
                continue
            donated = isinstance(dec, ast.Call) and any(
                kw.arg in _DONATE_KWARGS for kw in dec.keywords
            )
            if not donated:
                yield (
                    fn.lineno, fn.col_offset,
                    f"jitted `{fn.name}` takes training state but the "
                    "decorator donates nothing — pass donate_argnums",
                )
            break
