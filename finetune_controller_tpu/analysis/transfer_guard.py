"""Runtime transfer guard: no silent device↔host syncs in the hot windows.

The static rules (``host-sync-in-jit`` and its transitive v2) catch syncs a
reader can see; the expensive production regression is the one nobody
wrote: a debug ``jax.device_get`` left in the step path, a numpy array
slipping into a jitted call (implicit host→device transfer every step), a
logging helper that materialises a device value per token.  On CPU tests
these are free; on a TPU they serialize the dispatch pipeline and profile
as "mysteriously slow", never as an error.

:class:`TransferGuard` wraps the two host-side hot windows — the trainer's
jitted step call and the serve engine's decode dispatch — in a guard that
makes any transfer a LOUD failure:

* **jax's native transfer guards**: inside the window,
  ``jax.transfer_guard_host_to_device("disallow")`` (an np array reaching
  the jit boundary raises on every backend) and
  ``jax.transfer_guard_device_to_host("disallow_explicit")`` (any
  device→host materialisation raises — on accelerators; the CPU backend's
  arrays ARE host memory, so XLA never reports a d2h transfer there);
* **a thread-local ``jax.device_get`` trap**: installed once, the wrapper
  checks a thread-local "inside a guarded window" flag and trips the guard
  — this is what makes an injected ``jax.device_get`` abort the window on
  the CPU CI box too, and it is thread-safe by construction (the serve
  engine steps in worker threads while other threads use jax freely).

The first call per label is exempt: tracing/compilation legitimately
transfers closure constants host→device, and the guard targets the steady
state, not the compile.

Knobs (docs/static_analysis.md § Transfer guard):

* ``TrainConfig.transfer_guard`` — ``"raise"`` / ``"warn"`` / ``"off"``;
  the empty default inherits ``FTC_TRANSFER_GUARD`` from the env;
* ``FTC_TRANSFER_GUARD`` — same values, read by the serve engine and as
  the trainer fallback; off when unset;
* ``bench.py`` arms ``raise`` inside its timed windows (train and
  ``BENCH_MODE=serve``) behind ``BENCH_TRANSFER_GUARD`` (default on): a
  silently reintroduced sync ABORTS the bench instead of printing a slow
  number — the ``recompile_guard`` contract, for transfers;
* ``FTC_FAULT_TRANSFER=1`` — chaos hand for tests/bench: the guard itself
  injects a ``jax.device_get`` inside the window, proving the abort path.

``action="warn"`` swaps the disallow levels for jax's ``log`` levels and
downgrades trap trips to a once-per-label warning — observation mode for
triaging an existing pipeline without stopping it.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
from typing import Any, Callable

import jax

logger = logging.getLogger(__name__)

__all__ = ["TransferGuard", "TransferGuardError"]


class TransferGuardError(RuntimeError):
    """A device↔host transfer happened inside a guarded hot window."""


_WINDOW = threading.local()  # .guard / .label while inside a window
_trap_installed = False
_orig_device_get: Callable | None = None


def _install_device_get_trap() -> None:
    """Wrap ``jax.device_get`` once, process-wide: outside a window the
    wrapper is a thread-local read and a call — measured noise.  Inside a
    window it trips the active guard (works on EVERY backend, including
    CPU where XLA's own d2h guard cannot see a transfer)."""
    global _trap_installed, _orig_device_get
    if _trap_installed:
        return
    _trap_installed = True
    _orig_device_get = jax.device_get

    def guarded_device_get(x: Any) -> Any:
        guard = getattr(_WINDOW, "guard", None)
        if guard is not None:
            guard._trip(
                f"jax.device_get inside guarded window "
                f"{getattr(_WINDOW, 'label', '?')!r}"
            )
        return _orig_device_get(x)

    guarded_device_get.__wrapped__ = _orig_device_get
    jax.device_get = guarded_device_get


def _is_transfer_error(exc: BaseException) -> bool:
    text = str(exc)
    return "isallowed" in text and "transfer" in text


class TransferGuard:
    """Guard hot windows against device↔host transfers.

    One instance spans a run (trainer) or an engine lifetime (serve);
    ``trips`` counts violations observed — the default-on clean-path
    assertion is ``trips == 0``.
    """

    def __init__(
        self,
        action: str = "raise",  # "raise" | "warn"
        *,
        name: str = "transfer-guard",
        skip_first: bool = True,
        inject_fault: bool | None = None,
    ):
        if action not in ("raise", "warn"):
            raise ValueError(
                f"action must be 'raise' or 'warn', got {action!r}"
            )
        self.action = action
        self.name = name
        self.skip_first = skip_first
        self.trips = 0
        self._warned: set[str] = set()
        self._calls: dict[str, int] = {}
        #: chaos hand: perform a real jax.device_get INSIDE the window so
        #: tests/bench prove the abort path end to end
        self._fault = (
            inject_fault if inject_fault is not None
            else os.environ.get("FTC_FAULT_TRANSFER", "") not in ("", "0")
        )
        _install_device_get_trap()

    @classmethod
    def from_env(
        cls, default: str = "off", *, name: str = "transfer-guard"
    ) -> "TransferGuard | None":
        """Build from ``FTC_TRANSFER_GUARD`` (off/warn/raise); None = off."""
        mode = os.environ.get("FTC_TRANSFER_GUARD", default).strip().lower()
        if mode in ("", "0", "off", "false"):
            return None
        if mode in ("1", "on", "true"):
            mode = "raise"
        return cls(mode, name=name)

    # ---- the window --------------------------------------------------------

    def _trip(self, what: str) -> None:
        self.trips += 1
        detail = (
            f"{self.name}: {what} — a device<->host sync in a guarded hot "
            "window serializes the dispatch pipeline every step. Move the "
            "transfer outside the window (log-cadence host reads, explicit "
            "device_put before dispatch), or run with "
            "FTC_TRANSFER_GUARD=warn to observe without aborting."
        )
        if self.action == "raise":
            raise TransferGuardError(detail)
        label = getattr(_WINDOW, "label", "?")
        if label not in self._warned:
            self._warned.add(label)
            logger.warning("%s", detail)

    @contextlib.contextmanager
    def window(self, label: str):
        """Guard one hot-window execution.  Re-entrant per thread (the
        inner window wins); the first call per label is exempt so compile-
        time constant transfers don't trip the steady-state guard."""
        n = self._calls.get(label, 0)
        self._calls[label] = n + 1
        if self.skip_first and n == 0:
            yield
            return
        prev_guard = getattr(_WINDOW, "guard", None)
        prev_label = getattr(_WINDOW, "label", None)
        _WINDOW.guard, _WINDOW.label = self, label
        h2d = "disallow" if self.action == "raise" else "log"
        d2h = "disallow_explicit" if self.action == "raise" else "log_explicit"
        try:
            with jax.transfer_guard_host_to_device(h2d), \
                    jax.transfer_guard_device_to_host(d2h):
                yield
        except TransferGuardError:
            raise
        except Exception as exc:
            if _is_transfer_error(exc):
                self.trips += 1
                raise TransferGuardError(
                    f"{self.name}: XLA blocked a transfer inside window "
                    f"{label!r}: {exc}"
                ) from exc
            raise
        finally:
            _WINDOW.guard, _WINDOW.label = prev_guard, prev_label

    def run(self, label: str, fn: Callable, *args: Any, **kwargs: Any) -> Any:
        """Run ``fn`` inside a guarded window; the fault hand (if armed)
        device_gets the result INSIDE the window."""
        with self.window(label):
            out = fn(*args, **kwargs)
            self._maybe_inject(out)
            return out

    def _maybe_inject(self, out: Any) -> None:
        if not self._fault:
            return
        leaves = [
            x for x in jax.tree_util.tree_leaves(out)
            if hasattr(x, "shape") and hasattr(x, "dtype")
        ]
        if leaves:
            jax.device_get(leaves[0])

    def wrap(self, fn: Callable, label: str) -> Callable:
        """Wrap a (jitted) callable so every call runs in a guarded window."""

        def guarded(*args: Any, **kwargs: Any):
            return self.run(label, fn, *args, **kwargs)

        guarded.__name__ = f"transfer_guarded_{getattr(fn, '__name__', label)}"
        guarded.__wrapped__ = fn
        # AOT consumers (train/aot.py) lower the step jit without calling it
        if hasattr(fn, "lower"):
            guarded.lower = fn.lower
        return guarded
