"""Runtime shard audit: live state must carry the rule table's shardings.

The static sharding rules (``rules_sharding.py``) prove the PARTITION TABLE
is sound, and the AOT collective audit (``collective_audit.py``) proves the
COMPILED STEP moves what the docs say it moves — but neither sees the
arrays a running job actually holds.  The production bug class left over is
silent replication: an elastic restore, a checkpoint resharding path, or a
serve load that lands a leaf with the wrong (usually fully-replicated)
sharding.  Nothing fails — GSPMD inserts the resharding copies at the next
jit boundary and every step quietly pays full-param traffic, profiling as
"mysteriously slow", never as an error.

:class:`ShardAuditor` is the ``transfer_guard``-shaped complement: at the
checkpoint/restore boundaries (``train/trainer.py``) and on serve load
(``serve/loader.py``) it walks the live state tree and asserts each
device leaf's ``.sharding`` equals the expected :class:`NamedSharding`
from the rule table.  Host-side (numpy) leaves carry no sharding and are
skipped — the audit targets device state only.

Knobs (docs/static_analysis.md § Shard audit):

* ``TrainConfig.shard_audit`` — ``"raise"`` / ``"warn"`` / ``"off"``; the
  empty default inherits ``FTC_SHARD_AUDIT`` from the env;
* ``FTC_SHARD_AUDIT`` — same values, read by the serve loader and as the
  trainer fallback; off when unset;
* ``bench.py`` arms ``raise`` (``BENCH_SHARD_AUDIT``, default on): a
  mis-sharded timed run ABORTS instead of printing a slow number;
* ``FTC_FAULT_SHARD=1`` — chaos hand for tests/bench: the auditor itself
  re-``device_put``s one sharded leaf as fully replicated before checking,
  proving the abort path end to end.

The comparison is STRUCTURAL (``NamedSharding.__eq__``: mesh + spec), not
"semantic equivalence on this device count" — on the 1-device CI backend
every sharding is semantically equivalent to every other, and the audit
must still catch a replicated leaf there.  Leaves whose sharding is not a
``NamedSharding`` (e.g. a ``SingleDeviceSharding`` from host-side
construction) fall back to ``is_equivalent_to``, so single-device tests
don't false-positive on arrays that never crossed a mesh.

Process-wide counters (``metrics_snapshot``) surface as
``ftc_shard_audit_{checks,violations}_total`` on ``/metrics``
(docs/observability.md catalog).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any

logger = logging.getLogger(__name__)

__all__ = [
    "ShardAuditor",
    "ShardAuditError",
    "incr",
    "metrics_snapshot",
]

#: process-wide counters (the transport/__init__.py idiom): plain ints
#: behind a lock, snapshot by the controller's /metrics exposition
METRICS: dict[str, int] = {
    "checks_total": 0,
    "violations_total": 0,
}
_METRICS_LOCK = threading.Lock()


def incr(name: str, n: int = 1) -> None:
    with _METRICS_LOCK:
        METRICS[name] = METRICS.get(name, 0) + n


def metrics_snapshot() -> dict[str, int]:
    with _METRICS_LOCK:
        return dict(METRICS)


class ShardAuditError(RuntimeError):
    """A live state leaf's sharding diverged from the rule table."""


class ShardAuditor:
    """Assert live state leaves carry their rule-table shardings.

    One instance spans a trainer run or a serve load; ``checks`` /
    ``violations`` count leaves audited and divergences observed — the
    default-on clean-path assertion is ``violations == 0``.
    """

    def __init__(
        self,
        action: str = "raise",  # "raise" | "warn"
        *,
        name: str = "shard-audit",
        inject_fault: bool | None = None,
    ):
        if action not in ("raise", "warn"):
            raise ValueError(
                f"action must be 'raise' or 'warn', got {action!r}"
            )
        self.action = action
        self.name = name
        self.checks = 0
        self.violations = 0
        self._warned: set[str] = set()
        #: chaos hand: re-device_put ONE sharded leaf as replicated before
        #: checking, so tests/bench prove the abort path with a REAL
        #: mis-sharded array, not a mocked comparison
        self._fault = (
            inject_fault if inject_fault is not None
            else os.environ.get("FTC_FAULT_SHARD", "") not in ("", "0")
        )
        self._fault_fired = False

    @classmethod
    def from_env(
        cls, default: str = "off", *, name: str = "shard-audit"
    ) -> "ShardAuditor | None":
        """Build from ``FTC_SHARD_AUDIT`` (off/warn/raise); None = off."""
        mode = os.environ.get("FTC_SHARD_AUDIT", default).strip().lower()
        if mode in ("", "0", "off", "false"):
            return None
        if mode in ("1", "on", "true"):
            mode = "raise"
        return cls(mode, name=name)

    # ---- the audit ---------------------------------------------------------

    def _leaf_matches(self, leaf: Any, expected: Any) -> bool:
        import jax

        actual = getattr(leaf, "sharding", None)
        if actual is None:
            return True  # host-side (numpy) leaf — not audited
        if actual == expected:
            return True
        if not isinstance(actual, jax.sharding.NamedSharding):
            # a SingleDeviceSharding etc. never spells an intent; accept it
            # when it lays bytes out identically to the expectation
            try:
                return actual.is_equivalent_to(expected, leaf.ndim)
            except Exception:  # ftc: ignore[silent-except] -- an
                # incomparable sharding (cross-mesh, exotic layout) IS a
                # violation; the caller reports path + both specs
                return False
        return False

    def _inject(self, leaf: Any, expected: Any) -> Any:
        """The fault hand: return a REAL fully-replicated copy of ``leaf``."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        return jax.device_put(
            leaf, NamedSharding(expected.mesh, PartitionSpec())
        )

    def audit(self, tree: Any, expected: Any, *, label: str) -> int:
        """Walk ``tree`` against the same-structure ``expected`` shardings;
        returns the number of violations found at this boundary (and raises
        on the first batch of them when ``action == "raise"``)."""
        import jax

        bad: list[str] = []
        checked = 0
        leaves = jax.tree_util.tree_leaves_with_path(tree)
        exp_leaves = jax.tree_util.tree_leaves(
            expected, is_leaf=lambda x: hasattr(x, "spec")
        )
        for (kp, leaf), exp in zip(leaves, exp_leaves):
            if not hasattr(exp, "spec"):
                continue
            if (
                self._fault
                and not self._fault_fired
                and getattr(leaf, "sharding", None) is not None
                and len(exp.spec) > 0
            ):
                self._fault_fired = True
                leaf = self._inject(leaf, exp)
            checked += 1
            if not self._leaf_matches(leaf, exp):
                actual = getattr(leaf, "sharding", None)
                bad.append(
                    f"{jax.tree_util.keystr(kp)}: expected "
                    f"{getattr(exp, 'spec', exp)}, found "
                    f"{getattr(actual, 'spec', actual)}"
                )
        self.checks += checked
        incr("checks_total", checked)
        if not bad:
            return 0
        self.violations += len(bad)
        incr("violations_total", len(bad))
        shown = "; ".join(bad[:4]) + (
            f"; … {len(bad) - 4} more" if len(bad) > 4 else ""
        )
        detail = (
            f"{self.name}: {len(bad)} leaf/leaves mis-sharded at {label!r} — "
            f"{shown}. A leaf that lost its rule-table sharding (usually to "
            "full replication) makes every subsequent step pay a silent "
            "GSPMD reshard; fix the restore/load path, or run with "
            "FTC_SHARD_AUDIT=warn to observe without aborting."
        )
        if self.action == "raise":
            raise ShardAuditError(detail)
        if label not in self._warned:
            self._warned.add(label)
            logger.warning("%s", detail)
        return len(bad)
