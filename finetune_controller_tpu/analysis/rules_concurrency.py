"""Lock-discipline rule: races the per-file thread rule cannot see.

PRs 10–12 hand-fixed three races between the event loop and worker threads
(batcher ``_inflight`` registration, DRR rotation, adapter-unload busy
checks).  The shape is always the same: a field touched from a
``asyncio.to_thread``/``threading.Thread`` context AND from loop/main-thread
code, with at least one side doing a non-atomic read-modify-write.  The
per-file ``shared-mutable-without-lock`` rule only sees literal
``threading.Thread(target=...)`` in the same module; this rule uses the
project call graph to classify every method's execution context.

Two prongs, one rule id (``lock-discipline``):

**A — lock-holding classes.**  A class that creates a ``threading.Lock`` /
``RLock`` / ``Condition`` has declared itself multi-threaded.  The guarded
set is inferred: every ``self.<field>`` *mutated* under a ``with
self.<lock>:`` block somewhere in the class.  Findings: (1) any access
(read or write) of a guarded field outside the lock, and (2) any non-atomic
mutation (``+=`` / ``.append()``-family) of ANY field outside the lock —
the unguarded-counter shape.  ``__init__`` is exempt (construction
happens-before publication).

**B — lock-less classes provably touched from multiple threads.**  A class
with no lock whose bound methods are thread entries (handed to
``asyncio.to_thread`` / ``run_in_executor`` / ``Thread(target=...)``
anywhere in the project, or a ``threading.Thread`` subclass's ``run``).
Methods reachable from a thread root via sync edges are *thread-side*;
the rest are *loop-side*.  A field written from BOTH sides, with at least
one side non-atomic, is flagged once per (class, field) at the non-atomic
site — the message names the thread entry and the other-side writer so the
reader sees the interleaving without rebuilding the graph.  Sites where
the overlap is intentionally serialized (e.g. the batcher drive loop owns
the engine between steps) carry ``# ftc: ignore[lock-discipline]`` with
the ownership argument spelled out.
"""

from __future__ import annotations

import ast

from ._astutil import FuncDef, dotted_name, parent_map
from .engine import register_project

#: threading (NOT asyncio) synchronisation primitives
_LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition"}
_THREAD_BASES = {"threading.Thread", "Thread"}

#: in-place mutators whose read-modify-write spans bytecodes (mirrors the
#: per-file shared-mutable-without-lock table)
_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "add", "discard", "setdefault", "appendleft", "extendleft",
    "popleft", "move_to_end",
}


def _resolved(module, dotted: str) -> str:
    """Absolute dotted form via the module's import table (``Lock`` imported
    from threading resolves to ``threading.Lock``)."""
    if not dotted:
        return ""
    head, _, rest = dotted.partition(".")
    target = module.imports.get(head)
    if target is None:
        return dotted
    return f"{target}.{rest}" if rest else target


def _lock_attrs(ci) -> set[str]:
    out: set[str] = set()
    for method in ci.methods.values():
        for node in ast.walk(method.node):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                ctor = _resolved(ci.module, dotted_name(node.value.func))
                if ctor not in _LOCK_CTORS:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            dotted_name(t) == f"self.{t.attr}":
                        out.add(t.attr)
    return out


def _under_lock(node: ast.AST, parents, locks: set[str]) -> bool:
    while node in parents:
        node = parents[node]
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                expr = item.context_expr
                target = expr.func if isinstance(expr, ast.Call) else expr
                name = dotted_name(target)
                if name.startswith("self.") and name[5:] in locks:
                    return True
    return False


def _self_field(expr: ast.AST) -> str | None:
    """``self.<field>`` -> field name (one level only)."""
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self":
        return expr.attr
    return None


def _field_accesses(fn_node: FuncDef):
    """Yield ``(field, node, kind)`` for every ``self.<field>`` touch in the
    body; kind is "read", "write" (atomic rebind) or "rmw" (non-atomic).
    The ``self.f`` receiver inside ``self.f.append(...)`` / ``self.f += 1``
    / ``self.f[k] = v`` is reported once, under the stronger kind."""
    claimed: set[int] = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.AugAssign):
            target = node.target
            field = _self_field(target)
            if field is None and isinstance(target, ast.Subscript):
                field = _self_field(target.value)
                if field is not None:
                    claimed.add(id(target.value))
            if field is not None:
                claimed.add(id(target))
                yield field, node, "rmw"
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            field = _self_field(node.func.value)
            if field is not None and node.func.attr in _MUTATORS:
                claimed.add(id(node.func.value))
                yield field, node, "rmw"
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                field = _self_field(t)
                if field is not None:
                    claimed.add(id(t))
                    yield field, node, "write"
                elif isinstance(t, ast.Subscript):
                    field = _self_field(t.value)
                    if field is not None:  # self.f[k] = v mutates f in place
                        claimed.add(id(t.value))
                        yield field, node, "rmw"
    for node in ast.walk(fn_node):
        if id(node) in claimed:
            continue
        field = _self_field(node)
        if field is not None and isinstance(node.ctx, ast.Load):
            yield field, node, "read"


def _thread_root_map(project) -> dict[str, str]:
    """qualname -> the thread entry it is reachable from (first found)."""
    out: dict[str, str] = {}
    roots = sorted(q for q in project.thread_roots if q in project.functions)
    for cls in project.classes.values():
        if any(b in _THREAD_BASES or
               _resolved(cls.module, b) in _THREAD_BASES
               for b in cls.base_names):
            run = cls.methods.get("run")
            if run is not None:
                roots.append(run.qualname)
    for root in roots:
        stack = [root]
        while stack:
            q = stack.pop()
            if q in out:
                continue
            out[q] = root
            stack.extend(c.callee for c in project.sync_callees(q))
    return out


def _loop_reachable(project) -> set[str]:
    """Functions provably reachable from event-loop code: every async
    function plus its sync-call closure (deferred edges not crossed)."""
    seen: set[str] = set()
    stack = [fn.qualname for fn in project.async_functions()]
    while stack:
        q = stack.pop()
        if q in seen:
            continue
        seen.add(q)
        stack.extend(c.callee for c in project.sync_callees(q))
    return seen


@register_project(
    "lock-discipline",
    "concurrency",
    "field of a multi-threaded class accessed outside its lock (or raced lock-free)",
)
def lock_discipline(project):
    thread_of = _thread_root_map(project)
    loop_reach = _loop_reachable(project)
    for ci in sorted(project.classes.values(), key=lambda c: c.qualname):
        locks = _lock_attrs(ci)
        if locks:
            yield from _check_locked_class(ci, locks)
        else:
            yield from _check_lockfree_class(project, ci, thread_of,
                                             loop_reach)


def _check_locked_class(ci, locks: set[str]):
    # infer the guarded set: fields MUTATED under the lock anywhere
    guarded: set[str] = set()
    per_method: dict[str, list] = {}
    for mname, method in ci.methods.items():
        parents = parent_map(method.node)
        rows = [
            (field, node, kind, _under_lock(node, parents, locks))
            for field, node, kind in _field_accesses(method.node)
        ]
        per_method[mname] = rows
        for field, node, kind, locked in rows:
            if locked and kind in ("write", "rmw"):
                guarded.add(field)
    guarded -= locks
    for mname, rows in per_method.items():
        if mname in ("__init__", "__del__"):
            continue
        for field, node, kind, locked in rows:
            if locked or field in locks:
                continue
            if field in guarded:
                yield (
                    ci.module.path, node.lineno, node.col_offset,
                    f"`{ci.name}.{field}` is guarded by "
                    f"`self.{sorted(locks)[0]}` elsewhere in the class but "
                    f"{'mutated' if kind != 'read' else 'read'} here outside "
                    "the lock — take the lock or document the happens-before",
                )
            elif kind == "rmw":
                yield (
                    ci.module.path, node.lineno, node.col_offset,
                    f"non-atomic mutation of `{ci.name}.{field}` outside the "
                    f"lock in a lock-holding (multi-threaded) class — a "
                    "concurrent call loses updates; take "
                    f"`self.{sorted(locks)[0]}`",
                )


def _check_lockfree_class(project, ci, thread_of: dict[str, str],
                          loop_reach: set[str]):
    thread_side = {
        m for m in ci.methods.values() if m.qualname in thread_of
    }
    if not thread_side:
        return
    # loop-side must be PROVEN: reachable from an async function through
    # sync edges.  "not thread-reachable" alone is not evidence — an
    # unresolved caller would mis-classify a worker-thread helper as loop
    # code and flag phantom races.
    loop_side = [
        m for m in ci.methods.values()
        if m not in thread_side and m.qualname in loop_reach
        and m.name not in ("__init__", "__del__")
    ]
    #: field -> [(method, node, kind)]
    t_acc: dict[str, list] = {}
    l_acc: dict[str, list] = {}
    for methods, acc in ((thread_side, t_acc), (loop_side, l_acc)):
        for m in methods:
            if m.name in ("__init__", "__del__"):
                continue
            for field, node, kind in _field_accesses(m.node):
                acc.setdefault(field, []).append((m, node, kind))
    for field in sorted(t_acc.keys() & l_acc.keys()):
        t_writes = [r for r in t_acc[field] if r[2] in ("write", "rmw")]
        l_writes = [r for r in l_acc[field] if r[2] in ("write", "rmw")]
        if not t_writes or not l_writes:
            continue  # read-vs-write tearing is below this rule's bar
        rmw = [r for r in t_writes if r[2] == "rmw"] or \
              [r for r in l_writes if r[2] == "rmw"]
        if not rmw:
            continue  # both sides atomic rebinds: last-writer-wins, no RMW
        m, node, _kind = rmw[0]
        on_thread = m in thread_side
        entry = thread_of.get(t_writes[0][0].qualname, "?")
        other = (l_writes if on_thread else t_writes)[0][0]
        yield (
            ci.module.path, node.lineno, node.col_offset,
            f"`{ci.name}.{field}` is written from a worker thread "
            f"(`{t_writes[0][0].display}`, entered via thread target "
            f"`{entry.rsplit('.', 2)[-2]}.{entry.rsplit('.', 1)[-1]}`) AND "
            f"from loop/main-thread code (`{other.display}`) with no lock, "
            "non-atomically — guard both sides, or make one the single "
            "writer",
        )
