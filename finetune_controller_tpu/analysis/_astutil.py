"""Shared AST plumbing for the lint rules: dotted names, parent chains,
and jitted-function discovery."""

from __future__ import annotations

import ast
from typing import Iterator

FuncDef = ast.FunctionDef | ast.AsyncFunctionDef


def dotted_name(node: ast.AST) -> str:
    """``jax.random.split`` for a Name/Attribute chain, else ``""``."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def terminal_name(node: ast.AST) -> str:
    """Last segment of a Name/Attribute chain (``self._train_step`` ->
    ``_train_step``), else ``""``."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def parent_map(module: ast.Module) -> dict[ast.AST, ast.AST]:
    parents: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(module):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def ancestors(node: ast.AST, parents: dict[ast.AST, ast.AST]) -> Iterator[ast.AST]:
    while node in parents:
        node = parents[node]
        yield node


def functions(module: ast.Module) -> Iterator[FuncDef]:
    for node in ast.walk(module):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def walk_in_order(node: ast.AST) -> list[ast.AST]:
    """All descendants sorted by source position (linear-scan heuristics)."""
    out = [n for n in ast.walk(node) if hasattr(n, "lineno")]
    out.sort(key=lambda n: (n.lineno, n.col_offset))
    return out


_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.experimental.pjit.pjit"}


def is_jit_callable(node: ast.AST) -> bool:
    """Does this expression name ``jax.jit`` (or pjit)?"""
    return dotted_name(node) in _JIT_NAMES


def jit_call_target(call: ast.Call) -> ast.AST | None:
    """For ``jax.jit(f, ...)`` return the ``f`` expression, else None."""
    if is_jit_callable(call.func) and call.args:
        return call.args[0]
    return None


def _decorator_jits(fn: FuncDef) -> bool:
    """True when ``fn`` is decorated ``@jax.jit`` / ``@partial(jax.jit, ...)``
    / ``@jax.jit(...)``."""
    for dec in fn.decorator_list:
        if is_jit_callable(dec):
            return True
        if isinstance(dec, ast.Call):
            if is_jit_callable(dec.func):
                return True
            if dotted_name(dec.func) in ("partial", "functools.partial") and (
                dec.args and is_jit_callable(dec.args[0])
            ):
                return True
    return False


def jitted_functions(module: ast.Module) -> dict[FuncDef, str]:
    """Functions whose bodies are traced: decorated with jit, or referenced
    by name in a ``jax.jit(...)``/``shard_map(...)`` call anywhere in the
    module (``jax.jit(self._train_step, ...)`` marks ``_train_step``).

    Returns {function def: how it was detected} for diagnostics.
    """
    out: dict[FuncDef, str] = {}
    referenced: set[str] = set()
    for node in ast.walk(module):
        if not isinstance(node, ast.Call):
            continue
        target = jit_call_target(node)
        if target is None and dotted_name(node.func).endswith("shard_map") and node.args:
            target = node.args[0]
        if target is not None:
            name = terminal_name(target)
            if name:
                referenced.add(name)
    for fn in functions(module):
        if _decorator_jits(fn):
            out[fn] = "decorated"
        elif fn.name in referenced:
            out[fn] = "referenced"
    return out
