"""Interprocedural flow rules: the PR 2 rules, taken across call boundaries.

``blocking-io-in-async`` and ``host-sync-in-jit`` see one function at a
time, so the classic evasion is a helper: the async handler calls
``_stage()``, ``_stage()`` calls ``open()``, and the per-file rule sees two
innocent functions.  These rules walk the project call graph
(``analysis/project.py``) from every async function / jitted function
through *sync* edges only, and flag the FIRST hop out of the root when any
function in its closure contains a blocking / host-sync leaf — with the
full call chain rendered in the message so the reader doesn't have to
rediscover the path.

Traversal rules (all deliberately conservative — every rendered chain is a
real sequence of resolvable calls):

* only ``context="sync"`` edges are followed — a callee handed to
  ``asyncio.to_thread`` / ``run_in_executor`` / ``threading.Thread`` runs
  off the loop and is exactly the sanctioned fix;
* an async callee is not traversed (its body is its own root — it gets its
  own analysis, so one hazard yields one finding, not one per caller);
* a jitted callee of a jitted root is likewise skipped;
* depth starts at 1: the direct-call case inside the root body stays the
  per-file rule's finding.

Suppressions anchor at the first-hop call site inside the root — the line
a reader of the async handler actually sees.
"""

from __future__ import annotations

import ast
from typing import Callable, Iterable

from ._astutil import dotted_name
from .engine import register_project
from .rules_controller import blocking_call_message

#: traversal ceiling — chains longer than this are beyond human review and
#: almost certainly a resolution accident, not a real finding
_MAX_DEPTH = 12

#: unambiguous host-sync leaves for the transitive jit rule: each of these
#: forces a device sync (or is trace-time-wrong) in ANY traced context, so
#: no parameter-flow reasoning is needed to flag them in a helper
_SYNC_ATTRS = {"item", "tolist"}
_SYNC_NAMES = {"jax.device_get", "device_get"}


def _host_sync_message(node: ast.Call) -> str | None:
    name = dotted_name(node.func)
    if isinstance(node.func, ast.Attribute) and node.func.attr in _SYNC_ATTRS:
        return f".{node.func.attr}() forces a device->host transfer"
    if name in _SYNC_NAMES:
        return "jax.device_get blocks on the device inside the traced body"
    if name == "print":
        return ("print() in a traced body runs at TRACE time only (or "
                "syncs, if the value escapes) — use jax.debug.print")
    return None


def _own_calls(fn_node) -> Iterable[ast.Call]:
    """Calls in a function body, nested def/lambda/class scopes excluded
    (same deferral-boundary contract as the per-file rules)."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def _first_leaf(fn_info, matcher: Callable) -> tuple[ast.Call, str] | None:
    for call in _own_calls(fn_info.node):
        msg = matcher(call)
        if msg is not None:
            return call, msg
    return None


def _chains_from(project, root, matcher, *, skip: Callable):
    """For each sync call site in ``root``, BFS its closure; yield
    ``(site, chain, leaf_fn, leaf_call, msg)`` for the shortest path to a
    function containing a leaf.  One finding per first-hop site."""
    for site in project.sync_callees(root.qualname):
        first = project.function(site.callee)
        if first is None or skip(first):
            continue
        parent: dict[str, str | None] = {first.qualname: None}
        queue = [first.qualname]
        depth = {first.qualname: 1}
        found = None
        while queue and found is None:
            q = queue.pop(0)
            fn = project.function(q)
            if fn is None:
                continue
            leaf = _first_leaf(fn, matcher)
            if leaf is not None:
                found = (q, leaf)
                break
            if depth[q] >= _MAX_DEPTH:
                continue
            for nxt in project.sync_callees(q):
                callee = project.function(nxt.callee)
                if callee is None or skip(callee) or nxt.callee in parent:
                    continue
                parent[nxt.callee] = q
                depth[nxt.callee] = depth[q] + 1
                queue.append(nxt.callee)
        if found is None:
            continue
        leaf_q, (leaf_call, msg) = found
        chain = []
        cur: str | None = leaf_q
        while cur is not None:
            chain.append(project.function(cur))
            cur = parent[cur]
        chain.reverse()
        yield site, chain, project.function(leaf_q), leaf_call, msg


def _render_chain(root, chain, leaf_fn, leaf_call) -> str:
    hops = " -> ".join(f"`{fn.display}`" for fn in chain)
    return (f"`{root.display}` -> {hops} "
            f"(leaf at {leaf_fn.path}:{leaf_call.lineno})")


@register_project(
    "blocking-io-in-async-transitive",
    "flow",
    "async def reaches a blocking call (open/sleep/requests/...) through sync helpers",
)
def blocking_io_in_async_transitive(project):
    for root in project.async_functions():
        def skip(fn):
            # async callees are their own roots; a helper that is ALSO a
            # known thread entry still blocks when called synchronously,
            # so thread roots are NOT skipped
            return fn.is_async
        for site, chain, leaf_fn, leaf_call, msg in _chains_from(
            project, root, blocking_call_message, skip=skip
        ):
            yield (
                root.path, site.line, site.col,
                f"async `{root.display}` reaches blocking I/O through "
                f"{_render_chain(root, chain, leaf_fn, leaf_call)}: {msg}",
            )


@register_project(
    "host-sync-in-jit-transitive",
    "flow",
    "jitted function reaches a host sync (.item()/device_get/print) through helpers",
)
def host_sync_in_jit_transitive(project):
    for root_q, how in project.jitted.items():
        root = project.function(root_q)
        if root is None:
            continue

        def skip(fn):
            # a jitted callee is its own root — one hazard, one finding
            return fn.qualname in project.jitted
        for site, chain, leaf_fn, leaf_call, msg in _chains_from(
            project, root, _host_sync_message, skip=skip
        ):
            yield (
                root.path, site.line, site.col,
                f"jitted `{root.display}` ({how}) reaches a host sync "
                f"through {_render_chain(root, chain, leaf_fn, leaf_call)}: "
                f"{msg}",
            )
