"""The ftc-lint engine: file walker, rule registries, suppressions, reporting.

Two kinds of rule:

* a **per-file rule** is a callable ``(module: ast.Module, src: str, path:
  str) -> iterable of (line, col, message)`` registered under a kebab-case
  id with :func:`register`;
* a **project rule** is a callable ``(project: analysis.project.Project) ->
  iterable of (path, line, col, message)`` registered with
  :func:`register_project` — it sees the whole package at once (call graph,
  symbol table, thread/async/jit classification) and powers the
  interprocedural rules (``rules_flow``, ``rules_concurrency``,
  ``rules_protocol``).

The engine parses each file once, runs every selected per-file rule over
the tree, builds the project index (shared by all project rules), then
drops findings covered by an inline suppression::

    risky_line()  # ftc: ignore[rule-id] -- why this is intentional

A suppression comment matches on the finding's own line or the line directly
above it (for statements too long to share a line with their justification),
and may carry several ids: ``# ftc: ignore[silent-except,host-sync-in-jit]``.
The ``-- reason`` tail is free text; CI policy (docs/static_analysis.md) is
that every suppression carries one.

Output formats: ``text`` and ``json`` (byte-compatible with PR 2) plus
``sarif`` (SARIF 2.1.0 for CI annotations and editors).  ``--rules`` /
``--exclude-rules`` are selector aliases of ``--select`` / ``--ignore``.

Exit codes: 0 clean, 1 unsuppressed findings, 2 parse/usage errors.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import re
import sys
from pathlib import Path
from typing import Callable, Iterable, Iterator

__all__ = [
    "Finding",
    "LintResult",
    "Rule",
    "ProjectRule",
    "register",
    "register_project",
    "all_rules",
    "all_project_rules",
    "lint_source",
    "lint_paths",
    "main",
]

#: ``# ftc: ignore[id1,id2]`` with an optional ``-- reason`` tail
_SUPPRESS_RE = re.compile(
    r"#\s*ftc:\s*ignore\[(?P<ids>[a-z0-9_,\-\s]+)\]"
    r"(?:\s*--\s*(?P<reason>.*))?",
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule hit at one source location."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}{tag}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    plane: str  # "compute" | "controller"
    summary: str
    check: Callable[[ast.Module, str, str], Iterable[tuple[int, int, str]]]


@dataclasses.dataclass(frozen=True)
class ProjectRule:
    """A rule over the whole-package index (``analysis/project.py``):
    ``check(project)`` yields ``(path, line, col, message)``.

    ``heavy`` rules import jax / compile programs and are EXCLUDED from the
    default registry so the pure-AST pass keeps its 10s CI budget; they run
    only when named explicitly (``--rules shard-rule-coverage``), which is
    what the ``shard-audit-fast`` ci_check stage does."""

    id: str
    plane: str  # "flow" | "concurrency" | "protocol" | "sharding"
    summary: str
    check: Callable[[object], Iterable[tuple[str, int, int, str]]]
    heavy: bool = False


_REGISTRY: dict[str, Rule] = {}
_PROJECT_REGISTRY: dict[str, ProjectRule] = {}


def register(rule_id: str, plane: str, summary: str):
    """Decorator: register ``check(module, src, path)`` under ``rule_id``."""

    def deco(fn):
        if rule_id in _REGISTRY:
            raise ValueError(f"duplicate rule id {rule_id!r}")
        _REGISTRY[rule_id] = Rule(rule_id, plane, summary, fn)
        return fn

    return deco


def register_project(rule_id: str, plane: str, summary: str, *,
                     heavy: bool = False):
    """Decorator: register a project-wide ``check(project)`` under
    ``rule_id``.  Ids share one namespace with per-file rules (selectors
    don't care which kind they name).  ``heavy=True`` keeps the rule out of
    the default registry (see :class:`ProjectRule`)."""

    def deco(fn):
        if rule_id in _PROJECT_REGISTRY or rule_id in _REGISTRY:
            raise ValueError(f"duplicate rule id {rule_id!r}")
        _PROJECT_REGISTRY[rule_id] = ProjectRule(
            rule_id, plane, summary, fn, heavy=heavy
        )
        return fn

    return deco


def all_rules() -> dict[str, Rule]:
    """The per-file registry (importing the rule modules on first use)."""
    # imported lazily so `from .engine import register` inside the rule
    # modules doesn't cycle at package import time
    from . import rules_compute, rules_controller  # noqa: F401

    return dict(_REGISTRY)


def all_project_rules(include_heavy: bool = False) -> dict[str, ProjectRule]:
    """The project-wide registry (importing its rule modules on first use).

    Heavy rules (jax-importing: the sharding coverage/divisibility checks
    and the AOT collective audit) are excluded by default so the plain
    ``ftc-lint <pkg>`` pass stays inside its 10s CI budget; pass
    ``include_heavy=True`` (or name them via ``--rules``) to get them."""
    from . import (  # noqa: F401
        rules_concurrency,
        rules_flow,
        rules_protocol,
        rules_sharding,
    )

    rules = dict(_PROJECT_REGISTRY)
    if not include_heavy:
        rules = {k: v for k, v in rules.items() if not v.heavy}
    return rules


# ---- suppression handling --------------------------------------------------


def _suppressions(src: str) -> dict[int, set[str]]:
    """line number -> rule ids suppressed on that line."""
    out: dict[int, set[str]] = {}
    for i, text in enumerate(src.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if m:
            out[i] = {s.strip() for s in m.group("ids").split(",") if s.strip()}
    return out


def _is_suppressed(f: Finding, supp: dict[int, set[str]]) -> bool:
    for line in (f.line, f.line - 1):
        ids = supp.get(line)
        if ids and (f.rule in ids or "all" in ids):
            return True
    return False


# ---- running ---------------------------------------------------------------


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]
    errors: list[str]  # unparseable files etc.

    @property
    def active(self) -> list[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def exit_code(self) -> int:
        if self.errors:
            return 2
        return 1 if self.active else 0


def _lint_parsed(
    module: ast.Module, src: str, path: str, rules: dict[str, Rule]
) -> list[Finding]:
    supp = _suppressions(src)
    findings: list[Finding] = []
    seen: set[tuple] = set()
    for rule in rules.values():
        for line, col, message in rule.check(module, src, path):
            key = (rule.id, line, col)
            if key in seen:
                continue  # rules scanning nested scopes can visit a site twice
            seen.add(key)
            f = Finding(rule.id, path, line, col, message)
            if _is_suppressed(f, supp):
                f = dataclasses.replace(f, suppressed=True)
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_source(
    src: str,
    path: str = "<string>",
    rules: dict[str, Rule] | None = None,
) -> list[Finding]:
    """Lint one source string; returns findings with suppressions applied
    (suppressed findings are kept, flagged, for ``--show-suppressed``)."""
    rules = rules if rules is not None else all_rules()
    module = ast.parse(src, filename=path)
    return _lint_parsed(module, src, path, rules)


def _iter_py_files(paths: Iterable[str]) -> Iterator[Path]:
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            yield from sorted(p.rglob("*.py"))
        else:
            yield p


def lint_paths(
    paths: Iterable[str],
    rules: dict[str, Rule] | None = None,
    *,
    project_rules: dict[str, ProjectRule] | None = None,
    source_overrides: dict[str, str] | None = None,
) -> LintResult:
    """Lint files/directories with per-file AND project-wide rules.

    ``project_rules=None`` runs the full project registry; pass ``{}`` to
    skip the interprocedural pass.  ``source_overrides`` (absolute path ->
    source text) lints the tree with files swapped in memory — the
    mutation-test hook (delete an RPC handler, watch the lint turn red).
    """
    rules = rules if rules is not None else all_rules()
    project_rules = (
        project_rules if project_rules is not None else all_project_rules()
    )
    overrides = {str(Path(k)): v for k, v in (source_overrides or {}).items()}
    findings: list[Finding] = []
    errors: list[str] = []
    sources: dict[str, str] = {}
    path_list = list(paths)
    # ONE parse per file: the project index doubles as the parse cache for
    # the per-file pass (the 10s CI budget covers both passes together)
    project = None
    if project_rules:
        from .project import build_project

        project = build_project(path_list, source_overrides=overrides)
    for path in _iter_py_files(path_list):
        key = str(path)
        mod = project.modules_by_path.get(key) if project is not None else None
        if mod is not None:
            src = mod.src
        else:
            src = overrides.get(key)
            if src is None:
                try:
                    src = path.read_text(encoding="utf-8")
                except OSError as exc:
                    errors.append(f"{path}: unreadable: {exc}")
                    continue
        sources[key] = src
        try:
            if mod is not None:
                findings.extend(_lint_parsed(mod.tree, src, key, rules))
            else:
                findings.extend(lint_source(src, key, rules))
        except SyntaxError as exc:
            errors.append(f"{path}: parse error: {exc}")
    if project_rules:
        supp_cache: dict[str, dict[int, set[str]]] = {}

        def suppressions_for(path: str) -> dict[int, set[str]]:
            supp = supp_cache.get(path)
            if supp is None:
                src = sources.get(path)
                if src is None:  # e.g. a finding anchored in docs/*.md
                    try:
                        src = Path(path).read_text(encoding="utf-8")
                    except OSError:
                        src = ""
                supp = supp_cache[path] = _suppressions(src)
            return supp

        seen: set[tuple] = set()
        for rule in project_rules.values():
            for fpath, line, col, message in rule.check(project):
                # message included: one call site can carry DISTINCT findings
                # (a required key missing AND a sent key unread, same line)
                key = (rule.id, fpath, line, col, message)
                if key in seen:
                    continue
                seen.add(key)
                f = Finding(rule.id, fpath, line, col, message)
                if _is_suppressed(f, suppressions_for(fpath)):
                    f = dataclasses.replace(f, suppressed=True)
                findings.append(f)
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return LintResult(findings=findings, errors=errors)


# ---- CLI -------------------------------------------------------------------


def _select_rules(
    select: str | None, ignore: str | None
) -> tuple[dict[str, Rule], dict[str, ProjectRule]]:
    """Apply ``--select``/``--ignore`` (aka ``--rules``/``--exclude-rules``)
    across BOTH registries — selectors name rule ids, not rule kinds.
    Naming a heavy rule in ``--select`` opts it in; without a selector the
    default (non-heavy) registry runs."""
    rules = all_rules()
    project_rules = all_project_rules(include_heavy=True)
    known = rules.keys() | project_rules.keys()
    if select:
        wanted = {s.strip() for s in select.split(",") if s.strip()}
        unknown = wanted - known
        if unknown:
            raise SystemExit(f"ftc-lint: unknown rule(s): {sorted(unknown)}")
        rules = {k: v for k, v in rules.items() if k in wanted}
        project_rules = {k: v for k, v in project_rules.items() if k in wanted}
    else:
        project_rules = {
            k: v for k, v in project_rules.items() if not v.heavy
        }
    if ignore:
        dropped = {s.strip() for s in ignore.split(",") if s.strip()}
        unknown = dropped - known
        if unknown:
            raise SystemExit(f"ftc-lint: unknown rule(s): {sorted(unknown)}")
        rules = {k: v for k, v in rules.items() if k not in dropped}
        project_rules = {
            k: v for k, v in project_rules.items() if k not in dropped
        }
    return rules, project_rules


def _sarif_doc(shown: list[Finding], errors: list[str]) -> dict:
    """SARIF 2.1.0 payload: one run, findings as results, suppressed ones
    carrying an ``inSource`` suppression so viewers render them greyed."""
    metas: dict[str, str] = {}
    for reg in (all_rules(), all_project_rules(include_heavy=True)):
        for rid, rule in reg.items():
            metas[rid] = rule.summary
    used = sorted({f.rule for f in shown})
    return {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                   "master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "ftc-lint",
                "informationUri": "docs/static_analysis.md",
                "rules": [
                    {"id": rid,
                     "shortDescription": {"text": metas.get(rid, rid)}}
                    for rid in used
                ],
            }},
            "results": [
                {
                    "ruleId": f.rule,
                    "level": "warning",
                    "message": {"text": f.message},
                    **({"suppressions": [{"kind": "inSource"}]}
                       if f.suppressed else {}),
                    "locations": [{
                        "physicalLocation": {
                            "artifactLocation": {"uri": f.path},
                            "region": {
                                "startLine": f.line,
                                "startColumn": f.col + 1,
                            },
                        },
                    }],
                }
                for f in shown
            ],
            "invocations": [{
                "executionSuccessful": not errors,
                **({"toolExecutionNotifications": [
                    {"level": "error", "message": {"text": e}}
                    for e in errors
                ]} if errors else {}),
            }],
        }],
    }


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="ftc-lint",
        description="JAX-aware static analysis for finetune-controller-tpu "
        "(docs/static_analysis.md)",
    )
    p.add_argument("paths", nargs="*", default=["finetune_controller_tpu"],
                   help="files or directories (default: the package)")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text")
    p.add_argument("--select", "--rules", dest="select",
                   help="comma-separated rule ids to run")
    p.add_argument("--ignore", "--exclude-rules", dest="ignore",
                   help="comma-separated rule ids to skip")
    p.add_argument("--no-project", action="store_true",
                   help="skip the project-wide (interprocedural) pass")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print findings silenced by ftc: ignore")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        rows = list(all_rules().values()) + list(
            all_project_rules(include_heavy=True).values()
        )
        for rule in sorted(rows, key=lambda r: (r.plane, r.id)):
            tag = " [heavy: run via --rules]" if getattr(rule, "heavy", False) \
                else ""
            print(f"{rule.id:30} [{rule.plane:11}] {rule.summary}{tag}")
        return 0

    rules, project_rules = _select_rules(args.select, args.ignore)
    if args.no_project:
        project_rules = {}
    result = lint_paths(args.paths, rules, project_rules=project_rules)

    shown = result.findings if args.show_suppressed else result.active
    if args.format == "sarif":
        print(json.dumps(_sarif_doc(shown, result.errors), indent=2))
    elif args.format == "json":
        print(json.dumps({
            "findings": [f.to_json() for f in shown],
            "errors": result.errors,
            "counts": {
                "active": len(result.active),
                "suppressed": len(result.findings) - len(result.active),
            },
        }, indent=2))
    else:
        for f in shown:
            print(f.render())
        for err in result.errors:
            print(f"error: {err}", file=sys.stderr)
        n_sup = len(result.findings) - len(result.active)
        print(
            f"ftc-lint: {len(result.active)} finding(s), "
            f"{n_sup} suppressed, {len(result.errors)} error(s)",
            file=sys.stderr,
        )
    return result.exit_code


if __name__ == "__main__":
    raise SystemExit(main())
