"""The ftc-lint engine: file walker, rule registry, suppressions, reporting.

A rule is a callable ``(module: ast.Module, src: str, path: str) -> iterable
of (line, col, message)`` registered under a kebab-case id with
:func:`register`.  The engine parses each file once, runs every selected rule
over the tree, then drops findings covered by an inline suppression::

    risky_line()  # ftc: ignore[rule-id] -- why this is intentional

A suppression comment matches on the finding's own line or the line directly
above it (for statements too long to share a line with their justification),
and may carry several ids: ``# ftc: ignore[silent-except,host-sync-in-jit]``.
The ``-- reason`` tail is free text; CI policy (docs/static_analysis.md) is
that every suppression carries one.

Exit codes: 0 clean, 1 unsuppressed findings, 2 parse/usage errors.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import re
import sys
from pathlib import Path
from typing import Callable, Iterable, Iterator

__all__ = [
    "Finding",
    "LintResult",
    "Rule",
    "register",
    "all_rules",
    "lint_source",
    "lint_paths",
    "main",
]

#: ``# ftc: ignore[id1,id2]`` with an optional ``-- reason`` tail
_SUPPRESS_RE = re.compile(
    r"#\s*ftc:\s*ignore\[(?P<ids>[a-z0-9_,\-\s]+)\]"
    r"(?:\s*--\s*(?P<reason>.*))?",
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule hit at one source location."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}{tag}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    plane: str  # "compute" | "controller"
    summary: str
    check: Callable[[ast.Module, str, str], Iterable[tuple[int, int, str]]]


_REGISTRY: dict[str, Rule] = {}


def register(rule_id: str, plane: str, summary: str):
    """Decorator: register ``check(module, src, path)`` under ``rule_id``."""

    def deco(fn):
        if rule_id in _REGISTRY:
            raise ValueError(f"duplicate rule id {rule_id!r}")
        _REGISTRY[rule_id] = Rule(rule_id, plane, summary, fn)
        return fn

    return deco


def all_rules() -> dict[str, Rule]:
    """The full registry (importing the rule modules on first use)."""
    # imported lazily so `from .engine import register` inside the rule
    # modules doesn't cycle at package import time
    from . import rules_compute, rules_controller  # noqa: F401

    return dict(_REGISTRY)


# ---- suppression handling --------------------------------------------------


def _suppressions(src: str) -> dict[int, set[str]]:
    """line number -> rule ids suppressed on that line."""
    out: dict[int, set[str]] = {}
    for i, text in enumerate(src.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if m:
            out[i] = {s.strip() for s in m.group("ids").split(",") if s.strip()}
    return out


def _is_suppressed(f: Finding, supp: dict[int, set[str]]) -> bool:
    for line in (f.line, f.line - 1):
        ids = supp.get(line)
        if ids and (f.rule in ids or "all" in ids):
            return True
    return False


# ---- running ---------------------------------------------------------------


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]
    errors: list[str]  # unparseable files etc.

    @property
    def active(self) -> list[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def exit_code(self) -> int:
        if self.errors:
            return 2
        return 1 if self.active else 0


def lint_source(
    src: str,
    path: str = "<string>",
    rules: dict[str, Rule] | None = None,
) -> list[Finding]:
    """Lint one source string; returns findings with suppressions applied
    (suppressed findings are kept, flagged, for ``--show-suppressed``)."""
    rules = rules if rules is not None else all_rules()
    module = ast.parse(src, filename=path)
    supp = _suppressions(src)
    findings: list[Finding] = []
    seen: set[tuple] = set()
    for rule in rules.values():
        for line, col, message in rule.check(module, src, path):
            key = (rule.id, line, col)
            if key in seen:
                continue  # rules scanning nested scopes can visit a site twice
            seen.add(key)
            f = Finding(rule.id, path, line, col, message)
            if _is_suppressed(f, supp):
                f = dataclasses.replace(f, suppressed=True)
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def _iter_py_files(paths: Iterable[str]) -> Iterator[Path]:
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            yield from sorted(p.rglob("*.py"))
        else:
            yield p


def lint_paths(
    paths: Iterable[str],
    rules: dict[str, Rule] | None = None,
) -> LintResult:
    rules = rules if rules is not None else all_rules()
    findings: list[Finding] = []
    errors: list[str] = []
    for path in _iter_py_files(paths):
        try:
            src = path.read_text(encoding="utf-8")
        except OSError as exc:
            errors.append(f"{path}: unreadable: {exc}")
            continue
        try:
            findings.extend(lint_source(src, str(path), rules))
        except SyntaxError as exc:
            errors.append(f"{path}: parse error: {exc}")
    return LintResult(findings=findings, errors=errors)


# ---- CLI -------------------------------------------------------------------


def _select_rules(select: str | None, ignore: str | None) -> dict[str, Rule]:
    rules = all_rules()
    if select:
        wanted = {s.strip() for s in select.split(",") if s.strip()}
        unknown = wanted - rules.keys()
        if unknown:
            raise SystemExit(f"ftc-lint: unknown rule(s): {sorted(unknown)}")
        rules = {k: v for k, v in rules.items() if k in wanted}
    if ignore:
        dropped = {s.strip() for s in ignore.split(",") if s.strip()}
        unknown = dropped - all_rules().keys()
        if unknown:
            raise SystemExit(f"ftc-lint: unknown rule(s): {sorted(unknown)}")
        rules = {k: v for k, v in rules.items() if k not in dropped}
    return rules


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="ftc-lint",
        description="JAX-aware static analysis for finetune-controller-tpu "
        "(docs/static_analysis.md)",
    )
    p.add_argument("paths", nargs="*", default=["finetune_controller_tpu"],
                   help="files or directories (default: the package)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--select", help="comma-separated rule ids to run")
    p.add_argument("--ignore", help="comma-separated rule ids to skip")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print findings silenced by ftc: ignore")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule in sorted(all_rules().values(), key=lambda r: (r.plane, r.id)):
            print(f"{rule.id:30} [{rule.plane:10}] {rule.summary}")
        return 0

    rules = _select_rules(args.select, args.ignore)
    result = lint_paths(args.paths, rules)

    shown = result.findings if args.show_suppressed else result.active
    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_json() for f in shown],
            "errors": result.errors,
            "counts": {
                "active": len(result.active),
                "suppressed": len(result.findings) - len(result.active),
            },
        }, indent=2))
    else:
        for f in shown:
            print(f.render())
        for err in result.errors:
            print(f"error: {err}", file=sys.stderr)
        n_sup = len(result.findings) - len(result.active)
        print(
            f"ftc-lint: {len(result.active)} finding(s), "
            f"{n_sup} suppressed, {len(result.errors)} error(s)",
            file=sys.stderr,
        )
    return result.exit_code


if __name__ == "__main__":
    raise SystemExit(main())
