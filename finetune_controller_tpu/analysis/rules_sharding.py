"""Sharding-conformance rules: the partition-rule layer becomes checkable.

ROADMAP item 1 (the unified partition-rule layer) is the most invasive
refactor on the books, and until now nothing understood *sharding*: a dead
or shadowed entry in ``parallel/sharding.py::LLAMA_RULES``, a
``PartitionSpec`` naming an axis no mesh defines, or a spec whose mesh-axis
product stops dividing a leaf dim all compile fine and surface — if ever —
as a deep XLA partitioner error or a silent full-replication bandwidth tax.
This module closes that gap in two tiers:

**Fast (pure-AST, ride the default <10s lint stage):**

* ``shard-undefined-axis`` — string axis names inside
  ``PartitionSpec``/``P``/``NamedSharding`` literals (including specs built
  for ``with_sharding_constraint``) must be axes some mesh builder defines:
  the ``AxisNames`` table in ``parallel/mesh.py``, or a module-local
  ``Mesh(..., ("x",))`` construction (diagnostics meshes).  A typo'd axis
  raises at run time only on the code path that hits it; here it's red on
  every lint.
* ``shard-unsharded-device-put`` — a bare single-argument
  ``jax.device_put(x)`` on a multi-chip path (``parallel``/``train``/
  ``serve``/``transport``/``data`` subpackages) lands the array wherever
  the default device points — usually device 0 or full replication — and
  GSPMD quietly reshards it at the next jit boundary.  Pass the rule-table
  ``NamedSharding`` explicitly.

**Heavy (import jax / compile; excluded from the default registry, run by
the ``shard-audit-fast`` ci_check stage via ``--rules``):**

* ``shard-rule-coverage`` — reconstructs every ``PartitionRules`` table
  from its source AST (so mutation tests can rewrite the table text) and
  validates it against abstract ``jax.eval_shape`` param trees of the
  catalog presets (dense+LoRA, QLoRA int4, MoE, multimodal): every leaf
  matched by a rule; rules that match nothing (dead) or whose every match
  is taken by an earlier pattern (shadowed) flagged at their own line; spec
  axis names checked against ``AxisNames``; and — the deleted-rule trap —
  any matmul-weight leaf (``kernel``/``embedding``/``experts_*``/
  ``lora_*``) falling through to the bare ``.*`` catch-all is red, because
  replicate-by-default for a weight family is never a decision someone
  made on purpose.
* ``shard-divisibility`` — for each catalog topology (``train/aot.py::
  REALSCALE`` real-shape configs plus the simulated audit meshes), proves
  the resolved spec of every leaf names real mesh axes and that the
  mesh-axis product divides the leaf dim it shards — the static twin of
  the runtime check ``parallel/sharding.py::validate_spec`` now performs.
* ``collective-conformance`` — runs the AOT collective audit
  (``analysis/collective_audit.py``) and diffs the compiled HLO's
  collective set BOTH WAYS against the machine-checked **Collective
  catalog** in ``docs/performance.md``: an undocumented collective (the
  headline bug class: an unexpected full-param all-gather in the step
  body) or a documented-but-vanished one is red.

Fixture opt-outs mirror lint v2: no ``parallel/mesh.py`` module → axis
rules skip; no ``PartitionRules`` table → coverage skips; no Collective
catalog heading → conformance skips.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Any, Iterator

from ._astutil import dotted_name, terminal_name
from .engine import register_project

# ---------------------------------------------------------------------------
# mesh-axis extraction (shared)
# ---------------------------------------------------------------------------


def _mesh_module(project):
    for module in project.modules.values():
        if Path(module.path).as_posix().endswith("parallel/mesh.py"):
            return module
    return None


def _resolve_axis_value(node: ast.AST, attr_map: dict[str, Any]):
    """Evaluate an ``AxisNames`` class-body value: a string constant, a
    reference to an earlier attr, or a tuple of either (``BATCH_AXES =
    (DATA, FSDP)``)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name) and node.id in attr_map:
        return attr_map[node.id]
    if isinstance(node, (ast.Tuple, ast.List)):
        parts = [_resolve_axis_value(e, attr_map) for e in node.elts]
        if all(p is not None for p in parts):
            return tuple(parts)
    return None


def _axis_table(mesh_module) -> tuple[dict[str, Any], set[str]] | None:
    """``(AxisNames attr -> value, set of defined axis name strings)`` from
    the mesh module's AST, or None when it defines no ``AxisNames``."""
    cls = next(
        (n for n in ast.walk(mesh_module.tree)
         if isinstance(n, ast.ClassDef) and n.name == "AxisNames"),
        None,
    )
    if cls is None:
        return None
    attr_map: dict[str, Any] = {}
    values: set[str] = set()
    for stmt in cls.body:
        if isinstance(stmt, ast.Assign):
            targets = [t.id for t in stmt.targets if isinstance(t, ast.Name)]
            value = stmt.value
        elif isinstance(stmt, ast.AnnAssign) and \
                isinstance(stmt.target, ast.Name) and stmt.value is not None:
            targets, value = [stmt.target.id], stmt.value
        else:
            continue
        resolved = _resolve_axis_value(value, attr_map)
        if resolved is None:
            continue
        for t in targets:
            attr_map[t] = resolved
        for v in (resolved if isinstance(resolved, tuple) else (resolved,)):
            if isinstance(v, str):
                values.add(v)
    return attr_map, values


def _call_target(module, call: ast.Call) -> str:
    """Best-effort absolute dotted target of a call's callee."""
    func = call.func
    if isinstance(func, ast.Name):
        return module.imports.get(func.id, func.id)
    dotted = dotted_name(func)
    if dotted:
        head, _, rest = dotted.partition(".")
        head = module.imports.get(head, head)
        return f"{head}.{rest}" if rest else head
    return terminal_name(func) or ""


def _local_mesh_axes(module) -> set[str]:
    """Axis names a module defines by constructing ``Mesh(...)`` directly
    (diagnostics meshes like ``Mesh(devs, ("x",))``) — legal in specs within
    that module even though no shared builder exports them."""
    out: set[str] = set()
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        if _call_target(module, node).split(".")[-1] != "Mesh":
            continue
        sources = list(node.args[1:]) + [
            kw.value for kw in node.keywords if kw.arg == "axis_names"
        ]
        for src in sources:
            for c in ast.walk(src):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    out.add(c.value)
    return out


# ---------------------------------------------------------------------------
# fast rule: undefined axis names in sharding literals
# ---------------------------------------------------------------------------

_SPEC_CTORS = ("PartitionSpec", "NamedSharding")


def _axis_constants(call: ast.Call) -> Iterator[ast.Constant]:
    """String constants in a spec constructor's POSITIONAL args (keyword
    args like ``memory_kind="pinned_host"`` are not axis names), skipping
    nested calls — the outer walk visits those on its own."""
    for arg in call.args:
        stack: list[ast.AST] = [arg]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Call):
                continue
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                yield n
            else:
                stack.extend(ast.iter_child_nodes(n))


@register_project(
    "shard-undefined-axis",
    "sharding",
    "PartitionSpec/NamedSharding literals may only name axes a mesh defines",
)
def shard_undefined_axis(project):
    mesh_mod = _mesh_module(project)
    table = _axis_table(mesh_mod) if mesh_mod is not None else None
    if table is None:
        return  # fixture trees without a mesh module opt out
    _attr_map, defined = table
    for module in project.modules.values():
        # cheap source pre-filter: most modules never spell a spec ctor,
        # and this rule rides the 10s default lint stage
        if not any(ctor in module.src for ctor in _SPEC_CTORS):
            continue
        local: set[str] | None = None  # lazy: one extra AST walk, and only
        for node in ast.walk(module.tree):  # for modules with unknown axes
            if not isinstance(node, ast.Call):
                continue
            if _call_target(module, node).split(".")[-1] not in _SPEC_CTORS:
                continue
            for const in _axis_constants(node):
                if const.value in defined:
                    continue
                if local is None:
                    local = _local_mesh_axes(module)
                if const.value not in local:
                    yield (
                        module.path, const.lineno, const.col_offset,
                        f"sharding literal names axis {const.value!r}, but "
                        "no mesh defines it (parallel/mesh.py AxisNames: "
                        f"{', '.join(sorted(defined))}) — a typo'd axis "
                        "raises only on the code path that hits it",
                    )


# ---------------------------------------------------------------------------
# fast rule: device_put without explicit placement on multi-chip paths
# ---------------------------------------------------------------------------

_MULTICHIP_SEGMENTS = {"parallel", "train", "serve", "transport", "data"}


@register_project(
    "shard-unsharded-device-put",
    "sharding",
    "jax.device_put on multi-chip paths must pass an explicit sharding",
)
def shard_unsharded_device_put(project):
    if _mesh_module(project) is None:
        return  # single-chip fixture trees opt out
    for module in project.modules.values():
        if not (_MULTICHIP_SEGMENTS & set(module.name.split("."))):
            continue
        if "device_put" not in module.src:  # skip the AST walk entirely
            continue
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if _call_target(module, node) != "jax.device_put":
                continue
            explicit = len(node.args) >= 2 or any(
                kw.arg == "device" for kw in node.keywords
            )
            if not explicit:
                yield (
                    module.path, node.lineno, node.col_offset,
                    "jax.device_put without an explicit sharding on a "
                    "multi-chip path lands the array on the default device "
                    "(replicated or device 0) and GSPMD silently reshards "
                    "it at the next jit boundary — pass the rule-table "
                    "NamedSharding (parallel/sharding.py)",
                )


# ---------------------------------------------------------------------------
# PartitionRules table extraction (AST — mutation tests rewrite the source)
# ---------------------------------------------------------------------------


class _Entry:
    __slots__ = ("line", "col", "pattern", "spec")

    def __init__(self, line, col, pattern, spec):
        self.line, self.col = line, col
        self.pattern = pattern  # str | None (unparseable)
        self.spec = spec  # tuple of (None | str | tuple[str, ...]) | None


class _Table:
    __slots__ = ("module", "name", "line", "entries")

    def __init__(self, module, name, line, entries):
        self.module, self.name, self.line = module, name, line
        self.entries = entries

    @property
    def parsed(self) -> bool:
        return all(
            e.pattern is not None and e.spec is not None for e in self.entries
        )


def _eval_spec_entry(node: ast.AST, attr_map: dict[str, Any]):
    """One positional arg of a ``P(...)`` spec: None, an axis string, an
    ``Ax.NAME`` attribute, or a tuple of those.  Returns the Python value
    or raises ValueError when unresolvable."""
    if isinstance(node, ast.Constant) and (
        node.value is None or isinstance(node.value, str)
    ):
        return node.value
    if isinstance(node, ast.Attribute) and node.attr in attr_map:
        return attr_map[node.attr]
    if isinstance(node, ast.Name) and node.id in attr_map:
        return attr_map[node.id]
    if isinstance(node, (ast.Tuple, ast.List)):
        parts = []
        for e in node.elts:
            v = _eval_spec_entry(e, attr_map)
            parts.extend(v) if isinstance(v, tuple) else parts.append(v)
        return tuple(parts)
    raise ValueError(ast.dump(node))


def _eval_spec(node: ast.AST, attr_map: dict[str, Any], module):
    """A rule entry's spec: a ``P(...)``/``PartitionSpec(...)`` call whose
    args all evaluate; None when it doesn't."""
    if not isinstance(node, ast.Call) or node.keywords:
        return None
    if _call_target(module, node).split(".")[-1] != "PartitionSpec":
        return None
    try:
        return tuple(_eval_spec_entry(a, attr_map) for a in node.args)
    except ValueError:
        return None


def _find_tables(project, attr_map: dict[str, Any]) -> list[_Table]:
    tables: list[_Table] = []
    for module in project.modules.values():
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            call = node.value
            if _call_target(module, call).split(".")[-1] != "PartitionRules":
                continue
            if not (call.args and isinstance(call.args[0],
                                             (ast.List, ast.Tuple))):
                continue
            name = next(
                (t.id for t in node.targets if isinstance(t, ast.Name)),
                "<anon>",
            )
            entries = []
            for elt in call.args[0].elts:
                if isinstance(elt, ast.Tuple) and len(elt.elts) == 2:
                    pat_node, spec_node = elt.elts
                    pattern = (
                        pat_node.value
                        if isinstance(pat_node, ast.Constant)
                        and isinstance(pat_node.value, str) else None
                    )
                    spec = _eval_spec(spec_node, attr_map, module)
                else:
                    pattern = spec = None
                entries.append(
                    _Entry(elt.lineno, elt.col_offset, pattern, spec)
                )
            tables.append(_Table(module, name, node.lineno, entries))
    return tables


def _build_rules(table: _Table):
    """Runtime ``PartitionRules`` reconstructed from the parsed AST table —
    first-match semantics, pipe-axis stacking and rank handling all come
    from the real class, not a reimplementation."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.sharding import PartitionRules

    return PartitionRules(
        [(e.pattern, P(*e.spec)) for e in table.entries]
    )


# ---------------------------------------------------------------------------
# abstract catalog param trees (heavy, cached per process)
# ---------------------------------------------------------------------------

_VARIANT_CACHE: dict[str, list[tuple[str, Any]]] | None = None
_PRESET_CACHE: dict[str, list[tuple[str, Any]]] = {}


def _shape_leaves(model, *args) -> list[tuple[str, Any]]:
    import jax

    from ..parallel.sharding import key_path_str

    shapes = jax.eval_shape(
        model.init, {"params": jax.random.PRNGKey(0)}, *args
    )
    return [
        (key_path_str(kp), leaf)
        for kp, leaf in jax.tree_util.tree_leaves_with_path(shapes)
    ]


def _validation_trees() -> dict[str, list[tuple[str, Any]]]:
    """Abstract param trees spanning every weight family the rule tables
    must cover: dense+LoRA (untied, so lm_head exists), QLoRA int4 scales,
    MoE experts + router, and the multimodal projector + ViT tower.  All
    ``eval_shape`` — no parameter memory is allocated."""
    global _VARIANT_CACHE
    if _VARIANT_CACHE is not None:
        return _VARIANT_CACHE
    import jax.numpy as jnp

    from ..models.llama import PRESETS, LlamaForCausalLM
    from ..models.lora import LoRAConfig
    from ..models.multimodal import MM_PRESETS, LlavaForCausalLM

    tokens = jnp.zeros((1, 8), jnp.int32)
    out: dict[str, list[tuple[str, Any]]] = {}
    cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=4))
    out["tiny-test+lora"] = _shape_leaves(LlamaForCausalLM(cfg), tokens)
    cfg_q = PRESETS["tiny-test"].replace(
        lora=LoRAConfig(rank=4), quantize_base=True
    )
    out["tiny-test+qlora"] = _shape_leaves(LlamaForCausalLM(cfg_q), tokens)
    cfg_moe = PRESETS["tiny-moe-test"].replace(
        lora=LoRAConfig(rank=4), quantize_base=True
    )
    out["tiny-moe-test+qlora"] = _shape_leaves(
        LlamaForCausalLM(cfg_moe), tokens
    )
    mm = MM_PRESETS["tiny-mm-test"].replace(lora=LoRAConfig(rank=4))
    pixels = jnp.zeros(
        (1, mm.vision.image_size, mm.vision.image_size, 3), jnp.float32
    )
    out["tiny-mm-test+lora"] = _shape_leaves(
        LlavaForCausalLM(mm), tokens, pixels
    )
    _VARIANT_CACHE = out
    return out


def _preset_leaves(preset: str) -> list[tuple[str, Any]]:
    """Abstract param tree of a REALSCALE preset with the aot.py LoRA
    setup (rank 16) — real shapes, zero bytes allocated."""
    if preset not in _PRESET_CACHE:
        import jax.numpy as jnp

        from ..models.llama import PRESETS, LlamaForCausalLM
        from ..models.lora import LoRAConfig

        cfg = PRESETS[preset].replace(lora=LoRAConfig(rank=16))
        _PRESET_CACHE[preset] = _shape_leaves(
            LlamaForCausalLM(cfg), jnp.zeros((1, 8), jnp.int32)
        )
    return _PRESET_CACHE[preset]


# ---------------------------------------------------------------------------
# heavy rule: rule-table coverage (dead / shadowed / unmatched / fallthrough)
# ---------------------------------------------------------------------------


def _weight_like(path: str) -> bool:
    """Matmul-weight leaves: the ones whose sharding is always a decision.
    Norm scales / biases / rotary tables replicate by design and may ride
    the catch-all."""
    last = path.rsplit("/", 1)[-1]
    return (
        last in ("kernel", "embedding")
        or last.startswith("experts_")
        or last.startswith("lora_")
    )


@register_project(
    "shard-rule-coverage",
    "sharding",
    "every PartitionRules entry is live and every catalog param leaf is covered",
    heavy=True,
)
def shard_rule_coverage(project):
    mesh_mod = _mesh_module(project)
    table_info = _axis_table(mesh_mod) if mesh_mod is not None else None
    if table_info is None:
        return
    attr_map, defined = table_info
    tables = [t for t in _find_tables(project, attr_map) if t.parsed]
    if not tables:
        return
    trees = _validation_trees()

    for table in tables:
        # spec axis names against the mesh builders
        for entry in table.entries:
            for part in entry.spec:
                axes = part if isinstance(part, tuple) else (part,)
                for ax in axes:
                    if ax is not None and ax not in defined:
                        yield (
                            table.module.path, entry.line, entry.col,
                            f"rule {entry.pattern!r} spec names axis "
                            f"{ax!r}, but no mesh defines it (AxisNames: "
                            f"{', '.join(sorted(defined))})",
                        )

        rules = _build_rules(table)
        n = len(table.entries)
        catch_all = (
            n - 1 if table.entries and table.entries[-1].pattern == ".*"
            else None
        )
        first_hits: dict[int, str] = {}  # rule index -> witness path
        all_paths: list[str] = []
        for variant, leaves in trees.items():
            for path, _leaf in leaves:
                all_paths.append(path)
                idx = rules.match_index(path)
                if idx is None:
                    yield (
                        table.module.path, table.line, 0,
                        f"param leaf {path!r} ({variant}) is matched by no "
                        f"rule in {table.name} — every leaf needs an "
                        "explicit sharding decision (or a catch-all)",
                    )
                    continue
                first_hits.setdefault(idx, path)
                if idx == catch_all and _weight_like(path):
                    yield (
                        table.module.path, table.entries[idx].line,
                        table.entries[idx].col,
                        f"weight leaf {path!r} ({variant}) falls through to "
                        f"the bare catch-all in {table.name} — a "
                        "kernel/embedding replicated by DEFAULT is a "
                        "deleted or never-written rule, not a decision; "
                        "add an explicit entry for this family",
                    )

        compiled = [re.compile(e.pattern) for e in table.entries]
        for i, entry in enumerate(table.entries):
            if i in first_hits:
                continue
            witness = next(
                (p for p in all_paths if compiled[i].search(p)), None
            )
            if witness is None:
                yield (
                    table.module.path, entry.line, entry.col,
                    f"dead rule: {entry.pattern!r} matches no param leaf of "
                    "any catalog preset (dense+LoRA, QLoRA, MoE, "
                    "multimodal) — delete it, or it is a typo'd pattern "
                    "silently replicating the leaves it meant to shard",
                )
            else:
                j = rules.match_index(witness)
                shadow = table.entries[j]
                yield (
                    table.module.path, entry.line, entry.col,
                    f"shadowed rule: every leaf {entry.pattern!r} matches "
                    f"(e.g. {witness!r}) is taken first by the earlier rule "
                    f"{shadow.pattern!r} (line {shadow.line}) — reorder or "
                    "delete; first match wins",
                )


# ---------------------------------------------------------------------------
# heavy rule: axis sizes divide leaf dims on every catalog topology
# ---------------------------------------------------------------------------


def _catalog_topologies() -> list[tuple[str, str, dict[str, int]]]:
    """``(config name, preset, resolved axis sizes)`` for every catalog
    topology: the REALSCALE real-shape configs plus the simulated
    collective-audit meshes (tiny preset)."""
    from ..parallel.mesh import MeshSpec
    from ..train.aot import REALSCALE
    from .collective_audit import TOPOLOGIES

    out = []
    for name, spec in REALSCALE.items():
        sizes = MeshSpec(**spec["mesh"]).resolve(spec["n_devices"])
        out.append((name, spec["preset"], sizes))
    for name, spec in TOPOLOGIES.items():
        sizes = MeshSpec(**spec["mesh"]).resolve(spec["n_devices"])
        out.append((name, "tiny-test", sizes))
    return out


def _divisibility_error(
    path: str, shape: tuple, spec, sizes: dict[str, int]
) -> str | None:
    for dim, part in enumerate(spec):
        if part is None:
            continue
        axes = part if isinstance(part, (tuple, list)) else (part,)
        factor = 1
        for ax in axes:
            if ax not in sizes:
                return (
                    f"resolves {path!r} to spec {tuple(spec)} naming mesh "
                    f"axis {ax!r}, which this topology does not define"
                )
            factor *= sizes[ax]
        if dim >= len(shape) or (factor > 1 and shape[dim] % factor):
            size = shape[dim] if dim < len(shape) else "<missing>"
            return (
                f"resolves {path!r} (shape {tuple(shape)}) to spec "
                f"{tuple(spec)}, but dim {dim} (size {size}) is not "
                f"divisible by the {factor}-way sharding over {tuple(axes)}"
            )
    return None


@register_project(
    "shard-divisibility",
    "sharding",
    "resolved specs divide real leaf dims on every catalog topology",
    heavy=True,
)
def shard_divisibility(project):
    mesh_mod = _mesh_module(project)
    table_info = _axis_table(mesh_mod) if mesh_mod is not None else None
    if table_info is None:
        return
    attr_map, _defined = table_info
    tables = [t for t in _find_tables(project, attr_map) if t.parsed]
    if not tables:
        return

    for table in tables:
        rules = _build_rules(table)
        seen: set[tuple[int, str]] = set()  # (rule idx, message) dedup
        for cfg_name, preset, sizes in _catalog_topologies():
            for path, leaf in _preset_leaves(preset):
                idx = rules.match_index(path)
                if idx is None:
                    continue  # shard-rule-coverage owns unmatched leaves
                spec = rules.spec_for(path, leaf)
                err = _divisibility_error(path, tuple(leaf.shape), spec, sizes)
                if err is None:
                    continue
                key = (idx, err)
                if key in seen:
                    continue
                seen.add(key)
                entry = table.entries[idx]
                yield (
                    table.module.path, entry.line, entry.col,
                    f"on topology {cfg_name} ({_fmt_sizes(sizes)}), rule "
                    f"{entry.pattern!r} {err} — this compiles into a deep "
                    "XLA partitioner error (or worse, silent padding)",
                )


def _fmt_sizes(sizes: dict[str, int]) -> str:
    return "×".join(f"{k}{v}" for k, v in sizes.items() if v > 1) or "1 chip"


# ---------------------------------------------------------------------------
# heavy rule: compiled collectives match docs/performance.md
# ---------------------------------------------------------------------------


@register_project(
    "collective-conformance",
    "sharding",
    "compiled HLO collective sets match the Collective catalog in docs/performance.md",
    heavy=True,
)
def collective_conformance(project):
    docs = project.docs_file("performance.md")
    if docs is None:
        return  # fixture trees without docs opt out
    from .collective_audit import diff_catalog, full_audit, parse_catalog

    catalog, heading_line = parse_catalog(
        docs.read_text(encoding="utf-8")
    )
    if not catalog:
        return  # no catalog section yet: nothing to conform to
    observed = full_audit()
    for msg in diff_catalog(observed, catalog):
        yield (str(docs), heading_line, 0, msg)
