"""`python -m finetune_controller_tpu.analysis` == the ftc-lint CLI."""

import sys

from .engine import main

if __name__ == "__main__":
    sys.exit(main())
