"""Controller-plane rules: the async/threaded control plane's failure modes.

The control plane fails quietly: a broad ``except`` that logs nothing turns
a dead reconciler into a job stuck QUEUED forever; a thread target mutating
shared state without its lock turns a rare scheduler interleaving into a
corrupted queue; a blocking read inside an ``async def`` stalls every other
request on the event loop.  Each rule's escape hatch is the standard
``# ftc: ignore[rule-id] -- reason`` suppression.
"""

from __future__ import annotations

import ast

from ._astutil import ancestors, dotted_name, parent_map, terminal_name
from .engine import register

# ---------------------------------------------------------------------------
# silent-except
# ---------------------------------------------------------------------------

_BROAD = {"Exception", "BaseException"}
#: method names whose call counts as "the handler reported the failure"
_LOG_METHODS = {
    "exception", "error", "warning", "warn", "info", "debug", "critical", "log",
}
#: plain-call names that count as reporting (CLI modules print, benches fail)
_LOG_CALLS = {"print", "fail"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except:
    if isinstance(t, ast.Tuple):
        return any(terminal_name(e) in _BROAD for e in t.elts)
    return terminal_name(t) in _BROAD


def _handler_reports(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            # attr check separately from dotted_name: the receiver may be a
            # call chain (logging.getLogger(__name__).warning) it can't name
            if isinstance(node.func, ast.Attribute) and node.func.attr in _LOG_METHODS:
                return True
            if name in _LOG_CALLS:
                return True
            if name in ("traceback.print_exc", "traceback.print_exception",
                        "warnings.warn", "sys.exit"):
                return True
    return False


@register(
    "silent-except",
    "controller",
    "broad except whose body neither logs, re-raises, nor narrows the type",
)
def silent_except(module: ast.Module, src: str, path: str):
    for node in ast.walk(module):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(node):
            continue
        if _handler_reports(node):
            continue
        caught = "bare except" if node.type is None else (
            f"except {terminal_name(node.type) or '...'}"
        )
        yield (
            node.lineno, node.col_offset,
            f"{caught} swallows the failure silently — log it "
            "(logger.exception), re-raise, or narrow the exception type",
        )


# ---------------------------------------------------------------------------
# shared-mutable-without-lock
# ---------------------------------------------------------------------------

#: in-place mutators whose read-modify-write spans bytecodes
_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "add", "discard", "setdefault",
}


def _thread_target_names(module: ast.Module) -> set[str]:
    """Names passed as ``target=`` to ``threading.Thread`` (positional form
    ``Thread(group, target)`` is not used in this codebase)."""
    out: set[str] = set()
    for node in ast.walk(module):
        if not isinstance(node, ast.Call):
            continue
        if dotted_name(node.func) not in ("threading.Thread", "Thread"):
            continue
        for kw in node.keywords:
            if kw.arg == "target":
                name = terminal_name(kw.value)
                if name:
                    out.add(name)
    return out


def _under_lock(node: ast.AST, parents: dict[ast.AST, ast.AST]) -> bool:
    """Is this statement inside a ``with <something named *lock*>:`` block?"""
    for anc in ancestors(node, parents):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                expr = item.context_expr
                target = expr.func if isinstance(expr, ast.Call) else expr
                if "lock" in dotted_name(target).lower():
                    return True
    return False


@register(
    "shared-mutable-without-lock",
    "controller",
    "read-modify-write of shared state from a threading.Thread target without a lock",
)
def shared_mutable_without_lock(module: ast.Module, src: str, path: str):
    """Inside a function used as a ``threading.Thread`` target, flag
    augmented assignment to ``self.attr``/globals and in-place mutator calls
    (``.append``/``.update``/...) on ``self.attr`` that are not under a
    ``with <lock>`` block.  Plain rebinds (``self.x = v``) are a single
    atomic bytecode and stay unflagged."""
    targets = _thread_target_names(module)
    if not targets:
        return
    parents = parent_map(module)
    for fn in ast.walk(module):
        if not isinstance(fn, ast.FunctionDef) or fn.name not in targets:
            continue
        for node in ast.walk(fn):
            hit = None
            if isinstance(node, ast.AugAssign) and isinstance(
                node.target, (ast.Attribute, ast.Name, ast.Subscript)
            ):
                hit = (node, f"augmented assignment to "
                             f"`{ast.unparse(node.target)}`")
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS
                and isinstance(node.func.value, ast.Attribute)
                and dotted_name(node.func.value).startswith("self.")
            ):
                hit = (node, f"`{ast.unparse(node.func)}(...)`")
            if hit and not _under_lock(hit[0], parents):
                yield (
                    hit[0].lineno, hit[0].col_offset,
                    f"thread target `{fn.name}` mutates shared state "
                    f"({hit[1]}) without holding a lock",
                )


# ---------------------------------------------------------------------------
# blocking-io-in-async
# ---------------------------------------------------------------------------

_BLOCKING_EXACT = {
    "time.sleep": "time.sleep blocks the event loop — use asyncio.sleep",
    "open": "open() does blocking filesystem I/O on the event loop — "
            "await asyncio.to_thread(open, ...) or move the I/O to a thread",
    "socket.create_connection": "blocking socket connect on the event loop",
    "urllib.request.urlopen": "blocking HTTP on the event loop — use the "
                              "aiohttp session",
}
_BLOCKING_PREFIXES = ("requests.",)
_BLOCKING_SUBPROCESS = {"run", "check_output", "check_call", "call", "Popen"}
#: pathlib's whole-file helpers (blocking reads/writes by construction)
_PATHLIB_IO = {"read_text", "write_text", "read_bytes", "write_bytes"}


def blocking_call_message(node: ast.Call) -> str | None:
    """Why this call blocks the event loop, or None — the one matcher shared
    by the per-file rule and the transitive project rule (rules_flow)."""
    name = dotted_name(node.func)
    msg = _BLOCKING_EXACT.get(name)
    if msg is None and name.startswith(_BLOCKING_PREFIXES):
        msg = f"{name} is a blocking HTTP call on the event loop"
    if msg is None and name.startswith("subprocess.") and (
        name.split(".")[-1] in _BLOCKING_SUBPROCESS
    ):
        msg = f"{name} blocks the loop — use asyncio.create_subprocess_exec"
    if msg is None and (
        isinstance(node.func, ast.Attribute)
        and node.func.attr in _PATHLIB_IO
    ):
        msg = (
            f".{node.func.attr}() is a blocking whole-file "
            "read/write — await asyncio.to_thread(...) it"
        )
    return msg


@register(
    "blocking-io-in-async",
    "controller",
    "blocking call (time.sleep/requests/open/subprocess.run) inside async def",
)
def blocking_io_in_async(module: ast.Module, src: str, path: str):
    for fn in ast.walk(module):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        # a nested sync def is a deferral boundary: its body typically runs
        # via asyncio.to_thread / an executor, off the loop
        boundary = {
            n for n in ast.walk(fn)
            if isinstance(n, (ast.FunctionDef, ast.Lambda)) and n is not fn
        }
        skip: set[ast.AST] = set()
        for b in boundary:
            skip.update(ast.walk(b))
        for node in ast.walk(fn):
            if node in skip or not isinstance(node, ast.Call):
                continue
            msg = blocking_call_message(node)
            if msg:
                yield (
                    node.lineno, node.col_offset,
                    f"in async `{fn.name}`: {msg}",
                )


# ---------------------------------------------------------------------------
# unbounded-retry
# ---------------------------------------------------------------------------

_SLEEP_CALLS = {"time.sleep", "asyncio.sleep", "sleep", "anyio.sleep"}


def _is_const_true(test: ast.expr) -> bool:
    return isinstance(test, ast.Constant) and bool(test.value) is True


def _own_body_walk(root: ast.AST):
    """Walk a loop body without descending into nested function/class scopes
    (a ``return`` inside a nested def does not exit the loop)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _own_breaks(loop: ast.While):
    """``break`` statements belonging to THIS loop (not to a nested one)."""
    stack = [(child, loop) for child in ast.iter_child_nodes(loop)]
    while stack:
        node, owner = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        if isinstance(node, ast.Break):
            if owner is loop:
                yield node
            continue
        next_owner = node if isinstance(node, (ast.For, ast.AsyncFor,
                                               ast.While)) else owner
        stack.extend((c, next_owner) for c in ast.iter_child_nodes(node))


def _calls_sleep(root: ast.AST) -> ast.Call | None:
    for node in _own_body_walk(root):
        if isinstance(node, ast.Call) and dotted_name(node.func) in _SLEEP_CALLS:
            return node
    return None


@register(
    "unbounded-retry",
    "controller",
    "while-True retry loop that sleeps with no max-attempt or deadline bound",
)
def unbounded_retry(module: ast.Module, src: str, path: str):
    """Two shapes of the same bug — a failure loop that can spin forever:

    1. a ``while True:`` loop that sleeps and has NO exit at all (no
       ``break`` of its own, no ``return``, no ``raise`` in its body);
    2. a ``while True:`` loop whose ``except`` handler sleeps (the classic
       retry-after-failure) while that handler holds no ``raise``/
       ``return``/``break`` — success exits the loop, but the FAILURE path
       retries unboundedly, so a persistent error spins until an operator
       notices.

    The fix is a max-attempt counter or a deadline check that turns the last
    failure into a raise (see ``resilience/policy.py:RetryPolicy`` for the
    house pattern); intentional forever-loops (daemon reconcilers) carry a
    ``# ftc: ignore[unbounded-retry] -- reason``.
    """
    for loop in ast.walk(module):
        if not isinstance(loop, ast.While) or not _is_const_true(loop.test):
            continue
        sleep = _calls_sleep(loop)
        if sleep is None:
            continue
        has_exit = (
            next(_own_breaks(loop), None) is not None
            or any(
                isinstance(n, (ast.Return, ast.Raise))
                for n in _own_body_walk(loop)
            )
        )
        if not has_exit:
            yield (
                loop.lineno, loop.col_offset,
                "while-True loop sleeps but has no break/return/raise — it "
                "retries forever; bound it with a max-attempt counter or "
                "deadline",
            )
            continue
        for try_node in _own_body_walk(loop):
            if not isinstance(try_node, ast.Try):
                continue
            for handler in try_node.handlers:
                h_sleep = _calls_sleep(handler)
                if h_sleep is None:
                    continue
                handler_exits = (
                    any(
                        isinstance(n, (ast.Return, ast.Raise))
                        for n in _own_body_walk(handler)
                    )
                    or any(b for b in _own_breaks(loop)
                           if _within(handler, b))
                )
                # a bound may also live in the loop body OUTSIDE this try —
                # the deadline-check-then-raise shape (`if now > deadline:
                # raise` before the try) is correctly bounded; exits INSIDE
                # the try body (the success-path `return op()`) don't count,
                # they are unreachable on the failure path
                in_try = set(ast.walk(try_node))
                body_bound = any(
                    (isinstance(n, ast.Raise) and n not in in_try)
                    for n in _own_body_walk(loop)
                ) or any(b not in in_try for b in _own_breaks(loop))
                if not handler_exits and not body_bound:
                    yield (
                        h_sleep.lineno, h_sleep.col_offset,
                        "retry loop sleeps in an except handler with no "
                        "bound — a persistent failure retries forever; count "
                        "attempts or check a deadline and re-raise",
                    )


def _within(container: ast.AST, node: ast.AST) -> bool:
    return any(n is node for n in ast.walk(container))
