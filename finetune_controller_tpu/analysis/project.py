"""Project-wide analysis core: module index, symbol table, call graph.

PR 2's rules see one file at a time; the bugs that bite this codebase now
cross boundaries — an ``async def`` that blocks the event loop *through two
helpers*, a worker-thread mutation racing an event-loop read, an RPC client
whose op table drifted from the worker's.  This module parses the whole
package ONCE into:

* a **module index** (dotted name -> AST + source + per-line suppressions),
  with import resolution (absolute, ``import a.b as c``, and
  level-counted relative forms);
* a **symbol table** of every module-level function and class (methods,
  resolved base classes, and ``self.<attr>`` types inferred from annotated
  assignments, annotated ``__init__`` params, and direct construction);
* a conservative **call graph**: edges only where the callee provably
  resolves to a project symbol (local names, imports, ``self.method``,
  ``self.attr.method`` via the attr's inferred type, annotated params and
  locally-constructed variables).  Unresolvable calls produce NO edge —
  the graph under-approximates, which is the right bias for lint: every
  rendered call chain is real;
* **execution-context classification**: async functions (event-loop code),
  thread entries (``asyncio.to_thread(f)``, ``loop.run_in_executor(_, f)``,
  ``threading.Thread(target=f)``) and everything reachable from them, and
  jitted functions (the per-file ``jitted_functions`` detection, pooled).

Calls inside a nested ``def``/``lambda`` are deferral boundaries exactly as
in the per-file rules: they are not edges of the enclosing function.

The rule modules built on top: ``rules_flow`` (transitive async/jit),
``rules_concurrency`` (lock discipline), ``rules_protocol`` (RPC + metric
conformance).  ``source_overrides`` lets mutation tests lint the real
package with one file's source swapped in memory (delete a handler, watch
the lint turn red) without touching the tree.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Iterable, Iterator

from ._astutil import FuncDef, dotted_name, jitted_functions, terminal_name

__all__ = ["Project", "ModuleInfo", "ClassInfo", "FunctionInfo", "CallSite",
           "build_project"]

#: ``asyncio.to_thread(f, ...)`` / bare ``to_thread`` — first arg is deferred
_TO_THREAD = {"asyncio.to_thread", "to_thread"}
#: ``loop.run_in_executor(executor, f, ...)`` — second arg is deferred
_RUN_IN_EXECUTOR = "run_in_executor"
#: ``threading.Thread(target=f)`` — the ``target`` kwarg is a thread entry
_THREAD_CTORS = {"threading.Thread", "Thread"}


@dataclasses.dataclass(frozen=True)
class CallSite:
    """One resolved edge of the call graph."""

    callee: str          # qualname of the resolved target
    line: int
    col: int
    #: "sync"     — plain call, runs in the caller's execution context
    #: "deferred" — handed to a worker thread (to_thread/executor/Thread);
    #:              runs CONCURRENTLY with the caller's context
    context: str = "sync"


@dataclasses.dataclass(eq=False)
class FunctionInfo:
    qualname: str        # "pkg.mod.func" or "pkg.mod.Class.method"
    name: str
    module: "ModuleInfo"
    node: FuncDef
    cls: "ClassInfo | None" = None
    is_async: bool = False
    calls: list[CallSite] = dataclasses.field(default_factory=list)

    @property
    def path(self) -> str:
        return self.module.path

    @property
    def display(self) -> str:
        """Short human name for chain rendering: ``Class.method`` / ``func``."""
        if self.cls is not None:
            return f"{self.cls.name}.{self.name}"
        return self.name


@dataclasses.dataclass(eq=False)
class ClassInfo:
    qualname: str
    name: str
    module: "ModuleInfo"
    node: ast.ClassDef
    methods: dict[str, FunctionInfo] = dataclasses.field(default_factory=dict)
    base_names: list[str] = dataclasses.field(default_factory=list)
    #: ``self.<attr>`` -> class qualname, where inferable
    attr_types: dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(eq=False)
class ModuleInfo:
    name: str            # dotted module name
    path: str
    tree: ast.Module
    src: str
    #: local alias -> absolute dotted target ("np" -> "numpy",
    #: "register" -> "pkg.analysis.engine.register")
    imports: dict[str, str] = dataclasses.field(default_factory=dict)
    functions: dict[str, FunctionInfo] = dataclasses.field(default_factory=dict)
    classes: dict[str, ClassInfo] = dataclasses.field(default_factory=dict)


def _module_name_for(path: Path) -> str:
    """Dotted module name by walking up while ``__init__.py`` exists (a file
    outside any package keeps its stem)."""
    parts = [path.stem] if path.stem != "__init__" else []
    parent = path.parent
    while (parent / "__init__.py").exists():
        parts.insert(0, parent.name)
        parent = parent.parent
    return ".".join(parts) if parts else path.stem


def _resolve_relative(module_name: str, level: int, target: str | None) -> str:
    """``from ..a import b`` inside ``pkg.sub.mod`` -> ``pkg.a``.

    ``level`` counts the leading dots; the current module's last ``level``
    components are stripped (a module's own name counts as one)."""
    parts = module_name.split(".")
    base = parts[: len(parts) - level] if level <= len(parts) else []
    if target:
        base = base + target.split(".")
    return ".".join(base)


class Project:
    """The whole-package index the project rules consume."""

    def __init__(self) -> None:
        self.modules: dict[str, ModuleInfo] = {}
        self.modules_by_path: dict[str, ModuleInfo] = {}
        self.functions: dict[str, FunctionInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        #: qualnames handed to a worker thread somewhere in the project
        self.thread_roots: set[str] = set()
        #: qualname -> "decorated" | "referenced" (per-file jit detection)
        self.jitted: dict[str, str] = {}
        self.roots: list[Path] = []

    # ---- lookups -----------------------------------------------------------

    def function(self, qualname: str) -> FunctionInfo | None:
        return self.functions.get(qualname)

    def sync_callees(self, qualname: str) -> list[CallSite]:
        fn = self.functions.get(qualname)
        if fn is None:
            return []
        return [c for c in fn.calls if c.context == "sync"]

    def async_functions(self) -> Iterator[FunctionInfo]:
        for fn in self.functions.values():
            if fn.is_async:
                yield fn

    def thread_reachable(self) -> set[str]:
        """Thread roots plus everything reachable from them via sync edges."""
        seen = set()
        stack = [q for q in self.thread_roots if q in self.functions]
        while stack:
            q = stack.pop()
            if q in seen:
                continue
            seen.add(q)
            stack.extend(c.callee for c in self.sync_callees(q))
        return seen

    def resolve_class(self, module: ModuleInfo, dotted: str) -> ClassInfo | None:
        q = self._resolve_name(module, dotted)
        return self.classes.get(q) if q else None

    def method_of(self, cls: ClassInfo, name: str) -> FunctionInfo | None:
        """Method lookup walking project-resolvable base classes."""
        seen: set[str] = set()
        stack = [cls]
        while stack:
            c = stack.pop(0)
            if c.qualname in seen:
                continue
            seen.add(c.qualname)
            if name in c.methods:
                return c.methods[name]
            for base in c.base_names:
                b = self.resolve_class(c.module, base)
                if b is not None:
                    stack.append(b)
        return None

    def docs_file(self, filename: str) -> Path | None:
        """Locate ``docs/<filename>`` next to (or above) the linted roots —
        the metric-conformance rule reads the catalog from it."""
        for root in self.roots:
            base = root if root.is_dir() else root.parent
            for candidate in (base / "docs" / filename,
                              base.parent / "docs" / filename):
                if candidate.exists():
                    return candidate
        return None

    # ---- name resolution ---------------------------------------------------

    def _resolve_name(self, module: ModuleInfo, dotted: str) -> str | None:
        """Absolute qualname for a dotted local name, or None."""
        if not dotted:
            return None
        head, _, rest = dotted.partition(".")
        target = module.imports.get(head)
        if target is None:
            # a module-local symbol?
            local = f"{module.name}.{dotted}" if module.name else dotted
            if local in self.functions or local in self.classes:
                return local
            # "pkg.sub.mod.sym" spelled absolutely
            if dotted in self.functions or dotted in self.classes:
                return dotted
            return None
        full = f"{target}.{rest}" if rest else target
        if full in self.functions or full in self.classes:
            return full
        return None

    def resolve_callable(
        self,
        module: ModuleInfo,
        fn: FunctionInfo | None,
        expr: ast.AST,
        local_types: dict[str, str] | None = None,
    ) -> str | None:
        """Qualname of the function ``expr`` names in this scope, or None.

        Handles plain/dotted names, ``self.method``, ``self.attr.method``
        (via inferred attr types), ``var.method`` (via annotated params or
        local construction), and class references (-> ``__init__``)."""
        local_types = local_types or {}
        dotted = dotted_name(expr)
        if dotted:
            parts = dotted.split(".")
            if parts[0] == "self" and fn is not None and fn.cls is not None:
                if len(parts) == 2:  # self.method
                    m = self.method_of(fn.cls, parts[1])
                    return m.qualname if m else None
                if len(parts) == 3:  # self.attr.method
                    cls_q = fn.cls.attr_types.get(parts[1])
                    cls = self.classes.get(cls_q) if cls_q else None
                    if cls is not None:
                        m = self.method_of(cls, parts[2])
                        return m.qualname if m else None
                return None
            if len(parts) >= 2 and parts[0] in local_types:
                cls = self.classes.get(local_types[parts[0]])
                if cls is not None and len(parts) == 2:
                    m = self.method_of(cls, parts[1])
                    return m.qualname if m else None
                return None
            q = self._resolve_name(module, dotted)
            if q is None:
                return None
            if q in self.classes:  # constructing a class calls its __init__
                init = self.classes[q].methods.get("__init__")
                return init.qualname if init else q
            return q
        return None


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------


def _iter_py_files(paths: Iterable[str | Path]) -> Iterator[Path]:
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            yield from sorted(p.rglob("*.py"))
        elif p.suffix == ".py":
            yield p


def _collect_imports(module: ModuleInfo) -> None:
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                local = alias.asname or alias.name.split(".")[0]
                target = alias.name if alias.asname else alias.name.split(".")[0]
                module.imports[local] = target
        elif isinstance(node, ast.ImportFrom):
            base = (
                _resolve_relative(module.name, node.level, node.module)
                if node.level else (node.module or "")
            )
            for alias in node.names:
                if alias.name == "*":
                    continue
                local = alias.asname or alias.name
                module.imports[local] = f"{base}.{alias.name}" if base else alias.name


def _collect_symbols(project: Project, module: ModuleInfo) -> None:
    prefix = f"{module.name}." if module.name else ""
    for node in module.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            q = f"{prefix}{node.name}"
            fi = FunctionInfo(
                qualname=q, name=node.name, module=module, node=node,
                is_async=isinstance(node, ast.AsyncFunctionDef),
            )
            module.functions[node.name] = fi
            project.functions[q] = fi
        elif isinstance(node, ast.ClassDef):
            cq = f"{prefix}{node.name}"
            ci = ClassInfo(
                qualname=cq, name=node.name, module=module, node=node,
                base_names=[dotted_name(b) for b in node.bases if dotted_name(b)],
            )
            module.classes[node.name] = ci
            project.classes[cq] = ci
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    mq = f"{cq}.{item.name}"
                    fi = FunctionInfo(
                        qualname=mq, name=item.name, module=module,
                        node=item, cls=ci,
                        is_async=isinstance(item, ast.AsyncFunctionDef),
                    )
                    ci.methods[item.name] = fi
                    project.functions[mq] = fi


def _param_annotations(fn: FuncDef) -> dict[str, str]:
    out: dict[str, str] = {}
    a = fn.args
    for p in (*a.posonlyargs, *a.args, *a.kwonlyargs):
        if p.annotation is not None:
            ann = _annotation_name(p.annotation)
            if ann:
                out[p.arg] = ann
    return out


def _annotation_name(ann: ast.AST) -> str:
    """The class name an annotation spells: ``Batcher``, ``"Batcher"``
    (string form), ``Batcher | None`` / ``Optional[Batcher]``."""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.strip().split("|")[0].strip().strip("\"'")
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
        left = _annotation_name(ann.left)
        return left if left and left != "None" else _annotation_name(ann.right)
    if isinstance(ann, ast.Subscript):
        base = dotted_name(ann.value)
        if terminal_name(ann.value) == "Optional":
            return _annotation_name(ann.slice)
        return base
    name = dotted_name(ann)
    return "" if name == "None" else name


def _infer_attr_types(project: Project, ci: ClassInfo) -> None:
    """``self.<attr>`` -> project class, from (a) annotated assignment,
    (b) ``self.attr = <annotated __init__ param>``, (c) ``self.attr =
    ClassName(...)`` direct construction."""
    module = ci.module
    for method in ci.methods.values():
        params = _param_annotations(method.node)
        for node in ast.walk(method.node):
            attr = None
            value = None
            ann = None
            if isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Attribute):
                if dotted_name(node.target).startswith("self."):
                    attr = node.target.attr
                    ann = _annotation_name(node.annotation)
                    value = node.value
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 and (
                isinstance(node.targets[0], ast.Attribute)
                and dotted_name(node.targets[0]) == f"self.{node.targets[0].attr}"
            ):
                attr = node.targets[0].attr
                value = node.value
            if attr is None or attr in ci.attr_types:
                continue
            cls_q = None
            if ann:
                c = project.resolve_class(module, ann)
                cls_q = c.qualname if c else None
            if cls_q is None and isinstance(value, ast.Name):
                pann = params.get(value.id)
                if pann:
                    c = project.resolve_class(module, pann)
                    cls_q = c.qualname if c else None
            if cls_q is None and isinstance(value, ast.Call):
                c = project.resolve_class(module, dotted_name(value.func))
                cls_q = c.qualname if c else None
            if cls_q is not None:
                ci.attr_types[attr] = cls_q


def _local_var_types(project: Project, module: ModuleInfo, fn: FunctionInfo) -> dict[str, str]:
    """Function-local ``var -> class qualname``: annotated params plus
    single-name assignments from direct construction."""
    out: dict[str, str] = {}
    for name, ann in _param_annotations(fn.node).items():
        c = project.resolve_class(module, ann)
        if c is not None:
            out[name] = c.qualname
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and (
            isinstance(node.targets[0], ast.Name)
        ):
            c = project.resolve_class(module, dotted_name(node.value.func)) \
                if isinstance(node.value, ast.Call) else None
            if c is not None:
                out[node.targets[0].id] = c.qualname
    return out


def _own_nodes(fn: FuncDef) -> Iterator[ast.AST]:
    """Walk a function body WITHOUT descending into nested function/class
    scopes — a nested def is a deferral boundary, its calls are not the
    enclosing function's edges."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _deferred_target(call: ast.Call) -> ast.AST | None:
    """The callable expression this call hands to a worker thread, if any."""
    name = dotted_name(call.func)
    if name in _TO_THREAD and call.args:
        return call.args[0]
    if terminal_name(call.func) == _RUN_IN_EXECUTOR and len(call.args) >= 2:
        return call.args[1]
    if name in _THREAD_CTORS:
        for kw in call.keywords:
            if kw.arg == "target":
                return kw.value
    return None


def _build_edges(project: Project) -> None:
    for fn in list(project.functions.values()):
        module = fn.module
        local_types = _local_var_types(project, module, fn)
        for node in _own_nodes(fn.node):
            if not isinstance(node, ast.Call):
                continue
            deferred = _deferred_target(node)
            if deferred is not None:
                q = project.resolve_callable(module, fn, deferred, local_types)
                if q is not None:
                    fn.calls.append(CallSite(q, node.lineno, node.col_offset,
                                             context="deferred"))
                    project.thread_roots.add(q)
                continue
            q = project.resolve_callable(module, fn, node.func, local_types)
            if q is not None and q != fn.qualname:
                fn.calls.append(CallSite(q, node.lineno, node.col_offset))


def _classify_jitted(project: Project) -> None:
    for module in project.modules.values():
        node_to_fn = {
            fi.node: fi for fi in project.functions.values()
            if fi.module is module
        }
        for node, how in jitted_functions(module.tree).items():
            fi = node_to_fn.get(node)
            if fi is not None:
                project.jitted[fi.qualname] = how


def build_project(
    paths: Iterable[str | Path],
    *,
    source_overrides: dict[str, str] | None = None,
) -> Project:
    """Parse every ``.py`` under ``paths`` into a :class:`Project`.

    ``source_overrides`` maps absolute path strings to replacement source —
    the mutation-test hook: lint the real package with one file edited in
    memory.  Unparseable files are skipped (the per-file pass reports them).
    """
    overrides = {str(Path(k)): v for k, v in (source_overrides or {}).items()}
    project = Project()
    project.roots = [Path(p) for p in paths]
    for path in _iter_py_files(paths):
        key = str(path)
        try:
            src = overrides.get(key)
            if src is None:
                src = path.read_text(encoding="utf-8")
            tree = ast.parse(src, filename=key)
        except (OSError, SyntaxError):
            continue
        module = ModuleInfo(
            name=_module_name_for(path), path=key, tree=tree, src=src
        )
        project.modules[module.name] = module
        project.modules_by_path[key] = module
    for module in project.modules.values():
        _collect_imports(module)
        _collect_symbols(project, module)
    for ci in project.classes.values():
        _infer_attr_types(project, ci)
    _build_edges(project)
    _classify_jitted(project)
    return project
