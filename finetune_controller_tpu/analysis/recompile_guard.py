"""Runtime complement to the static recompile rules: count compilations.

The static rules (``recompile-jit-in-loop``, ``recompile-fresh-callable``)
catch the lexical traps, but the expensive production failure is dynamic: a
Python scalar or shape that varies per step flows into a jitted function's
signature and every step silently pays a full XLA compile.  On a CPU test
run that is a warm fuzzy 100 ms; on a v5e slice it is minutes per step of
burned TPU time that profiles as "mysteriously slow", not as an error.

:class:`RecompileGuard` wraps already-jitted callables and fingerprints each
call's *compilation signature* — pytree structure plus per-leaf
(shape, dtype, weak_type).  Python numeric scalars contribute only their
TYPE (jit traces them as weak-typed arrays, so a varying value does not
recompile); any other non-array leaf can only reach jit as a static
argument, where its value IS part of the cache key.  A new signature means
a new trace/compile.  Past ``budget`` distinct signatures the guard warns once
(``on_excess="warn"``) or raises :class:`RecompileBudgetExceeded`
(``on_excess="raise"``).  Where the wrapped fn exposes jit's own
``_cache_size()`` the guard cross-checks it, so signatures the fingerprint
cannot see (e.g. closure captures) still surface.

Threaded into the hot paths behind config flags:

* ``TrainConfig.recompile_budget`` (0 = off) wraps the trainer's step/eval
  jits; ``TrainConfig.recompile_action`` picks warn vs raise;
* ``BENCH_RECOMPILE_BUDGET`` does the same for ``bench.py`` with
  ``on_excess="raise"`` — a recompiling bench is a measurement bug and must
  fail loudly, not print a slow number.
"""

from __future__ import annotations

import logging
from typing import Any, Callable

import jax

logger = logging.getLogger(__name__)

__all__ = ["RecompileBudgetExceeded", "RecompileGuard"]


class RecompileBudgetExceeded(RuntimeError):
    """More distinct jit signatures than the configured budget."""


def _leaf_signature(leaf: Any) -> Any:
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return ("array", tuple(shape), str(dtype),
                bool(getattr(leaf, "weak_type", False)))
    if isinstance(leaf, (bool, int, float, complex)):
        # jit traces a Python scalar as a weak-typed 0-d array: the TYPE is
        # part of its cache key, the value is not — fingerprinting the value
        # would flag recompiles that never happen
        return ("pyscalar", type(leaf).__name__)
    # any other leaf can only reach a jitted fn as a STATIC argument, where
    # its value genuinely keys the cache
    try:
        hash(leaf)
        return ("static", leaf)
    except TypeError:
        return ("static", repr(leaf))


def signature_of(*args: Any, **kwargs: Any) -> tuple:
    """The (structure, leaf-signature) fingerprint jit keys its cache on."""
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    # the treedef object itself is hashable/eq-comparable; str()-ifying a
    # TrainState-sized treedef every step would be measurable host overhead
    # inside the very windows bench.py times
    return (treedef, tuple(_leaf_signature(x) for x in leaves))


class RecompileGuard:
    """Count distinct compilation signatures across a set of wrapped fns.

    One guard instance spans a whole training run: the budget covers the
    SUM of compilations over every label (init + per-batch-structure step +
    eval is the healthy ceiling a caller budgets for).
    """

    def __init__(
        self,
        budget: int,
        *,
        on_excess: str = "warn",   # "warn" | "raise"
        name: str = "recompile-guard",
    ):
        if budget < 1:
            raise ValueError(f"budget must be >= 1, got {budget}")
        if on_excess not in ("warn", "raise"):
            raise ValueError(f"on_excess must be 'warn' or 'raise', got {on_excess!r}")
        self.budget = budget
        self.on_excess = on_excess
        self.name = name
        self._seen: dict[str, set[tuple]] = {}
        self._warned = False

    @property
    def compilations(self) -> int:
        """Distinct signatures observed so far, across all labels."""
        return sum(len(s) for s in self._seen.values())

    def counts(self) -> dict[str, int]:
        return {label: len(sigs) for label, sigs in self._seen.items()}

    def check(self, label: str, sig: tuple, fn: Any = None) -> None:
        sigs = self._seen.setdefault(label, set())
        if sig in sigs:
            return
        sigs.add(sig)
        total = self.compilations
        # cross-check against jit's real cache where exposed: captures the
        # recompiles our arg fingerprint cannot see (closure-captured
        # scalars, donated-buffer changes)
        cache_size = getattr(fn, "_cache_size", None)
        if callable(cache_size):
            try:
                total = max(total, int(cache_size()))
            except Exception:  # pragma: no cover - jax internals drift
                logger.debug("jit _cache_size() probe failed", exc_info=True)
        if total <= self.budget:
            if total > 1:
                logger.info(
                    "%s: compilation %d/%d (label=%s)",
                    self.name, total, self.budget, label,
                )
            return
        detail = (
            f"{self.name}: {total} distinct jit compilations exceed the "
            f"budget of {self.budget} (per label: {self.counts()}). A "
            "signature changing per call usually means a shape or a static "
            "Python value varies per step — pad to a fixed shape or hoist "
            "the varying value into an array argument."
        )
        if self.on_excess == "raise":
            raise RecompileBudgetExceeded(detail)
        if not self._warned:  # one warning, not one per extra compile
            self._warned = True
            logger.warning("%s", detail)

    def wrap(self, fn: Callable, label: str) -> Callable:
        """Wrap a (jitted) callable; each call checks its signature first."""

        def guarded(*args: Any, **kwargs: Any):
            self.check(label, signature_of(*args, **kwargs), fn)
            return fn(*args, **kwargs)

        guarded.__name__ = f"guarded_{getattr(fn, '__name__', label)}"
        guarded.__wrapped__ = fn
        # AOT consumers (train/aot.py) lower the step jit without calling it
        if hasattr(fn, "lower"):
            guarded.lower = fn.lower
        return guarded
