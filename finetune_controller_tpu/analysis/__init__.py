"""`ftc-lint`: JAX-aware static analysis for the two planes of this repo.

The compute plane (jitted training/inference code) and the controller plane
(async control-plane services, thread-backed pipelines) fail in different,
equally silent ways: a host sync inside a jitted step loop shows up only as a
mysteriously slow TPU profile; a swallowed exception in the reconciler shows
up as a job stuck QUEUED forever.  This package makes both classes of hazard
a mechanical CI failure instead of an expensive rediscovery:

* :mod:`engine` — the AST walker, the per-file AND project-wide rule
  registries, ``# ftc: ignore[rule-id]`` suppressions, text/JSON/SARIF
  reporting, and the ``ftc-lint`` console entry;
* :mod:`project` — the v2 core: whole-package module/symbol index, a
  conservative call graph, and async/thread-entry/jit classification;
* :mod:`rules_compute` — host-sync-in-jit, prng-key-reuse, recompile
  hazards, missing-donation;
* :mod:`rules_controller` — silent-except, shared-mutable-without-lock,
  blocking-io-in-async;
* :mod:`rules_flow` — the transitive (interprocedural) versions of
  blocking-io-in-async and host-sync-in-jit, with rendered call chains;
* :mod:`rules_concurrency` — lock-discipline: guarded-field inference for
  lock-holding classes, loop-vs-worker-thread race detection without one;
* :mod:`rules_protocol` — rpc-conformance (transport worker + state
  service op/payload tables vs their clients) and metric-doc-drift
  (emitted ``ftc_*`` families vs docs/observability.md's catalog);
* :mod:`recompile_guard` — the runtime complement: counts distinct jit
  signatures behind ``TrainConfig.recompile_budget`` / bench env knobs and
  warns or raises when a shape-unstable step blows the budget;
* :mod:`transfer_guard` — runtime complement #2: wraps the trainer step
  and serve decode hot windows in ``jax.transfer_guard`` (plus a
  backend-independent ``jax.device_get`` trap) behind
  ``TrainConfig.transfer_guard`` / ``FTC_TRANSFER_GUARD``, armed by
  ``bench.py`` so a reintroduced sync aborts the timed window.

``tests/test_lint_clean.py`` gates the repo: zero unsuppressed findings over
``finetune_controller_tpu/``.  See ``docs/static_analysis.md``.
"""

from .engine import Finding, LintResult, lint_paths, lint_source, main  # noqa: F401

__all__ = [
    "Finding",
    "LintResult",
    "lint_paths",
    "lint_source",
    "main",
    "RecompileGuard",
    "RecompileBudgetExceeded",
    "TransferGuard",
    "TransferGuardError",
]


def __getattr__(name: str):
    # the guards pull in jax; loaded lazily so the pure-AST `ftc-lint` CLI
    # (and scripts/ci_check.sh, which runs it first) stays jax-import-free
    if name in ("RecompileGuard", "RecompileBudgetExceeded"):
        from . import recompile_guard

        return getattr(recompile_guard, name)
    if name in ("TransferGuard", "TransferGuardError"):
        from . import transfer_guard

        return getattr(transfer_guard, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
