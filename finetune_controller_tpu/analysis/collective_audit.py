"""AOT collective audit: the compiled step moves what the docs say it moves.

The multi-chip hot path is a handful of collectives — FSDP weight
all-gathers, gradient reduce-scatters/all-reduces, TP activation
reductions — and the expensive regression is a NEW one nobody meant to
add: a partition-rule edit or an optimizer change that makes XLA
all-gather full parameters inside the step body turns into a silent
bandwidth tax that profiles as "slow", never as an error.  EQuARX-style
collective quantization (ROADMAP item 3) is about to make this set
load-bearing, so it gets the metric-catalog treatment (PR 13): the
compiled HLO's collective set is diffed BOTH WAYS against a
machine-checked **Collective catalog** in ``docs/performance.md`` — an
undocumented collective or a documented-but-vanished one turns the
``collective-conformance`` lint rule red.

Mechanics mirror ``train/aot.py``: each topology audits in a fresh
subprocess whose CPU backend fakes the device count
(``--xla_force_host_platform_device_count``), AOT-lowering the jitted
train step and the serve engine's decode step over the tiny preset with
the real rule-table shardings — zero parameter-sized buffers are
allocated for the train leg, and the whole thing runs on a laptop-class
CPU box.  ``diff_catalog`` is a PURE function of (observed sets, catalog
text), so the catalog-mutation tests re-diff without re-compiling.
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Any

__all__ = [
    "TOPOLOGIES",
    "audit_topology",
    "run_audit_subprocess",
    "full_audit",
    "parse_catalog",
    "diff_catalog",
]

#: the simulated topologies the conformance gate audits: the three mesh
#: shapes whose collective signatures differ in kind (pure data-parallel,
#: FSDP weight gathering, and a dp×tp hybrid adding TP activation
#: reductions).  Tiny preset, so the subprocess compiles in seconds.
TOPOLOGIES: dict[str, dict[str, Any]] = {
    "dp2": dict(mesh=dict(dp=2), n_devices=2),
    "fsdp2": dict(mesh=dict(fsdp=2), n_devices=2),
    "dp2tp2": dict(mesh=dict(dp=2, tp=2), n_devices=4),
}

#: the audited steps per topology
STEPS = ("train", "serve")

_CATALOG_HEADING = re.compile(r"^(#+)\s.*collective catalog", re.IGNORECASE)


def audit_topology(name: str) -> dict[str, Any]:
    """Lower + compile the train step and serve decode step on the named
    simulated topology; return ``{"name", "train": [...], "serve": [...]}``
    with each step's sorted compiled-collective set.  Must run in a process
    whose backend has at least ``n_devices`` (virtual CPU) devices."""
    import jax
    import jax.numpy as jnp

    from ..models.llama import PRESETS, LlamaForCausalLM
    from ..models.lora import LoRAConfig
    from ..parallel.mesh import MeshSpec
    from ..parallel.sharding import LLAMA_RULES, sharding_for_tree
    from ..train.aot import _COLLECTIVE_RE
    from ..train.trainer import TrainConfig, Trainer

    spec = TOPOLOGIES[name]
    devices = jax.devices()[: spec["n_devices"]]
    if len(devices) < spec["n_devices"]:
        raise RuntimeError(
            f"{name} needs {spec['n_devices']} devices, backend has "
            f"{len(devices)} — set xla_force_host_platform_device_count "
            "before JAX init"
        )
    mesh = MeshSpec(**spec["mesh"]).build(devices)

    # ---- train leg: the aot.py abstract recipe on the tiny preset ----------
    model_cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=4))
    train_cfg = TrainConfig(
        mode="lora", batch_size=4, seq_len=32, total_steps=10
    )
    trainer = Trainer(model_cfg, train_cfg, mesh=mesh)
    state_shapes = jax.eval_shape(trainer._raw_init, jax.random.PRNGKey(0))
    abstract_state = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        state_shapes, trainer._state_shardings,
    )
    b, s = train_cfg.batch_size, train_cfg.seq_len
    abstract_batch = {
        "tokens": jax.ShapeDtypeStruct((b, s), jnp.int32),
        "loss_mask": jax.ShapeDtypeStruct((b, s), jnp.float32),
    }
    step = trainer._get_step_jit(abstract_batch)
    train_hlo = step.lower(abstract_state, abstract_batch).compile().as_text()

    # ---- serve leg: the engine's REAL decode jit, weights rule-sharded ----
    from ..serve.engine import BatchEngine, EngineConfig

    serve_model = LlamaForCausalLM(PRESETS["tiny-test"])
    variables = serve_model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 4), jnp.int32)
    )
    variables = jax.tree.map(
        jax.device_put, variables,
        sharding_for_tree(variables, mesh, LLAMA_RULES),
    )
    engine = BatchEngine(
        serve_model, variables,
        EngineConfig(slots=2, prompt_buckets=(16,), max_new_tokens=16),
    )
    slots = engine.config.slots
    decode_args = (
        engine.variables, engine._tenants_arg(), engine._cache,
        jnp.zeros((slots, 1), jnp.int32), jnp.zeros((slots, 1), jnp.int32),
        jnp.zeros((slots,), jnp.float32), jnp.zeros((slots,), jnp.int32),
        jnp.asarray(engine._rng_keys),
        engine._page_table_arg(), engine._adapter_ids_arg(),
    )
    serve_hlo = engine._decode.lower(*decode_args).compile().as_text()

    return {
        "name": name,
        "train": sorted(set(_COLLECTIVE_RE.findall(train_hlo))),
        "serve": sorted(set(_COLLECTIVE_RE.findall(serve_hlo))),
    }


def run_audit_subprocess(name: str, timeout: float = 300.0) -> dict[str, Any]:
    """Audit one topology in a fresh subprocess owning its virtual device
    count (the XLA flag must precede backend init — the same constraint as
    ``train/aot.py::run_report_subprocess``)."""
    import os
    import subprocess
    import sys

    spec = TOPOLOGIES[name]
    env = dict(os.environ)
    kept = " ".join(
        p for p in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in p
    )
    env["XLA_FLAGS"] = (
        f"{kept} --xla_force_host_platform_device_count={spec['n_devices']}"
    ).strip()
    out = subprocess.run(
        [sys.executable, "-m",
         "finetune_controller_tpu.analysis.collective_audit", name],
        env=env, capture_output=True, text=True, timeout=timeout,
    )
    if out.returncode != 0:
        raise RuntimeError(
            f"collective audit {name} failed:\n" + out.stderr[-2000:]
        )
    return json.loads(out.stdout.strip().splitlines()[-1])


def full_audit() -> dict[str, dict[str, list[str]]]:
    """Audit every topology (one subprocess each); returns
    ``{topology: {"train": [...], "serve": [...]}}``."""
    out: dict[str, dict[str, list[str]]] = {}
    for name in TOPOLOGIES:
        report = run_audit_subprocess(name)
        out[name] = {step: report[step] for step in STEPS}
    return out


# ---- the documented catalog ------------------------------------------------


def parse_catalog(text: str) -> tuple[dict[tuple[str, str], set[str]], int]:
    """Parse the ``## Collective catalog`` section of docs/performance.md:
    table rows ``| topology | step | op, op |`` scoped to the heading (the
    metric-catalog convention — the section ends at the next heading of the
    same or higher level).  Returns ``((topology, step) -> ops, heading
    line number)``; an absent heading returns ``({}, 0)`` (catalog opt-out,
    mirroring the metric rule)."""
    rows: dict[tuple[str, str], set[str]] = {}
    lines = text.splitlines()
    start = level = None
    for i, line in enumerate(lines):
        m = _CATALOG_HEADING.match(line)
        if m:
            start, level = i, len(m.group(1))
            break
    if start is None:
        return {}, 0
    for line in lines[start + 1:]:
        hm = re.match(r"^(#+)\s", line)
        if hm and len(hm.group(1)) <= level:
            break
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if len(cells) < 3 or cells[0] in ("topology", "") \
                or set(cells[0]) <= {"-", " ", ":"}:
            continue
        topo, step, ops = cells[0], cells[1], cells[2]
        rows[(topo, step)] = {
            op.strip().strip("`") for op in ops.split(",")
            if op.strip().strip("`") not in ("", "none")
        }
    return rows, start + 1


def diff_catalog(
    observed: dict[str, dict[str, list[str]]],
    catalog: dict[tuple[str, str], set[str]],
) -> list[str]:
    """Both-direction diff of the audited collective sets against the
    documented catalog; returns human-readable drift messages (empty =
    conformant).  Pure — the mutation tests re-diff edited catalog text
    against one recorded audit without re-compiling anything."""
    out: list[str] = []
    for topo, steps in sorted(observed.items()):
        for step in STEPS:
            seen = set(steps[step])
            documented = catalog.get((topo, step))
            if documented is None:
                out.append(
                    f"collective set for {topo}/{step} "
                    f"({', '.join(sorted(seen)) or 'none'}) has no Collective "
                    "catalog row in docs/performance.md"
                )
                continue
            for op in sorted(seen - documented):
                out.append(
                    f"compiled {topo}/{step} step contains {op!r} but the "
                    "Collective catalog does not document it — an unexpected "
                    "collective in the step body is a silent bandwidth tax; "
                    "document it or fix the sharding that introduced it"
                )
            for op in sorted(documented - seen):
                out.append(
                    f"Collective catalog documents {op!r} for {topo}/{step} "
                    "but the compiled step no longer contains it — drop the "
                    "row or restore the collective"
                )
    for topo, step in sorted(catalog):
        if topo not in observed:
            out.append(
                f"Collective catalog documents topology {topo!r} but the "
                "audit does not simulate it (analysis/collective_audit.py "
                "TOPOLOGIES)"
            )
    return out


def catalog_path() -> Path:
    """docs/performance.md relative to the repo root (best-effort)."""
    return Path(__file__).resolve().parents[2] / "docs" / "performance.md"


def main() -> None:
    import os
    import sys

    import jax

    # same contract as train/aot.py: virtual CPU devices, platform forced
    # before backend init
    jax.config.update("jax_platforms", os.environ.get("AOT_PLATFORM", "cpu"))
    print(json.dumps(audit_topology(sys.argv[1])))


if __name__ == "__main__":
    main()
