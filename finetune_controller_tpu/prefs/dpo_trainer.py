"""DPO on top of the LoRA SFT trainer — same machinery, different objective.

:class:`DPOTrainer` swaps the loss (``prefs/losses.py``) and nothing else:
sharded init, the jitted step with donation/grad-accum, checkpoint manifests,
elastic resume, preemption handling, heartbeats, and the metrics CSV all ride
``train/trainer.py`` unchanged.  The metrics rows gain ``reward_margin`` and
``dpo_accuracy`` (plus their ``eval_`` twins on the eval cadence).

The reference model is FREE here (docs/preference.md): in LoRA mode the
policy is base + adapter, so the reference forward is the frozen base with
the adapter branch disabled — a rank-0 twin of the model applied over the
``params`` collection only.  No second weight copy exists on device, and no
gradient path into the trainable tree exists on the reference side (tested).

Batch contract (``data/preference.py``)::

    {"chosen_tokens", "chosen_mask", "rejected_tokens", "rejected_mask"}

Chosen and rejected sequences are stacked into ONE ``(2B, S)`` forward per
model (policy and reference), so a DPO step costs two forwards of twice the
batch — not four forwards.
"""

from __future__ import annotations

import logging

import jax.numpy as jnp

from ..models.lora import LoRAConfig
from ..train.trainer import Trainer
from .losses import dpo_loss, masked_sequence_logprobs

logger = logging.getLogger(__name__)


class DPOTrainer(Trainer):
    """Preference-pair trainer (``TrainConfig.task == "dpo"``/``"rlhf"``).

    Restrictions (all checked at construction): LoRA mode only (the
    adapter-disabled reference trick is what makes the reference model free;
    full fine-tune would need a second frozen weight copy), dense text models
    (MoE capacity routing couples the stacked chosen/rejected rows; the
    multimodal prefix has no pair semantics), no pipeline parallelism.
    """

    def __init__(self, model_cfg, train_cfg, mesh=None, **kw):
        if train_cfg.mode != "lora":
            raise ValueError(
                "DPO requires mode='lora': the reference model is the "
                "adapter-disabled base, which only exists in LoRA mode"
            )
        if getattr(model_cfg, "n_experts", 0):
            raise ValueError("DPO does not support MoE configs")
        if getattr(model_cfg, "vision", None) is not None:
            raise ValueError("DPO supports text models only")
        if train_cfg.dpo_beta <= 0:
            raise ValueError(f"dpo_beta must be > 0, got {train_cfg.dpo_beta}")
        super().__init__(model_cfg, train_cfg, mesh=mesh, **kw)
        if self._pp > 1:
            raise ValueError("DPO does not support pipeline parallelism")
        #: the reference forward: the SAME architecture at LoRA rank 0 —
        #: its ``params`` tree is structurally identical to the policy's
        #: frozen base, so it applies over ``frozen["params"]`` directly
        self._ref_model = type(self.model)(
            cfg=model_cfg.replace(
                lora=LoRAConfig(
                    rank=0,
                    alpha=model_cfg.lora.alpha,
                    targets=model_cfg.lora.targets,
                )
            )
        )
        #: host-side metrics provider for the rlhf learner (rollout buffer
        #: depth/staleness, actor tok/s) — merged into every logged row
        self.rollout_stats_fn = None
        if train_cfg.task == "rlhf" \
                and not getattr(train_cfg, "rollout_workers", 0):
            # IN-PROCESS loop only.  The actor only sees COMMITTED
            # checkpoints; synchronous commits bound its policy lag
            # deterministically (one round), where an async save could land
            # arbitrarily many rollout rounds late
            self._blocking_checkpoints = True
            if train_cfg.prefetch:
                # the rollout stream RUNS the actor inside next(): a
                # background prefetch thread would interleave the serve
                # engine's decode steps with the learner's jitted steps and
                # read checkpoints concurrently with the blocking save —
                # enforce here so every caller (cli, bench, harnesses) is
                # covered
                logger.info("rlhf task: forcing prefetch=0 (actor runs inline)")
                train_cfg.prefetch = 0
        # remote rollout workers (rollout_workers > 0) keep BOTH: actors
        # decode in their own processes, so prefetch threads never touch the
        # learner's engine, and async checkpoint commits are safe — the
        # plane pushes a policy only after latest_step() reports it durable.
        # That async overlap is the whole point of disaggregation
        # (docs/preference.md §Disaggregated rollouts).

    # ---- objective -------------------------------------------------------

    def _pair_logprobs(self, model, variables, batch, rngs=None):
        """(chosen_lp, rejected_lp), each (B,): one stacked (2B, S) forward."""
        b = batch["chosen_tokens"].shape[0]
        tokens = jnp.concatenate(
            [batch["chosen_tokens"], batch["rejected_tokens"]], axis=0
        )
        masks = jnp.concatenate(
            [batch["chosen_mask"], batch["rejected_mask"]], axis=0
        )
        logits = model.apply(
            variables, tokens,
            deterministic=rngs is None, rngs=rngs,
        )
        lp = masked_sequence_logprobs(logits, tokens, masks)
        return lp[:b], lp[b:]

    def _dpo_metrics(self, trainable, frozen, batch, dropout_rng=None):
        variables = self._assemble(frozen, trainable)
        rngs = (
            {"dropout": dropout_rng}
            if (self._use_dropout and dropout_rng is not None) else None
        )
        pc, pr = self._pair_logprobs(self.model, variables, batch, rngs=rngs)
        # adapter-disabled reference: frozen base only, always deterministic
        rc, rr = self._pair_logprobs(
            self._ref_model, {"params": frozen["params"]}, batch
        )
        loss, metrics = dpo_loss(pc, pr, rc, rr, self.cfg.dpo_beta)
        # fit()'s log line and the eval_* naming expect loss/accuracy keys;
        # accuracy IS the pair-ranking accuracy for a preference objective
        metrics["accuracy"] = metrics["dpo_accuracy"]
        metrics["policy_chosen_logprob"] = pc.mean()
        metrics["policy_rejected_logprob"] = pr.mean()
        return loss, metrics

    def _loss_fn(self, trainable, frozen, batch, dropout_rng):
        return self._dpo_metrics(trainable, frozen, batch, dropout_rng)

    def _eval_step(self, state, batch: dict):
        """Forward-only DPO metrics on held-out pairs (dropout off)."""
        _, metrics = self._dpo_metrics(state.trainable, state.frozen, batch)
        return metrics

    # ---- metrics plumbing ------------------------------------------------

    def _writer_extra_fields(self, eval_enabled: bool) -> tuple[str, ...]:
        fields = super()._writer_extra_fields(eval_enabled)
        if eval_enabled:
            fields += ("eval_reward_margin", "eval_dpo_accuracy")
        if self.rollout_stats_fn is not None:
            fields += (
                "rollout_buffer_depth", "rollout_staleness",
                "actor_tokens_per_sec", "actor_version",
            )
            if getattr(self.cfg, "rollout_workers", 0):
                fields += (
                    "rollout_workers_alive", "rollout_respawns_total",
                    "rollout_dup_pairs_total",
                )
        return fields

    def _row_extras(self) -> dict:
        if self.rollout_stats_fn is None:
            return {}
        return {k: float(v) for k, v in self.rollout_stats_fn().items()}
