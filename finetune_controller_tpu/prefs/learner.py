"""RLHF-lite learner: the actor/learner gang wired through the SFT machinery.

The learner IS :class:`~.dpo_trainer.DPOTrainer` — same jitted step,
checkpoints, elastic resume, preemption handling.  What makes it an
actor/learner loop is the BATCH STREAM: :func:`rollout_batch_stream` is an
iterator whose ``next()`` runs the actor's control loop before yielding a
batch —

1. reload the policy if the learner committed a new checkpoint
   (:meth:`~.actor.RolloutActor.maybe_reload` — so the actor picks up step
   N+1 on the first batch after the commit, i.e. within one round);
2. enforce the staleness watermark on the rollout buffer (the learner never
   trains on pairs more than ``staleness_checkpoints`` checkpoints old);
3. top the buffer up with fresh on-policy pairs until it holds at least
   ``min_fill``;
4. yield a seed-deterministic DPO batch sampled from the buffer.

Because ``Trainer.fit`` pulls batches synchronously (the rlhf path forces
``prefetch=0`` — the actor's engine must not decode on a background thread
interleaved with the learner's jitted steps), the actor and learner execute
as a round-robin gang on the job's chips: generate, then train, then
generate — the Podracer architecture collapsed onto one substrate, with the
``sched/`` gang admission holding the chips for both halves atomically
(``atomic_gang`` in the job spec).
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Iterator

from .actor import RolloutActor, increment_prompts, increment_reward
from .dpo_trainer import DPOTrainer
from .rollout_buffer import RolloutBuffer

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class RolloutConfig:
    """Knobs of the actor/learner loop (job-spec arguments; the ``FTC_RLHF_*``
    env vars in ``examples/ftc.env.example`` are per-pod operator overrides,
    the ``FTC_FLASH_*`` convention)."""

    pairs_per_round: int = 16
    buffer_capacity: int = 256
    #: min pairs in the buffer before the learner takes a batch
    min_fill: int = 16
    #: drop pairs older than this many CHECKPOINTS behind the newest commit
    staleness_checkpoints: int = 2
    temperature: float = 0.8
    top_k: int = 0
    max_new_tokens: int = 16
    #: decode lanes of the actor's serve engine
    slots: int = 4
    #: consecutive pair-less rollout rounds tolerated before the learner
    #: proceeds on a partially-filled buffer (or fails loudly on an empty
    #: one) — the liveness backstop for converged/wedged policies
    max_empty_rounds: int = 25
    #: learned reward model endpoint (remote rollout workers only): when
    #: ``reward_port`` is set, actors score candidates through the served
    #: reward model's batched ``reward_score`` RPC instead of the
    #: programmatic increment reward (docs/preference.md §Disaggregated
    #: rollouts)
    reward_host: str = ""
    reward_port: int = 0

    _ENV_FIELDS = {
        "pairs_per_round": "FTC_RLHF_PAIRS_PER_ROUND",
        "buffer_capacity": "FTC_RLHF_BUFFER_CAPACITY",
        "min_fill": "FTC_RLHF_MIN_FILL",
        "staleness_checkpoints": "FTC_RLHF_STALENESS_CHECKPOINTS",
        "temperature": "FTC_RLHF_TEMPERATURE",
        "top_k": "FTC_RLHF_TOP_K",
        "max_new_tokens": "FTC_RLHF_MAX_NEW_TOKENS",
        "slots": "FTC_RLHF_SLOTS",
        "reward_host": "FTC_RLHF_REWARD_HOST",
        "reward_port": "FTC_RLHF_REWARD_PORT",
    }

    def apply_env_overrides(self) -> "RolloutConfig":
        """Operator env overrides (read in the job pod, not the controller)."""
        out = self
        for field, env in self._ENV_FIELDS.items():
            raw = os.environ.get(env)
            if raw is None:
                continue
            kind = type(getattr(self, field))
            out = dataclasses.replace(out, **{field: kind(raw)})
        return out


def rollout_batch_stream(
    actor: RolloutActor,
    buffer: RolloutBuffer,
    *,
    batch_size: int,
    seq_len: int,
    checkpoint_every: int,
    rollout: RolloutConfig,
) -> Iterator[dict]:
    """The learner's infinite batch source — see the module docstring."""
    while True:
        reloaded = actor.maybe_reload()
        min_version = actor.version - (
            rollout.staleness_checkpoints * checkpoint_every
        )
        buffer.evict_below(min_version, watermark=actor.version)
        if reloaded:
            # fresh policy ⇒ fresh on-policy data: one generation round per
            # reload keeps the buffer tracking the newest checkpoint even
            # when nothing was evicted yet
            for pair in actor.generate_pairs(rollout.pairs_per_round):
                buffer.push(pair)
        empty_rounds = 0
        while buffer.depth < rollout.min_fill:
            fresh = actor.generate_pairs(rollout.pairs_per_round)
            for pair in fresh:
                buffer.push(pair)
            if fresh:
                empty_rounds = 0
                continue
            # an all-ties round: common early (a fresh policy decodes
            # near-uniform noise — the oracle bootstrap usually breaks it)
            # and again at CONVERGENCE (every candidate scores 1.0, so
            # neither ranking nor bootstrap yields signal).  Bounded: past
            # the cap, train on whatever the buffer holds rather than
            # busy-looping the decoder forever; a buffer with NOTHING to
            # train on is a wedged reward function — fail loudly.
            empty_rounds += 1
            logger.info(
                "rollout round %d produced no ranked pairs (%d empty in a "
                "row)", actor.rounds, empty_rounds,
            )
            if empty_rounds >= rollout.max_empty_rounds:
                if buffer.depth > 0:
                    logger.info(
                        "proceeding below min_fill (%d/%d pairs) after %d "
                        "pair-less rounds — policy likely converged",
                        buffer.depth, rollout.min_fill, empty_rounds,
                    )
                    break
                raise RuntimeError(
                    f"{empty_rounds} consecutive rollout rounds produced no "
                    "preference pairs and the buffer is empty — the reward "
                    "function cannot rank this policy's samples"
                )
        yield buffer.sample_batch(batch_size, seq_len)


def build_rlhf_loop(
    trainer: DPOTrainer,
    artifacts_dir: str,
    *,
    rollout: RolloutConfig | None = None,
    pretrained_dir: str | None = None,
    prompt_fraction: float = 0.5,
) -> tuple[Iterator[dict], RolloutActor, RolloutBuffer]:
    """Wire an actor + buffer + batch stream onto a DPO learner.

    The actor shares the FROZEN base with the learner (same init seed — or
    the same pretrained weights — so the step-0 policy is identical), but
    its trainable adapter always comes from committed checkpoints: weights
    cross the actor/learner boundary only through the checkpoint channel.

    Known cost at scale: ``Trainer.fit`` re-inits (and re-loads pretrained
    weights) on entry, so the init here is paid twice and the actor pins
    its own base copy on device — fine for the current gang-on-one-substrate
    shape, and it disappears when the actor becomes a separate process
    (ROADMAP item 5 follow-on (a)).
    """
    import jax

    rollout = (rollout or RolloutConfig()).apply_env_overrides()
    cfg = trainer.cfg
    model_cfg = trainer.model_cfg
    state = trainer.init_state()
    if pretrained_dir:
        state = trainer.load_pretrained(state, pretrained_dir)
    vocab = model_cfg.vocab_size
    prompt_len = max(2, int(cfg.seq_len * prompt_fraction))
    # per-process seed offset: on a multi-host gang every host builds its
    # own loop, and identical seeds would make all hosts generate (and
    # sample) the SAME rollouts — a global batch of duplicated rows.  The
    # same shard-offset discipline every other data path uses.
    shard = jax.process_index()
    actor = RolloutActor(
        trainer.model,
        dict(state.frozen)["params"],
        f"{artifacts_dir}/checkpoints",
        reward_fn=lambda p, c: increment_reward(p, c, vocab),
        prompts=increment_prompts(
            cfg.seq_len, vocab, cfg.seed + 7919 + shard, prompt_fraction
        ),
        # the reward-optimal continuation — the cold-start bootstrap side
        oracle_fn=lambda p, n: [(p[-1] + 1 + i) % vocab for i in range(n)],
        # shape-validated restores (collective on multi-host — all hosts
        # build the loop, so all participate in the gather)
        state_template=trainer.state_to_host(state),
        prompt_bucket=prompt_len,
        max_new_tokens=min(rollout.max_new_tokens, cfg.seq_len - prompt_len),
        temperature=rollout.temperature,
        top_k=rollout.top_k,
        slots=rollout.slots,
        seed=cfg.seed + shard,
    )
    buffer = RolloutBuffer(
        rollout.buffer_capacity, seed=cfg.seed + shard,
        # versions are checkpoint STEPS; report staleness in checkpoints —
        # the unit the staleness_checkpoints knob (and the operator) uses
        version_granularity=max(1, cfg.checkpoint_every),
    )
    stream = rollout_batch_stream(
        actor, buffer,
        batch_size=trainer.local_batch_size,
        seq_len=cfg.seq_len,
        checkpoint_every=cfg.checkpoint_every,
        rollout=rollout,
    )

    def stats() -> dict:
        return {
            **buffer.stats(),
            "actor_tokens_per_sec": round(actor.tokens_per_sec, 1),
            "actor_version": actor.version,
        }

    trainer.rollout_stats_fn = stats
    return stream, actor, buffer
