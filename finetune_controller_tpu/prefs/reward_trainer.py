"""Learned reward model: a scalar head trained on the DPO data path.

``TrainConfig.task == "reward"`` rides the whole SFT/DPO machinery — sharded
init, jitted step with donation/grad-accum, checkpoints, elastic resume,
eval cadence, metrics CSV — and swaps only the trainable tree and the loss:

* **trainable** = ``{"lora": <adapter>, "head": {"a", "w", "b"}}`` — the
  policy trunk's LoRA adapter plus a tiny scalar head over the logits
  (``prefs/losses.py::reward_scores``).  At init ``a=1, w=0, b=0``, so the
  step-0 score is exactly the mean completion likelihood — the DPO
  implicit-reward feature — and Bradley–Terry training starts from a proven
  ranking signal instead of noise;
* **loss** = pairwise Bradley–Terry over the stacked (2B, S) forward
  (``prefs/losses.py::bradley_terry_loss``), the same one-forward pair
  stacking the DPO trainer uses.

The batch contract is the DPO one (``data/preference.py``): chosen/rejected
token+mask quadruples, so the same synthetic/JSONL pipelines feed both
objectives unchanged.

Serving: :meth:`export_artifacts` ships the trunk adapter exactly like a
DPO job (PEFT layout — the registry can multiplex it) plus
``reward_head.msgpack``; the serve worker rebuilds the trunk through the
normal ``deploy_dir`` builder and answers the batched ``reward_score`` RPC
with :class:`~.rollout_plane.RewardScorer` (docs/preference.md
§Disaggregated rollouts).
"""

from __future__ import annotations

import logging
import os

import jax.numpy as jnp

from ..train.trainer import Trainer
from .losses import bradley_terry_loss, reward_scores

logger = logging.getLogger(__name__)

REWARD_HEAD_FILENAME = "reward_head.msgpack"


class RewardModelTrainer(Trainer):
    """Bradley–Terry reward model (``TrainConfig.task == "reward"``).

    Same construction-time restrictions as DPO, for the same reasons: LoRA
    mode only (the head + adapter is what keeps the export an adapter-sized
    artifact), dense text models, no pipeline parallelism.
    """

    def __init__(self, model_cfg, train_cfg, mesh=None, **kw):
        if train_cfg.mode != "lora":
            raise ValueError(
                "task='reward' requires mode='lora': the reward model is "
                "the policy trunk's adapter plus a scalar head"
            )
        if getattr(model_cfg, "n_experts", 0):
            raise ValueError("reward training does not support MoE configs")
        if getattr(model_cfg, "vision", None) is not None:
            raise ValueError("reward training supports text models only")
        super().__init__(model_cfg, train_cfg, mesh=mesh, **kw)
        if self._pp > 1:
            raise ValueError(
                "reward training does not support pipeline parallelism"
            )

    # ---- trainable tree ---------------------------------------------------

    def _split(self, variables):
        frozen, lora = super()._split(variables)
        # runs inside the jitted sharded init: head leaves pick up the rule
        # table's `.*` replicated fallback (scalars and a (V,) vector — no
        # weight-like names, nothing worth sharding)
        head = {
            "a": jnp.ones((), jnp.float32),
            "w": jnp.zeros((self.model_cfg.vocab_size,), jnp.float32),
            "b": jnp.zeros((), jnp.float32),
        }
        return frozen, {"lora": lora, "head": head}

    def _assemble(self, frozen, trainable):
        # model variables take only the trunk adapter; the head never enters
        # model.apply — it consumes the logits downstream
        return super()._assemble(frozen, trainable["lora"])

    # ---- objective --------------------------------------------------------

    def _reward_metrics(self, trainable, frozen, batch, dropout_rng=None):
        b = batch["chosen_tokens"].shape[0]
        tokens = jnp.concatenate(
            [batch["chosen_tokens"], batch["rejected_tokens"]], axis=0
        )
        masks = jnp.concatenate(
            [batch["chosen_mask"], batch["rejected_mask"]], axis=0
        )
        variables = self._assemble(frozen, trainable)
        rngs = (
            {"dropout": dropout_rng}
            if (self._use_dropout and dropout_rng is not None) else None
        )
        logits = self.model.apply(
            variables, tokens, deterministic=rngs is None, rngs=rngs,
        )
        scores = reward_scores(logits, tokens, masks, trainable["head"])
        loss, metrics = bradley_terry_loss(scores[:b], scores[b:])
        # fit()'s log line and the eval_* naming expect loss/accuracy keys;
        # accuracy IS pairwise ranking accuracy for a reward model (the
        # held-out number the promotion gate reads)
        metrics["accuracy"] = metrics["bt_accuracy"]
        return loss, metrics

    def _loss_fn(self, trainable, frozen, batch, dropout_rng):
        return self._reward_metrics(trainable, frozen, batch, dropout_rng)

    def _eval_step(self, state, batch: dict):
        """Forward-only Bradley–Terry metrics on held-out pairs."""
        _, metrics = self._reward_metrics(state.trainable, state.frozen, batch)
        return metrics

    def _writer_extra_fields(self, eval_enabled: bool) -> tuple[str, ...]:
        fields = super()._writer_extra_fields(eval_enabled)
        if eval_enabled:
            fields += ("eval_reward_margin", "eval_bt_accuracy")
        return fields

    # ---- export -----------------------------------------------------------

    def export_artifacts(self, state, artifacts_dir: str,
                         pretrained_dir: str | None = None) -> None:
        """Adapter export (the trunk, PEFT layout — same path as every LoRA
        job) plus the head as ``reward_head.msgpack`` at the artifact root.
        The head also lives in every checkpoint's trainable tree, so serve
        workers staging only spec+checkpoints can restore it without this
        file (``rollout_plane.RewardScorer.from_artifacts``)."""
        import jax
        import numpy as np
        from flax import serialization

        # collective — every rank calls; rank 0 writes
        host = self.state_to_host(state, fields=("trainable",))
        if jax.process_index() != 0:
            return
        head = jax.tree.map(
            lambda x: np.asarray(jax.device_get(x)),
            dict(host["trainable"]["head"]),
        )
        path = os.path.join(artifacts_dir, REWARD_HEAD_FILENAME)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(serialization.msgpack_serialize(head))
        os.replace(tmp, path)
        scan = self.model_cfg.scan_layers
        if not scan:
            logger.warning(
                "HF adapter export supports the scanned layer layout only: "
                "reward job exported the head but no adapter"
            )
            return
        from ..models.hf_export import export_lora_adapter

        export_lora_adapter(
            self.model_cfg, host["trainable"]["lora"],
            f"{artifacts_dir}/adapter",
        )
