"""Bounded, seed-deterministic preference rollout buffer (docs/preference.md).

The queue between the actor (generates on-policy pairs from the latest
committed checkpoint) and the learner (consumes them through the DPO loss).
Three properties the actor/learner loop depends on:

* **bounded** — ``capacity`` caps memory; pushing past it drops the OLDEST
  pairs (on-policy data ages fastest, so FIFO eviction is also the freshest-
  data policy);
* **seed-deterministic** — sampling uses the buffer's own
  ``np.random.default_rng(seed)``, so a resumed/replayed run draws the same
  batches from the same contents;
* **staleness-capped** — every pair carries the checkpoint step (``version``)
  the actor generated it from; :meth:`evict_below` enforces the watermark so
  the learner never trains on pairs more than K checkpoints old.
"""

from __future__ import annotations

import collections
import dataclasses

import numpy as np

from ..data.preference import _stack_pairs


@dataclasses.dataclass(frozen=True)
class PreferencePair:
    """One scored rollout pair, tagged with its generation provenance."""

    prompt: tuple[int, ...]
    chosen: tuple[int, ...]
    rejected: tuple[int, ...]
    #: checkpoint step of the policy the actor decoded with (0 = the base
    #: model before any commit)
    version: int
    reward_chosen: float = 0.0
    reward_rejected: float = 0.0


class RolloutBuffer:
    def __init__(self, capacity: int, seed: int = 0,
                 version_granularity: int = 1):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        #: versions per "checkpoint" for the :attr:`staleness` metric: pair
        #: versions are optimizer STEPS, but the staleness knob (and the
        #: exported gauge) count CHECKPOINTS — the learner passes
        #: ``checkpoint_every`` here so the two share a unit
        self.version_granularity = max(1, version_granularity)
        self._pairs: collections.deque[PreferencePair] = collections.deque(
            maxlen=capacity
        )
        self._rng = np.random.default_rng(seed)
        # counters (the learner's rollout_* metrics columns read these)
        self.pushed_total = 0
        self.evicted_stale_total = 0
        #: newest checkpoint step :meth:`evict_below` was told about
        self.watermark = 0

    def __len__(self) -> int:
        return len(self._pairs)

    @property
    def depth(self) -> int:
        return len(self._pairs)

    def push(self, pair: PreferencePair) -> None:
        self._pairs.append(pair)  # deque(maxlen) drops the oldest past cap
        self.pushed_total += 1

    def evict_below(self, min_version: int, *, watermark: int | None = None) -> int:
        """Drop pairs generated from a checkpoint older than ``min_version``
        (the staleness cap).  Returns how many were dropped."""
        if watermark is not None:
            self.watermark = max(self.watermark, watermark)
        kept = [p for p in self._pairs if p.version >= min_version]
        dropped = len(self._pairs) - len(kept)
        if dropped:
            self._pairs = collections.deque(kept, maxlen=self.capacity)
            self.evicted_stale_total += dropped
        return dropped

    @property
    def staleness(self) -> int:
        """CHECKPOINT lag of the OLDEST pair behind the watermark (0 =
        everything is from the newest known checkpoint) — raw step deltas
        divide by ``version_granularity``, rounded up."""
        if not self._pairs:
            return 0
        oldest = min(p.version for p in self._pairs)
        steps = max(0, self.watermark - oldest)
        return -(-steps // self.version_granularity)

    def sample_batch(self, batch_size: int, seq_len: int) -> dict:
        """A DPO batch (``data/preference.py`` layout) sampled from the
        buffer — without replacement when it is deep enough, tiled otherwise.
        Deterministic given the buffer's seed and call history."""
        if not self._pairs:
            raise ValueError("rollout buffer is empty")
        pairs = list(self._pairs)
        replace = len(pairs) < batch_size
        idx = self._rng.choice(len(pairs), size=batch_size, replace=replace)
        picked = [
            (list(pairs[i].prompt), list(pairs[i].chosen),
             list(pairs[i].rejected))
            for i in idx
        ]
        return _stack_pairs(picked, seq_len)

    def stats(self) -> dict:
        return {
            "rollout_buffer_depth": self.depth,
            "rollout_staleness": self.staleness,
            "rollout_pairs_total": self.pushed_total,
            "rollout_evicted_stale_total": self.evicted_stale_total,
        }
