"""Disaggregated RLHF data plane (docs/preference.md §Disaggregated rollouts).

The Podracer split realised on our substrate: the rollout actor moves out of
the learner's process into a serve-fleet worker (``transport/worker.py`` with
a ``rollout`` spec section), and scored preference pairs stream back over
four idempotent RPCs instead of a shared Python deque::

    learner (DPOTrainer.fit)                 rollout worker (own process)
    ────────────────────────                 ───────────────────────────
    RolloutPlane.push_policy  ──policy_version──▶  RolloutService (actor)
    puller thread             ──rollout_pull────▶    outbox of ROUND docs
       dedup → RolloutBuffer  ◀─rounds+spans────
                              ──rollout_ack─────▶    trim outbox

Exactly-once without a transaction log:

* the worker's outbox is a monotonically-sequenced list of ROUND documents;
  ``pull(after_seq)`` is a pure cursor read and ``ack(up_to_seq)`` a
  monotonic trim — a re-delivered pull replays identical documents;
* every pair carries an id ``v{version}:r{round}:p{i}``.  Generation is
  deterministic per (actor seed, version, round), so a SIGKILLed worker that
  respawns and regenerates the same round at the same policy version emits
  byte-identical pairs under the SAME ids — the learner's bounded seen-set
  then drops them as duplicates.  No pair enters the buffer twice (chaos
  test: ``tests/test_rollout_plane.py``).

Policy rollover is a PUSH of the adapter delta (``transport/wire.py::
tree_to_blob`` — megabytes of LoRA, the PR-11 wire format, never base
weights): the learner's checkpoint commits ship the trainable tree over
``rollout_policy_version``; the worker installs it BETWEEN rounds with the
zero-recompile in-place swap (:meth:`~.actor.RolloutActor.install_policy`),
so reload never stalls generation.  The frozen base crosses once, at spawn,
through the ``rollout_base`` artifact on disk (``transport/builders.py``).

The second half of the plane is the learned reward model: a ``task: reward``
job (:mod:`.reward_trainer`) trains a scalar head on the DPO data path; its
export is served by a standard worker with a ``reward`` spec section, and
:class:`RewardScorer` answers the batched ``reward_score`` RPC the actor's
``batch_reward_fn`` points at — one RPC scores a whole round's candidates.

Each round document ships a host-clock span (start/end ``time.time_ns``);
the learner re-records them into the job trace (service="rollout") so the
PR-9 timeline PROVES actor generation overlapped learner steps.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import logging
import os
import threading
import time
from pathlib import Path
from typing import Any, Callable, Iterator

from ..resilience.policy import RetryPolicy
from .actor import RolloutActor, increment_prompts, increment_reward
from .learner import RolloutConfig
from .rollout_buffer import PreferencePair, RolloutBuffer

logger = logging.getLogger(__name__)


def pair_id(version: int, round_no: int, index: int) -> str:
    """The idempotency key: deterministic generation makes a regenerated
    (version, round, index) byte-identical, so the id doubles as a content
    address for the learner's dedup."""
    return f"v{int(version)}:r{int(round_no)}:p{int(index)}"


def _pair_doc(pair: PreferencePair, pid: str) -> dict[str, Any]:
    return {
        "id": pid,
        "prompt": [int(t) for t in pair.prompt],
        "chosen": [int(t) for t in pair.chosen],
        "rejected": [int(t) for t in pair.rejected],
        "version": int(pair.version),
        "reward_chosen": float(pair.reward_chosen),
        "reward_rejected": float(pair.reward_rejected),
    }


def _pair_from_doc(doc: dict[str, Any]) -> PreferencePair:
    return PreferencePair(
        prompt=tuple(int(t) for t in doc["prompt"]),
        chosen=tuple(int(t) for t in doc["chosen"]),
        rejected=tuple(int(t) for t in doc["rejected"]),
        version=int(doc.get("version", 0)),
        reward_chosen=float(doc.get("reward_chosen", 0.0)),
        reward_rejected=float(doc.get("reward_rejected", 0.0)),
    )


# ---------------------------------------------------------------------------
# worker side: the streaming pair service
# ---------------------------------------------------------------------------


class RolloutService:
    """Producer loop + outbox behind the ``rollout_*`` RPCs.

    One daemon thread runs the actor round-robin: install any pending policy
    push, generate a round, append its document to the bounded outbox.  RPC
    handlers only touch the outbox/pending slots under the lock — a policy
    push never blocks on an in-flight generate round (it installs between
    rounds), which is what keeps rollover from stalling generation.
    """

    def __init__(self, actor: RolloutActor, *, reward_client=None,
                 max_outbox_rounds: int = 64):
        self.actor = actor
        self._reward_client = reward_client
        #: backpressure bound: a learner that stops acking stops the actor
        #: from burning device time on pairs nobody will train on
        self._max_outbox = max(1, max_outbox_rounds)
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._outbox: collections.deque[dict] = collections.deque()
        self._seq = 0
        self._pairs_per_round = 0
        self._pending_policy: tuple[int, dict | None] | None = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._error: str | None = None
        self.rounds_total = 0
        self.policy_installs_total = 0

    # ---- RPC surface (sync; the worker wraps these in to_thread) ----------

    def start(self, pairs_per_round: int) -> dict[str, Any]:
        """Idempotent: a re-delivered start (or one after a plane respawn)
        re-confirms the running producer instead of double-starting it."""
        with self._lock:
            self._pairs_per_round = max(1, int(pairs_per_round))
            running = self._thread is not None and self._thread.is_alive()
            if not running and not self._stop.is_set():
                self._thread = threading.Thread(
                    target=self._produce, name="ftc-rollout-producer",
                    daemon=True,
                )
                self._thread.start()
                running = True
            seq = self._seq
        return {
            "started": running, "seq": seq,
            "version": self.actor.version,
        }

    def pull(self, after_seq: int, max_rounds: int = 8) -> dict[str, Any]:
        if self._error is not None:
            raise RuntimeError(f"rollout producer died: {self._error}")
        with self._lock:
            rounds = [
                d for d in self._outbox if d["seq"] > int(after_seq)
            ][: max(1, int(max_rounds))]
            seq = self._seq
        return {
            "rounds": rounds, "seq": seq,
            "version": self.actor.version, "stats": self.stats(),
        }

    def ack(self, up_to_seq: int) -> dict[str, Any]:
        dropped = 0
        with self._lock:
            while self._outbox and self._outbox[0]["seq"] <= int(up_to_seq):
                self._outbox.popleft()
                dropped += 1
            depth = len(self._outbox)
        self._wake.set()  # backpressured producer may resume
        return {"acked": dropped, "outbox_depth": depth}

    def push_policy(self, version: int, tree_blob: bytes | None
                    ) -> dict[str, Any]:
        """Stage a learner-shipped adapter delta; the producer installs it
        between rounds.  Idempotent + monotonic (stale versions no-op), so
        the plane may re-push its cached policy after every respawn."""
        from ..transport.wire import tree_from_blob

        version = int(version)
        tree = tree_from_blob(tree_blob) if tree_blob else None
        with self._lock:
            pending_v = self._pending_policy[0] if self._pending_policy else 0
            accepted = version > max(self.actor.version, pending_v)
            if accepted:
                self._pending_policy = (version, tree)
            running = self._thread is not None and self._thread.is_alive()
        if accepted and not running:
            # pushed before start(): install inline so the first round
            # already decodes with the shipped policy
            self._install_pending()
        with self._lock:
            pending = self._pending_policy is not None
        return {"accepted": accepted, "version": self.actor.version,
                "pending": pending}

    # ---- producer ---------------------------------------------------------

    def _install_pending(self) -> None:
        with self._lock:
            pending = self._pending_policy
            self._pending_policy = None
        if pending is not None and self.actor.install_policy(*pending):
            with self._lock:
                self.policy_installs_total += 1

    def _produce(self) -> None:
        try:
            while not self._stop.is_set():
                self._install_pending()
                with self._lock:
                    n = self._pairs_per_round
                    backpressure = len(self._outbox) >= self._max_outbox
                if backpressure:
                    self._wake.wait(0.05)
                    # ftc: ignore[lock-discipline,shared-mutable-without-lock] -- threading.Event is internally synchronized; a clear racing an ack's set() only costs one extra 50ms poll
                    self._wake.clear()
                    continue
                t0 = time.time_ns()
                pairs = self.actor.generate_pairs(n)
                t1 = time.time_ns()
                round_no = self.actor.rounds
                version = self.actor.version
                docs = [
                    _pair_doc(p, pair_id(version, round_no, i))
                    for i, p in enumerate(pairs)
                ]
                with self._lock:
                    self._seq += 1
                    self._outbox.append({
                        "seq": self._seq,
                        "round": round_no,
                        "version": version,
                        "pairs": docs,
                        # host-clock span, shipped to the learner's trace so
                        # the PR-9 timeline can prove generate/train overlap
                        "span": {
                            "start_ns": t0, "end_ns": t1,
                            "pairs": len(docs),
                            "tokens": self.actor.tokens_generated,
                        },
                    })
                    self.rounds_total += 1
        # ftc: ignore[silent-except] -- not swallowed: re-raised to the learner on its next pull
        except BaseException as exc:
            self._error = f"{type(exc).__name__}: {exc}"
            logger.exception("rollout producer died")

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        with self._lock:
            thread = self._thread
        if thread is not None:
            thread.join(timeout=10.0)
        if self._reward_client is not None:
            self._reward_client.close()

    def stats(self) -> dict[str, Any]:
        with self._lock:
            counters = {
                "rollout_rounds_total": self.rounds_total,
                "rollout_outbox_depth": len(self._outbox),
                "rollout_seq": self._seq,
                "rollout_policy_installs_total": self.policy_installs_total,
            }
        return {
            **counters,
            "actor_tokens_per_sec": round(self.actor.tokens_per_sec, 1),
            "actor_version": self.actor.version,
            "actor_pairs_generated": self.actor.pairs_generated,
            "actor_rounds": self.actor.rounds,
            # cumulative decode counters: windowed deltas give the decode
            # throughput over any interval (the BENCH_MODE=dpo overlap leg)
            "actor_tokens_generated": self.actor.tokens_generated,
            "actor_generate_seconds": round(self.actor.generate_seconds, 6),
        }


class _RolloutBatcherShim:
    """The batcher-shaped surface :class:`~..transport.worker.WorkerServer`'s
    probe/heartbeat/drain paths expect, over a :class:`RolloutService` — NOT
    a real ``Batcher`` (one would double-step the actor's engine)."""

    def __init__(self, service: RolloutService):
        self.service = service
        self.engine = service.actor._engine

    async def health_probe(self) -> dict[str, Any]:
        return {
            "steps_total": self.engine.steps_total,
            "slots_busy": 0,
            "queue_depth": len(self.service._outbox),
            "step_errors_total": 1 if self.service._error else 0,
            "last_step_error": self.service._error,
        }

    def retry_after_s(self, extra_requests: int = 1) -> float:
        return 1.0

    def stats(self) -> dict[str, Any]:
        return self.service.stats()

    async def tenant_busy(self, adapter_id: str) -> int:
        return 0

    async def drain(self, timeout_s: float = 30.0) -> bool:
        import asyncio

        await asyncio.to_thread(self.service.stop)
        return True

    async def close(self, exc: BaseException | None = None) -> None:
        import asyncio

        await asyncio.to_thread(self.service.stop)


def build_rollout_worker(spec, *, exit_on_drain: bool = True):
    """Assemble a rollout-tenant worker from its spec (the ``spec.rollout``
    branch of ``transport/worker.py::build_worker``)."""
    from ..serve.engine import warm_engine
    from ..transport.builders import resolve_builder
    from ..transport.worker import WorkerServer

    rcfg = dict(spec.rollout or {})
    builder = resolve_builder(spec.builder)
    model, variables = builder(**(spec.builder_kwargs or {}))
    vocab = int(model.cfg.vocab_size)
    seq_len = int(rcfg.get("seq_len") or model.cfg.max_seq_len)
    prompt_fraction = float(rcfg.get("prompt_fraction", 0.5))
    prompt_len = max(2, int(seq_len * prompt_fraction))
    seed = int(rcfg.get("seed", 0))
    reward_client = None
    batch_reward_fn = None
    if int(rcfg.get("reward_port") or 0):
        from ..transport.client import RewardClient

        reward_client = RewardClient(
            str(rcfg.get("reward_host") or "127.0.0.1"),
            int(rcfg["reward_port"]),
        )
        batch_reward_fn = reward_client.batch_reward_fn()
    oracle_fn = None
    if batch_reward_fn is None and bool(rcfg.get("oracle_bootstrap", True)):
        # programmatic-reward mode keeps the cold-start bootstrap; with a
        # LEARNED reward model scores are continuous (ties are measure-zero)
        # and the oracle is retired to tests
        oracle_fn = lambda p, n: [(p[-1] + 1 + i) % vocab for i in range(n)]
    actor = RolloutActor(
        model, dict(variables)["params"], None,  # push mode: no ckpt_dir
        reward_fn=lambda p, c: increment_reward(p, c, vocab),
        batch_reward_fn=batch_reward_fn,
        prompts=increment_prompts(
            seq_len, vocab, seed + 7919, prompt_fraction
        ),
        oracle_fn=oracle_fn,
        prompt_bucket=prompt_len,
        max_new_tokens=min(
            int(rcfg.get("max_new_tokens", 16)), seq_len - prompt_len
        ),
        temperature=float(rcfg.get("temperature", 0.8)),
        top_k=int(rcfg.get("top_k", 0)),
        slots=int(rcfg.get("slots", 4)),
        seed=seed,
    )
    if spec.warm_start:
        warm_engine(actor._engine)
    service = RolloutService(
        actor, reward_client=reward_client,
        max_outbox_rounds=int(rcfg.get("max_outbox_rounds", 64)),
    )
    server = WorkerServer(spec, actor._engine, _RolloutBatcherShim(service),
                          None, exit_on_drain=exit_on_drain)
    server.rollout = service
    return server


# ---------------------------------------------------------------------------
# reward serving: the batched pair scorer behind ``reward_score``
# ---------------------------------------------------------------------------

REWARD_HEAD_FILENAME = "reward_head.msgpack"


class RewardScorer:
    """Scalar scores for (prompt, completion) items over a served policy
    trunk + the reward job's exported head (``prefs/losses.py::
    reward_scores``).  Batches are padded to pow2 (rows and length) so the
    jit cache stays bounded the same way the serve engine's buckets do."""

    def __init__(self, model, variables: dict, head: dict):
        import jax
        import jax.numpy as jnp

        self._model = model
        self._variables = variables
        self._head = jax.tree.map(jnp.asarray, head)
        self._fns: dict[tuple[int, int], Any] = {}
        self.scored_total = 0

    @classmethod
    def from_artifacts(cls, artifacts_dir: str, model,
                       variables: dict) -> "RewardScorer":
        """Load the head from a reward job's artifacts: the exported
        ``reward_head.msgpack`` when present, else the latest checkpoint's
        trainable tree — a staged serve prefix carries only
        spec+checkpoints (``serve/loader.py::fetch_promoted``), and the head
        rides every checkpoint by construction."""
        from flax import serialization

        path = os.path.join(artifacts_dir, REWARD_HEAD_FILENAME)
        if os.path.exists(path):
            with open(path, "rb") as f:
                head = serialization.msgpack_restore(f.read())
            return cls(model, variables, head)
        from ..train.checkpoint import CheckpointManager

        ckpt = CheckpointManager(os.path.join(artifacts_dir, "checkpoints"))
        latest = ckpt.latest_step()
        if latest is None:
            raise FileNotFoundError(
                f"no {REWARD_HEAD_FILENAME} and no committed checkpoint "
                f"under {artifacts_dir} — is this a task: reward job's "
                "artifact/deploy prefix?"
            )
        host = ckpt.restore(latest)  # raw: template-free, head only
        head = (host.get("trainable") or {}).get("head")
        if not isinstance(head, dict):
            raise ValueError(
                f"checkpoint step {latest} under {artifacts_dir} carries no "
                "reward head — was this job trained with task: reward?"
            )
        return cls(model, variables, head)

    def _fn(self, b: int, s: int):
        key = (b, s)
        fn = self._fns.get(key)
        if fn is None:
            import jax

            from .losses import reward_scores

            def score(variables, tokens, mask, head):
                logits = self._model.apply(
                    variables, tokens, deterministic=True
                )
                return reward_scores(logits, tokens, mask, head)

            fn = jax.jit(score)
            self._fns[key] = fn
        return fn

    def score(self, items: list[dict[str, Any]]) -> list[float]:
        import numpy as np

        from ..data.preference import _pad_pair

        if not items:
            return []
        n = len(items)
        longest = max(
            len(it["prompt"]) + len(it["completion"]) for it in items
        )
        s = 8
        while s < longest:
            s <<= 1
        s = min(s, int(self._model.cfg.max_seq_len))
        b = 1
        while b < n:
            b <<= 1
        tokens = np.zeros((b, s), np.int32)
        mask = np.zeros((b, s), np.float32)
        for i, it in enumerate(items):
            t, m = _pad_pair(
                [int(x) for x in it["prompt"]],
                [int(x) for x in it["completion"]], s,
            )
            tokens[i], mask[i] = t, m
        out = self._fn(b, s)(self._variables, tokens, mask, self._head)
        self.scored_total += n
        return [float(x) for x in np.asarray(out)[:n]]


# ---------------------------------------------------------------------------
# learner side: the plane
# ---------------------------------------------------------------------------


def write_rollout_base(artifacts_dir: str, model_spec: dict,
                       base_params: dict) -> str:
    """Stage the frozen base for remote actors (``transport/builders.py::
    rollout_base`` reads it back): model spec JSON + flax-msgpack params,
    written atomically.  Base weights cross the boundary HERE, on disk,
    exactly once — the wire only ever carries adapter deltas."""
    import jax
    import numpy as np
    from flax import serialization

    base = os.path.join(artifacts_dir, "rollout_base")
    os.makedirs(base, exist_ok=True)
    host = jax.tree.map(
        lambda x: np.asarray(jax.device_get(x)), base_params
    )
    blob = serialization.msgpack_serialize(host)
    for name, data in (
        ("model.json", json.dumps(model_spec, indent=2).encode()),
        ("params.msgpack", blob),
    ):
        tmp = os.path.join(base, f"{name}.tmp")
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, os.path.join(base, name))
    return base


@dataclasses.dataclass
class _WorkerState:
    worker_id: str
    handle: Any = None
    generation: int = 0
    #: highest seq ingested from the CURRENT incarnation (resets on respawn
    #: — the worker's outbox restarts at seq 1, and the pair-id dedup is
    #: what keeps the reset from double-ingesting)
    cursor: int = 0
    alive: bool = False
    last_stats: dict = dataclasses.field(default_factory=dict)


class RolloutPlane:
    """Learner-side home of the remote actors: spawns workers, pulls rounds
    into the :class:`~.rollout_buffer.RolloutBuffer` (one puller thread per
    worker), dedups by pair id, pushes policy rollovers, and respawns dead
    workers with seeded decorrelated backoff.

    ``spawn_fn`` is an async ``(worker_id, generation) -> handle`` where the
    handle speaks the :class:`~..transport.client.RemoteReplica` rollout
    surface — production uses :class:`~..transport.process.ProcessTransport`
    spawns; tests inject in-memory fakes to pin dedup/respawn semantics
    without process spawns.
    """

    def __init__(
        self,
        buffer: RolloutBuffer,
        *,
        num_workers: int,
        spawn_fn: Callable[..., Any],
        pairs_per_round: int,
        span_recorder=None,
        retry: RetryPolicy | None = None,
        dedup_capacity: int = 8192,
        pull_max_rounds: int = 8,
        idle_sleep_s: float = 0.02,
        rpc_timeout_s: float = 300.0,
    ):
        import asyncio

        self.buffer = buffer
        self._spawn_fn = spawn_fn
        self.pairs_per_round = int(pairs_per_round)
        self._spans = span_recorder
        # effectively-unbounded attempts: a rollout worker is cattle; the
        # learner keeps stepping on buffered pairs while it comes back
        self._retry = retry or RetryPolicy(
            max_attempts=10**9, base_delay_s=0.2, max_delay_s=10.0, seed=0
        )
        self._pull_max_rounds = int(pull_max_rounds)
        self._idle_sleep_s = idle_sleep_s
        self._rpc_timeout_s = rpc_timeout_s
        #: guards buffer + seen-set + ingest counters (pullers push from
        #: their own threads; the learner samples from the fit thread)
        self._lock = threading.Lock()
        self._seen: collections.OrderedDict[str, None] = (
            collections.OrderedDict()
        )
        self._dedup_capacity = int(dedup_capacity)
        self._workers = [
            _WorkerState(f"rollout-{i}") for i in range(max(1, num_workers))
        ]
        self._policy: tuple[int, bytes] | None = None
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self.respawns_total = 0
        self.dup_pairs_total = 0
        self.policy_pushes_total = 0
        self.rounds_received_total = 0
        self._loop = asyncio.new_event_loop()
        self._loop_thread = threading.Thread(
            target=self._loop.run_forever, name="ftc-rollout-plane",
            daemon=True,
        )
        self._loop_thread.start()

    # ---- plumbing ---------------------------------------------------------

    def _run(self, coro, timeout: float | None = None):
        import asyncio

        return asyncio.run_coroutine_threadsafe(coro, self._loop).result(
            timeout or self._rpc_timeout_s
        )

    def start(self) -> "RolloutPlane":
        for ws in self._workers:
            self._bring_up(ws)
            t = threading.Thread(
                target=self._pull_loop, args=(ws,),
                name=f"ftc-pull-{ws.worker_id}", daemon=True,
            )
            with self._lock:
                self._threads.append(t)
            t.start()
        return self

    def _bring_up(self, ws: _WorkerState) -> None:
        ws.generation += 1
        ws.handle = self._run(self._spawn_fn(ws.worker_id, ws.generation))
        ws.cursor = 0  # fresh incarnation = fresh outbox sequence
        if self._policy is not None:
            version, blob = self._policy
            self._run(ws.handle.rollout_policy_version(version, blob))
        self._run(ws.handle.rollout_start(self.pairs_per_round))
        ws.alive = True

    # ---- the pull loop (one thread per worker) ----------------------------

    def _pull_loop(self, ws: _WorkerState) -> None:
        delay: float | None = None
        while not self._stop.is_set():
            try:
                out = self._run(
                    ws.handle.rollout_pull(ws.cursor, self._pull_max_rounds)
                )
            # ftc: ignore[silent-except] -- not swallowed: every failure funnels into the respawn path below
            except Exception as exc:
                if self._stop.is_set():
                    return
                ws.alive = False
                delay = self._retry.next_delay(delay)
                logger.warning(
                    "rollout worker %s lost (%s: %s); respawning in %.2fs",
                    ws.worker_id, type(exc).__name__, exc, delay,
                )
                if self._stop.wait(delay):
                    return
                try:
                    old = ws.handle
                    if old is not None:
                        # reap the corpse (kills a half-dead process)
                        self._run(old.close(), timeout=30.0)
                # ftc: ignore[silent-except] -- best-effort reap of an already-dead worker
                except Exception:
                    pass
                try:
                    self._bring_up(ws)
                    with self._lock:
                        self.respawns_total += 1
                # ftc: ignore[silent-except] -- respawn failure loops back into the backoff above
                except Exception as exc2:
                    logger.warning("respawn of %s failed: %s",
                                   ws.worker_id, exc2)
                continue
            delay = None
            ws.last_stats = out.get("stats") or ws.last_stats
            rounds = out.get("rounds") or []
            if not rounds:
                self._stop.wait(self._idle_sleep_s)
                continue
            acked = ws.cursor
            for doc in rounds:
                self._ingest(ws, doc)
                acked = max(acked, int(doc["seq"]))
            ws.cursor = acked
            try:
                self._run(ws.handle.rollout_ack(acked))
            # ftc: ignore[silent-except] -- a lost ack only re-delivers rounds the dedup already holds
            except Exception:
                pass

    def _ingest(self, ws: _WorkerState, doc: dict) -> None:
        fresh = 0
        with self._lock:
            for pd in doc.get("pairs") or []:
                pid = str(pd["id"])
                if pid in self._seen:
                    self.dup_pairs_total += 1
                    continue
                self._seen[pid] = None
                while len(self._seen) > self._dedup_capacity:
                    self._seen.popitem(last=False)
                self.buffer.push(_pair_from_doc(pd))
                fresh += 1
            self.rounds_received_total += 1
        span = doc.get("span") or {}
        if self._spans is not None and span.get("start_ns"):
            # worker-stamped interval, learner-recorded: both processes
            # share the host clock, so the trace timeline is comparable
            self._spans.record(
                "rollout.round",
                start_ns=span["start_ns"], end_ns=span["end_ns"],
                worker=ws.worker_id, seq=int(doc.get("seq", 0)),
                policy_version=int(doc.get("version", 0)),
                pairs=fresh,
            )

    # ---- learner-facing surface ------------------------------------------

    def push_policy(self, version: int, lora_tree: dict) -> None:
        """Ship the committed trainable tree to every live worker; cached so
        respawns re-push the newest policy before streaming resumes."""
        from ..transport.wire import tree_to_blob

        blob = tree_to_blob(lora_tree)
        self._policy = (int(version), blob)
        for ws in self._workers:
            if not ws.alive:
                continue
            try:
                self._run(ws.handle.rollout_policy_version(int(version), blob))
                with self._lock:
                    self.policy_pushes_total += 1
            # ftc: ignore[silent-except] -- the puller detects the death and the respawn re-pushes the cached policy
            except Exception as exc:
                logger.warning("policy push v%d to %s failed: %s",
                               version, ws.worker_id, exc)

    def depth(self) -> int:
        with self._lock:
            return self.buffer.depth

    def evict_below(self, min_version: int, *, watermark: int) -> int:
        with self._lock:
            return self.buffer.evict_below(min_version, watermark=watermark)

    def sample_batch(self, batch_size: int, seq_len: int) -> dict:
        with self._lock:
            return self.buffer.sample_batch(batch_size, seq_len)

    def workers_alive(self) -> int:
        return sum(1 for ws in self._workers if ws.alive)

    def stats(self) -> dict[str, Any]:
        with self._lock:
            out: dict[str, Any] = dict(self.buffer.stats())
            counters = {
                "rollout_respawns_total": self.respawns_total,
                "rollout_dup_pairs_total": self.dup_pairs_total,
                "rollout_policy_pushes_total": self.policy_pushes_total,
                "rollout_rounds_received_total": self.rounds_received_total,
            }
        out.update({
            "actor_tokens_per_sec": max(
                (float(ws.last_stats.get("actor_tokens_per_sec", 0.0))
                 for ws in self._workers), default=0.0,
            ),
            "actor_version": max(
                (int(ws.last_stats.get("actor_version", 0))
                 for ws in self._workers), default=0,
            ),
            "rollout_workers_alive": self.workers_alive(),
            "rollout_actor_tokens_generated": sum(
                int(ws.last_stats.get("actor_tokens_generated", 0))
                for ws in self._workers
            ),
            "rollout_actor_generate_seconds": sum(
                float(ws.last_stats.get("actor_generate_seconds", 0.0))
                for ws in self._workers
            ),
            **counters,
        })
        return out

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=15.0)
        for ws in self._workers:
            ws.alive = False
            if ws.handle is None:
                continue
            try:
                self._run(ws.handle.close(), timeout=30.0)
            # ftc: ignore[silent-except] -- teardown of workers that may already be dead
            except Exception:
                logger.debug("close of %s raced its exit", ws.worker_id,
                             exc_info=True)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._loop_thread.join(timeout=5.0)


def remote_rollout_batch_stream(
    plane: RolloutPlane,
    ckpt_reader,
    state_template: dict,
    *,
    batch_size: int,
    seq_len: int,
    checkpoint_every: int,
    rollout: RolloutConfig,
    fill_timeout_s: float = 180.0,
) -> Iterator[dict]:
    """The learner's batch source in remote mode.  Unlike the in-process
    stream, ``next()`` never RUNS the actor — generation happens in the
    worker processes continuously — so learner steps and rollout rounds
    genuinely overlap in wall-clock.  Each ``next()``:

    1. polls the learner's checkpoint dir; a new COMMITTED step ships its
       trainable tree to the fleet (``push_policy`` — works with async
       commits: ``latest_step`` only ever reports durable checkpoints);
    2. enforces the staleness watermark on the buffer;
    3. waits (bounded) for ``min_fill``, then samples a batch.
    """
    pushed = 0
    while True:
        latest = ckpt_reader.latest_step()
        if latest is not None and latest > pushed:
            host = ckpt_reader.restore(latest, like=state_template)
            plane.push_policy(latest, host["trainable"])
            pushed = latest
        min_version = pushed - (
            rollout.staleness_checkpoints * checkpoint_every
        )
        plane.evict_below(min_version, watermark=pushed)
        deadline = time.monotonic() + fill_timeout_s
        while plane.depth() < rollout.min_fill:
            if time.monotonic() > deadline:
                if plane.depth() > 0:
                    break  # train on what we have; workers are behind
                raise RuntimeError(
                    f"no rollout pairs arrived within {fill_timeout_s:.0f}s "
                    f"({plane.workers_alive()} workers alive) — remote "
                    "actors wedged or respawn-looping"
                )
            time.sleep(0.01)
        yield plane.sample_batch(batch_size, seq_len)


def build_remote_rlhf_loop(
    trainer,
    artifacts_dir: str,
    *,
    rollout: RolloutConfig | None = None,
    pretrained_dir: str | None = None,
    prompt_fraction: float = 0.5,
    model_spec: dict | None = None,
    spawn_fn=None,
) -> tuple[Iterator[dict], RolloutPlane, RolloutBuffer]:
    """Wire remote actors + plane + buffer onto a DPO learner — the
    disaggregated twin of :func:`~.learner.build_rlhf_loop`.

    ``model_spec`` is the job spec's ``model`` section (preset/overrides/
    lora); workers rebuild the exact policy architecture from it, so it is
    required unless a custom ``spawn_fn`` is injected.
    """
    import jax

    from ..obs.trace import SpanRecorder
    from ..train.checkpoint import CheckpointManager

    rollout = (rollout or RolloutConfig()).apply_env_overrides()
    cfg = trainer.cfg
    num_workers = max(1, int(getattr(cfg, "rollout_workers", 1)))
    if jax.process_count() > 1:
        raise ValueError(
            "remote rollout workers require a single-controller learner "
            "(multi-host gangs use the in-process rlhf loop)"
        )
    state = trainer.init_state()
    if pretrained_dir:
        state = trainer.load_pretrained(state, pretrained_dir)
    if spawn_fn is None and model_spec is None:
        raise ValueError(
            "build_remote_rlhf_loop needs the job's model spec (preset/"
            "overrides/lora) so workers can rebuild the policy architecture"
        )
    write_rollout_base(
        artifacts_dir, model_spec or {}, dict(state.frozen)["params"]
    )
    # the reader MUST exist before fit's first save: CheckpointManager's
    # init sweeps leftover staging dirs, and constructing it concurrently
    # with an in-flight async save would sweep the save's own staging dir
    reader = CheckpointManager(f"{artifacts_dir}/checkpoints", keep=10**9)
    state_template = trainer.state_to_host(state)
    buffer = RolloutBuffer(
        rollout.buffer_capacity, seed=cfg.seed,
        version_granularity=max(1, cfg.checkpoint_every),
    )
    prompt_len = max(2, int(cfg.seq_len * prompt_fraction))
    if spawn_fn is None:
        from ..serve.engine import EngineConfig
        from ..transport.process import ProcessTransport

        transport = ProcessTransport(
            job_id=os.path.basename(os.path.normpath(artifacts_dir))
            or "rlhf",
            root=Path(artifacts_dir) / "rollout_workers",
            payload={
                "builder": "rollout_base", "kwargs": {"dir": artifacts_dir}
            },
        )
        bucket = 8
        while bucket < prompt_len:
            bucket <<= 1
        engine_cfg = EngineConfig(
            slots=rollout.slots, prompt_buckets=(bucket,),
            max_new_tokens=min(
                rollout.max_new_tokens, cfg.seq_len - prompt_len
            ),
            prefix_cache_bytes=0,
        )

        async def spawn_fn(worker_id: str, generation: int):
            index = int(worker_id.rsplit("-", 1)[-1])
            rdoc: dict[str, Any] = {
                "seq_len": cfg.seq_len,
                "prompt_fraction": prompt_fraction,
                "max_new_tokens": rollout.max_new_tokens,
                "temperature": rollout.temperature,
                "top_k": rollout.top_k,
                "slots": rollout.slots,
                # STABLE across respawns (never generation-dependent):
                # deterministic regeneration is what makes replayed pair
                # ids collide with their originals and dedup cleanly
                "seed": cfg.seed + index,
            }
            if rollout.reward_port:
                rdoc["reward_host"] = rollout.reward_host or "127.0.0.1"
                rdoc["reward_port"] = rollout.reward_port
            return await transport.spawn(
                worker_id, generation,
                engine_config=engine_cfg, batcher_kwargs={},
                warm_start=True, rollout=rdoc,
            )

    trace_id = os.environ.get("FTC_TRACE_ID", "")
    spans = SpanRecorder(
        artifacts_dir, trace_id, service="rollout",
        attempt=int(os.environ.get("FTC_ATTEMPT", "1") or 1),
    )
    plane = RolloutPlane(
        buffer,
        num_workers=num_workers,
        spawn_fn=spawn_fn,
        pairs_per_round=rollout.pairs_per_round,
        span_recorder=spans,
        retry=RetryPolicy(
            max_attempts=10**9, base_delay_s=0.2, max_delay_s=10.0,
            seed=cfg.seed,
        ),
    )
    plane.start()
    stream = remote_rollout_batch_stream(
        plane, reader, state_template,
        batch_size=trainer.local_batch_size,
        seq_len=cfg.seq_len,
        checkpoint_every=cfg.checkpoint_every,
        rollout=rollout,
    )
    trainer.rollout_stats_fn = plane.stats
    return stream, plane, buffer
