"""Preference optimization (docs/preference.md): DPO as a first-class job
type plus the RLHF-lite actor/learner loop that closes the train→serve loop.
"""

from .dpo_trainer import DPOTrainer
from .losses import dpo_loss, masked_sequence_logprobs
from .rollout_buffer import PreferencePair, RolloutBuffer

__all__ = [
    "DPOTrainer",
    "PreferencePair",
    "RolloutBuffer",
    "dpo_loss",
    "masked_sequence_logprobs",
]
