"""Rollout actor: the PR-4 serve engine driven by the learner's checkpoints.

The Podracer shape (PAPERS.md) on our substrate: the actor is a decoupled
rollout generator that only ever sees the learner's COMMITTED checkpoints —
weights flow exclusively through the checkpoint channel, never through shared
Python state — so the same class serves the in-process gang today and a
separate actor process later.

Per round the actor:

1. :meth:`maybe_reload` — if ``checkpoints/`` has a newer committed step,
   restore its trainable tree, fold the LoRA deltas into the base
   (``serve.loader.merge_lora_variables`` — the serving path's merge), and
   swap the engine's weight dict IN PLACE.  The engine's compiled functions
   take ``variables`` as an argument, so a reload costs zero recompiles —
   the whole loop stays inside the engine's existing compile budget (the
   armed :class:`~..analysis.recompile_guard.RecompileGuard` raises
   otherwise, and the BENCH_MODE=dpo smoke asserts it);
2. :meth:`generate_pairs` — batch-decode TWO sampled candidates per prompt
   through :class:`~..serve.engine.BatchEngine` (continuous batching: both
   candidates of all prompts share the decode lanes), score them with the
   reward function, and emit the better/worse completions as a
   :class:`~.rollout_buffer.PreferencePair` tagged with the checkpoint step.

Sampling seeds derive deterministically from (actor seed, round, prompt,
candidate), so a given checkpoint + seed always produces the same pairs.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Iterator

from ..models.lora import LoRAConfig
from ..serve.engine import BatchEngine, EngineConfig, GenRequest
from ..train.checkpoint import CheckpointManager
from .rollout_buffer import PreferencePair

logger = logging.getLogger(__name__)


def increment_reward(prompt: list[int], completion: list[int],
                     vocab_size: int) -> float:
    """Reward for the synthetic increment task: the fraction of completion
    tokens that continue the +1 (mod vocab) sequence — the programmatic
    stand-in for a reward model that makes the loop seed-deterministic and
    egress-free (RLHF-*lite*)."""
    if not completion:
        return 0.0
    prev = prompt[-1]
    good = 0
    for tok in completion:
        if tok == (prev + 1) % vocab_size:
            good += 1
        prev = tok
    return good / len(completion)


def increment_prompts(seq_len: int, vocab_size: int, seed: int,
                      prompt_fraction: float = 0.5) -> Iterator[list[int]]:
    """Deterministic stream of increment prompts (matches the prompt half of
    ``data/preference.make_increment_pair``)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    prompt_len = max(2, int(seq_len * prompt_fraction))
    while True:
        start = int(rng.integers(0, vocab_size))
        yield [(start + i) % vocab_size for i in range(prompt_len)]


class RolloutActor:
    """Generates scored preference pairs from the latest committed checkpoint.

    ``base_variables`` is the frozen base (the ``params`` collection the
    trainer initialised/loaded); the trainable adapter ALWAYS comes from the
    checkpoint directory.  Before the first commit the actor decodes with the
    plain base — exactly the policy at step 0, since LoRA's B matrices are
    zero-initialised.
    """

    def __init__(
        self,
        model: object,                      # the policy model (LoRA config)
        base_params: dict,                  # frozen base "params" tree
        ckpt_dir: str | None,
        *,
        reward_fn: Callable[[list[int], list[int]], float],
        prompts: Iterator[list[int]],
        batch_reward_fn: Callable[
            [list[tuple[list[int], list[int]]]], list[float]
        ] | None = None,
        oracle_fn: Callable[[list[int], int], list[int]] | None = None,
        state_template: dict | None = None,
        prompt_bucket: int = 0,
        max_new_tokens: int = 16,
        temperature: float = 0.8,
        top_k: int = 0,
        slots: int = 4,
        seed: int = 0,
    ):
        self._model = model
        self._model_cfg = model.cfg
        self._base_params = base_params
        #: None = push mode (the remote rollout worker): the learner SHIPS
        #: adapter deltas through :meth:`install_policy` instead of the actor
        #: polling a shared checkpoint directory it cannot see
        self._ckpt = (
            CheckpointManager(ckpt_dir, keep=10**9)  # reader: no gc
            if ckpt_dir else None
        )
        self._reward_fn = reward_fn
        #: one-RPC-per-round scoring (the remote reward model): all 2n
        #: candidates of a round score in a single batched call; falls back
        #: to per-pair ``reward_fn`` when unset
        self._batch_reward_fn = batch_reward_fn
        self._prompts = prompts
        #: cold-start escape hatch: a freshly-initialised policy samples
        #: near-uniform noise, so both candidates often score 0.0 and tie —
        #: rounds could pass without a single ranked pair.  When a WHOLE
        #: round ties, ``oracle_fn(prompt, n)`` (the reward-optimal
        #: continuation) stands in as the chosen side against the sampled
        #: rollout — the best-of-n-with-oracle-fallback bootstrap
        #: (docs/preference.md).  None disables the fallback.
        self._oracle_fn = oracle_fn
        #: host-side template of the checkpoint tree (``state_to_host``
        #: layout) — restore validates shapes against it instead of
        #: restoring blind
        self._state_template = state_template
        self.bootstrap_pairs = 0
        self._max_new_tokens = max_new_tokens
        self._temperature = temperature
        self._top_k = top_k
        self._seed = seed
        #: checkpoint step the engine currently decodes with (0 = base)
        self.version = 0
        self.reloads = 0
        self.rounds = 0
        self.pairs_generated = 0
        self.tokens_generated = 0
        self.generate_seconds = 0.0
        # rank-0 twin for the merged serving weights (serve-loader semantics)
        self._merged_cfg = self._model_cfg.replace(
            lora=LoRAConfig(rank=0, alpha=self._model_cfg.lora.alpha,
                            targets=self._model_cfg.lora.targets)
        )
        self._merged_model = type(model)(cfg=self._merged_cfg)
        # one prefill bucket sized to the prompt distribution (the caller
        # knows it); default: the model's max — correct but compiles a
        # bigger-than-needed prefill
        bucket = 8
        prompt_cap = prompt_bucket or max(2, int(self._model_cfg.max_seq_len))
        while bucket < prompt_cap:
            bucket <<= 1
        self._engine = BatchEngine(
            self._merged_model,
            self._merge({}),  # adapterless start = the step-0 policy
            EngineConfig(
                slots=slots,
                prompt_buckets=(bucket,),
                max_new_tokens=max_new_tokens,
                # stale KV from a pre-reload policy must never splice into a
                # post-reload admission, so the prefix cache stays off here
                prefix_cache_bytes=0,
            ),
        )

    # ---- weights ---------------------------------------------------------

    def _merge(self, lora_tree: dict) -> dict:
        """Fold adapter deltas into the base kernels (dense serve weights)."""
        if not lora_tree:
            return {"params": self._base_params}
        from ..serve.loader import merge_lora_variables

        _, merged = merge_lora_variables(
            self._model_cfg,
            {"params": self._base_params, "lora": lora_tree},
        )
        return merged

    def maybe_reload(self) -> bool:
        """Swap in the newest committed checkpoint's policy; True on reload.

        Variables are an ARGUMENT of the engine's compiled fns, so this
        never recompiles — shapes are identical across checkpoints.
        """
        if self._ckpt is None:
            return False  # push mode: install_policy is the only reload path
        latest = self._ckpt.latest_step()
        if latest is None or latest == self.version:
            return False
        host = self._ckpt.restore(latest, like=self._state_template)
        self._engine.variables = self._merge(host["trainable"])
        self.version = latest
        self.reloads += 1
        logger.info("actor reloaded policy from checkpoint step %d", latest)
        return True

    def install_policy(self, version: int, lora_tree: dict | None) -> bool:
        """Push-mode rollover: install a learner-shipped adapter delta.

        Idempotent and monotonic — a re-delivered or stale push (version ≤
        the installed one) is a no-op, so the learner may re-push after a
        respawn without version checks of its own.  Same zero-recompile
        in-place swap as :meth:`maybe_reload`.
        """
        version = int(version)
        if version <= self.version:
            return False
        self._engine.variables = self._merge(dict(lora_tree or {}))
        self.version = version
        self.reloads += 1
        logger.info("actor installed pushed policy version %d", version)
        return True

    @property
    def compilations(self) -> int:
        return self._engine.compilations

    @property
    def compile_budget(self) -> int:
        return self._engine.guard.budget

    @property
    def tokens_per_sec(self) -> float:
        return self.tokens_generated / max(self.generate_seconds, 1e-9)

    # ---- rollouts --------------------------------------------------------

    def generate_pairs(self, n_pairs: int) -> list[PreferencePair]:
        """Decode 2 sampled candidates for each of ``n_pairs`` prompts and
        rank them by reward.  Ties are kept out of the buffer (a tied pair
        carries no preference signal and would only flatten the margin)."""
        self.rounds += 1
        prompts = [next(self._prompts) for _ in range(n_pairs)]
        requests = []
        for i, prompt in enumerate(prompts):
            for cand in range(2):
                requests.append(GenRequest(
                    request_id=f"r{self.rounds}p{i}c{cand}",
                    tokens=list(prompt),
                    max_new_tokens=self._max_new_tokens,
                    temperature=self._temperature,
                    top_k=self._top_k,
                    # deterministic per (actor seed, round, prompt, candidate)
                    seed=(((self._seed * 1_000_003 + self.rounds) * 4093
                           + i) * 2 + cand),
                ))
        t0 = time.perf_counter()
        results = self._engine.run(requests)
        self.generate_seconds += time.perf_counter() - t0
        pairs: list[PreferencePair] = []
        scored: list[tuple[list[int], list[list[int]], list[float]]] = []
        all_outs: list[list[list[int]]] = []
        for i, prompt in enumerate(prompts):
            outs = [
                results[f"r{self.rounds}p{i}c{c}"].generated for c in (0, 1)
            ]
            self.tokens_generated += sum(len(o) for o in outs)
            all_outs.append(outs)
        if self._batch_reward_fn is not None:
            # one batched scoring call for the whole round's 2n candidates
            # (one RPC when the reward model serves remotely)
            flat = self._batch_reward_fn([
                (prompt, out)
                for prompt, outs in zip(prompts, all_outs) for out in outs
            ])
            all_rewards = [
                [float(flat[2 * i]), float(flat[2 * i + 1])]
                for i in range(len(prompts))
            ]
        else:
            all_rewards = [
                [self._reward_fn(p, o) for o in outs]
                for p, outs in zip(prompts, all_outs)
            ]
        for i, prompt in enumerate(prompts):
            outs, rewards = all_outs[i], all_rewards[i]
            scored.append((prompt, outs, rewards))
            if rewards[0] == rewards[1]:
                continue
            hi, lo = (0, 1) if rewards[0] > rewards[1] else (1, 0)
            pairs.append(PreferencePair(
                prompt=tuple(prompt),
                chosen=tuple(outs[hi]),
                rejected=tuple(outs[lo]),
                version=self.version,
                reward_chosen=rewards[hi],
                reward_rejected=rewards[lo],
            ))
        if not pairs and self._oracle_fn is not None:
            # whole round tied (cold-start noise): oracle-bootstrap — the
            # reward-optimal continuation beats any imperfect rollout
            for prompt, outs, rewards in scored:
                if rewards[0] >= 1.0:
                    continue  # the rollout is already optimal; no signal
                oracle = self._oracle_fn(prompt, len(outs[0]) or 1)
                pairs.append(PreferencePair(
                    prompt=tuple(prompt),
                    chosen=tuple(oracle),
                    rejected=tuple(outs[0]),
                    version=self.version,
                    reward_chosen=self._reward_fn(prompt, oracle),
                    reward_rejected=rewards[0],
                ))
                self.bootstrap_pairs += 1
        self.pairs_generated += len(pairs)
        return pairs
