"""Preference-optimization objectives (docs/preference.md).

Direct Preference Optimization (Rafailov et al., 2023): given a prompt with a
*chosen* and a *rejected* completion, push the policy's likelihood ratio over
the frozen reference toward the chosen side:

    loss = -log sigmoid( beta * [ (pi_c - ref_c) - (pi_r - ref_r) ] )

where each term is a per-sequence MASKED sum of token logprobs (prompt tokens
excluded — only completion targets count, the same mask convention the SFT
loss uses).  ``beta`` is the KL inverse-temperature: small beta tolerates a
policy far from the reference; large beta pins it close.

The reference model costs us nothing extra on device: in LoRA mode the policy
IS base + adapter, so the reference forward is just the base with the adapter
branch disabled (``prefs/dpo_trainer.py`` runs a rank-0 twin of the model
over the frozen ``params`` collection) — no second model copy lives in HBM.

All math in f32, matching ``train/losses.py``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def masked_sequence_logprobs(
    logits: jax.Array, tokens: jax.Array, loss_mask: jax.Array | None = None
) -> jax.Array:
    """Per-sequence sum of next-token logprobs over masked targets.

    logits: (B, S, V); tokens: (B, S) int; loss_mask: (B, S) — 1 where the
    *target* token counts (completion tokens; prompt and padding are 0).
    Returns (B,) f32.  Same shift/mask convention as
    :func:`train.losses.next_token_loss`: position ``t`` of the mask gates the
    prediction OF token ``t`` (tested for parity in ``tests/test_prefs.py``).
    """
    targets = tokens[:, 1:]
    logits = logits[:, :-1].astype(jnp.float32)
    if loss_mask is None:
        mask = jnp.ones_like(targets, jnp.float32)
    else:
        mask = loss_mask[:, 1:].astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    tok_lp = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return (tok_lp * mask).sum(axis=-1)


def reward_scores(
    logits: jax.Array,
    tokens: jax.Array,
    loss_mask: jax.Array,
    head: dict[str, jax.Array],
) -> jax.Array:
    """Scalar reward per sequence from a head over the policy trunk's logits.

    The head is ``{"a": (), "w": (V,), "b": ()}``: the score is

        a * mean masked target logprob  +  pooled_logits @ w  +  b

    where ``pooled_logits`` is the masked mean over completion positions of
    the (f32) logit rows.  With the init used by
    :class:`~.reward_trainer.RewardModelTrainer` (``a=1, w=0, b=0``) the
    step-0 score IS the mean completion likelihood — the DPO implicit-reward
    feature — so Bradley–Terry training starts from a proven ranking signal
    and learns the residual through ``w`` and the LoRA trunk.
    """
    targets = tokens[:, 1:]
    lg = logits[:, :-1].astype(jnp.float32)
    mask = loss_mask[:, 1:].astype(jnp.float32)
    logp = jax.nn.log_softmax(lg, axis=-1)
    tok_lp = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    count = jnp.maximum(mask.sum(axis=-1), 1.0)
    mean_lp = (tok_lp * mask).sum(axis=-1) / count
    pooled = (lg * mask[..., None]).sum(axis=1) / count[:, None]  # (B, V)
    return head["a"] * mean_lp + pooled @ head["w"] + head["b"]


def bradley_terry_loss(
    chosen_scores: jax.Array, rejected_scores: jax.Array
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Pairwise Bradley–Terry objective over scalar rewards, both (B,) f32:

        loss = -log sigmoid(s_chosen - s_rejected)

    — the standard reward-model loss (InstructGPT / RLHF practice).  Metrics:
    ``bt_accuracy`` is the fraction of pairs ranked correctly (the number the
    reward job's held-out gate reads), ``reward_margin`` the mean score gap.
    """
    margin = chosen_scores - rejected_scores
    loss = -jax.nn.log_sigmoid(margin).mean()
    metrics = {
        "loss": loss,
        "reward_margin": margin.mean(),
        "bt_accuracy": (margin > 0).astype(jnp.float32).mean(),
        "score_chosen": chosen_scores.mean(),
        "score_rejected": rejected_scores.mean(),
    }
    return loss, metrics


def dpo_loss(
    policy_chosen_lp: jax.Array,
    policy_rejected_lp: jax.Array,
    ref_chosen_lp: jax.Array,
    ref_rejected_lp: jax.Array,
    beta: float,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """The DPO objective over per-sequence logprobs; all inputs (B,) f32.

    The reference logprobs are treated as constants (``stop_gradient`` —
    belt-and-braces: the trainer's reference forward never touches the
    trainable tree, so no gradient path exists anyway; tested).

    Metrics: ``reward_margin`` is the mean of beta*[(pi_c-ref_c)-(pi_r-ref_r)]
    — the number a healthy DPO run drives up — and ``dpo_accuracy`` the
    fraction of pairs with a positive margin (the implicit reward model
    ranking the pair correctly).
    """
    ref_chosen_lp = jax.lax.stop_gradient(ref_chosen_lp)
    ref_rejected_lp = jax.lax.stop_gradient(ref_rejected_lp)
    chosen_reward = beta * (policy_chosen_lp - ref_chosen_lp)
    rejected_reward = beta * (policy_rejected_lp - ref_rejected_lp)
    margin = chosen_reward - rejected_reward
    loss = -jax.nn.log_sigmoid(margin).mean()
    metrics = {
        "loss": loss,
        "reward_margin": margin.mean(),
        "dpo_accuracy": (margin > 0).astype(jnp.float32).mean(),
        "reward_chosen": chosen_reward.mean(),
        "reward_rejected": rejected_reward.mean(),
    }
    return loss, metrics
