"""Platform-resolution helpers shared by every JAX entrypoint.

A site TPU plugin may call ``jax.config.update("jax_platforms", ...)`` at
interpreter startup, and an explicit config update outranks the
``JAX_PLATFORMS`` env var in JAX's resolution order — so entrypoints that
must honour the env var (tests on a virtual CPU mesh, CI bench runs) have to
re-assert it through the config API after importing jax.
"""

from __future__ import annotations

import logging
import os


def assert_platform_env() -> None:
    """Make the ``JAX_PLATFORMS`` env var authoritative, if set.

    One carve-out: a site tunnel plugin (e.g. the axon remote-TPU plugin)
    may expose the TPU backend under its *own* platform name while the
    devices it serves still present ``platform == "tpu"``. Forcing the
    literal ``"tpu"`` platform list on such a box selects the local libtpu
    — which has no device — and backend init fails. So after honouring
    ``JAX_PLATFORMS=tpu`` we probe device init once and, if the literal
    name cannot initialise, restore the plugin's own resolution (which is
    what the operator meant by "tpu" on that machine anyway).
    """
    requested = os.environ.get("JAX_PLATFORMS")
    if not requested:
        return
    import jax

    prev = jax.config.jax_platforms
    jax.config.update("jax_platforms", requested)
    if requested.strip().lower() == "tpu":
        try:
            jax.devices()
        except RuntimeError as err:
            jax.config.update("jax_platforms", prev)
            # xla_bridge caches failed backend inits; without a reset the
            # second jax.devices() can re-raise the cached 'tpu' error even
            # though the restored platform list would resolve fine
            try:
                from jax.extend.backend import clear_backends

                clear_backends()
            except Exception:  # pragma: no cover - version drift safety
                logging.getLogger(__name__).warning(
                    "could not clear cached jax backends before re-probe",
                    exc_info=True,
                )
            # The fallback must still deliver a TPU: JAX_PLATFORMS=tpu run
            # silently landing on CPU would produce CPU numbers labelled as
            # TPU measurements. Let a second init failure propagate loudly.
            if not any(d.platform == "tpu" for d in jax.devices()):
                raise RuntimeError(
                    "JAX_PLATFORMS=tpu: the literal 'tpu' platform failed to "
                    f"initialise ({err}) and the site plugin's own resolution "
                    f"({prev!r}) has no TPU device either"
                ) from err
            logging.getLogger(__name__).warning(
                "JAX_PLATFORMS=tpu: literal 'tpu' backend failed to "
                "initialise; using the site plugin's resolution %r, which "
                "serves a TPU device", prev,
            )


def env_flag(name: str, default: bool = False) -> bool:
    """Parse a boolean env var: '', '0', 'false', 'no', 'off' are false."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in ("", "0", "false", "no", "off")
