"""Platform-resolution helpers shared by every JAX entrypoint.

A site TPU plugin may call ``jax.config.update("jax_platforms", ...)`` at
interpreter startup, and an explicit config update outranks the
``JAX_PLATFORMS`` env var in JAX's resolution order — so entrypoints that
must honour the env var (tests on a virtual CPU mesh, CI bench runs) have to
re-assert it through the config API after importing jax.
"""

from __future__ import annotations

import os


def assert_platform_env() -> None:
    """Make the ``JAX_PLATFORMS`` env var authoritative, if set."""
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def env_flag(name: str, default: bool = False) -> bool:
    """Parse a boolean env var: '', '0', 'false', 'no', 'off' are false."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in ("", "0", "false", "no", "off")
