"""Attention implementations with a single dispatch point.

``impl``:
  * ``"auto"``   — measured dispatch: Pallas flash on TPU at long sequence
                   (crossover from ``ops.kernel_bench``), XLA otherwise.
  * ``"xla"``    — einsum + masked softmax; XLA fuses this well on TPU and it
                   runs everywhere (CPU tests).  Default.
  * ``"pallas"`` — hand-written TPU flash attention (``ops.pallas``); wins
                   at the benchmark shapes by never materialising (S, S).
  * ``"ring"``   — ring attention over the ``sp`` mesh axis for long context
                   (``parallel.ring``); requires shard_map.
  * ``"ulysses"`` — all-to-all head-sharded sequence parallelism
                   (``parallel.ulysses``); ``sp`` must divide ``n_kv_heads``.
                   Local kernel via ``FTC_ULYSSES_INNER`` (xla | pallas).

All paths compute softmax in float32 regardless of input dtype (bf16 inputs,
f32 accumulation — the MXU-friendly recipe).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _gqa_scores(q: jax.Array, k: jax.Array) -> jax.Array:
    """q: (B, S, H, D); k: (B, S, Hkv, D) → scores (B, Hkv, G, S, S)."""
    b, s, h, d = q.shape
    hkv = k.shape[2]
    g = h // hkv
    q = q.reshape(b, s, hkv, g, d)
    return jnp.einsum("bskgd,btkd->bkgst", q, k)


def xla_causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    scale: float | None = None,
    segment_ids: jax.Array | None = None,
) -> jax.Array:
    """Causal (optionally segment-masked) GQA attention.

    Shapes: q (B, S, H, D); k, v (B, S, Hkv, D) with H % Hkv == 0.
    Returns (B, S, H, D) in q.dtype.
    """
    b, s, h, d = q.shape
    hkv = k.shape[2]
    scale = scale if scale is not None else d ** -0.5
    scores = _gqa_scores(q * scale, k).astype(jnp.float32)

    pos = jnp.arange(s)
    mask = pos[:, None] >= pos[None, :]  # (S, S) causal
    mask = mask[None, None, None]
    if segment_ids is not None:
        seg = segment_ids[:, None, None, :, None] == segment_ids[:, None, None, None, :]
        mask = mask & seg
    scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)

    out = jnp.einsum("bkgst,btkd->bskgd", probs, v)
    return out.reshape(b, s, h, d)


def chunked_cache_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    idx: jax.Array,
) -> jax.Array:
    """A chunk of S query tokens against a static-length KV cache.

    q: (B, S, H, D); caches (B, M, Hkv, D); ``idx`` is the absolute position
    of the chunk's FIRST query token — scalar (whole batch in lockstep) or
    (B,) per-row.  Query j attends cache slots ``<= idx + j``: causal within
    the chunk, full visibility over the already-cached prefix.  S = 1 is the
    classic decode step; S > 1 is a suffix prefill continuing a prefix cache
    (``serve/prefix_cache.py``).  Same f32-softmax and 1/sqrt(D) conventions
    as :func:`xla_causal_attention`, so a chunked fill matches a monolithic
    one bit-for-bit: masked slots contribute exactly 0 to the softmax (the
    f32-min fill underflows exp to 0.0), making per-row results independent
    of the cache length and of whatever stale data other slots hold.
    """
    b, s, h, d = q.shape
    hkv = k_cache.shape[2]
    g = h // hkv
    qh = (q * d ** -0.5).reshape(b, s, hkv, g, d)
    scores = jnp.einsum("bskgd,btkd->bkgst", qh, k_cache).astype(jnp.float32)
    idx = jnp.asarray(idx)
    if idx.ndim:  # (B,) per-row positions -> broadcast over (b, k, g, s, t)
        idx = idx.reshape(b, 1, 1, 1, 1)
    qpos = idx + jnp.arange(s).reshape(1, 1, 1, s, 1)
    valid = jnp.arange(k_cache.shape[1]).reshape(1, 1, 1, 1, -1) <= qpos
    scores = jnp.where(valid, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(v_cache.dtype)
    out = jnp.einsum("bkgst,btkd->bskgd", probs, v_cache)
    return out.reshape(b, s, h, d)


def single_token_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    idx: jax.Array,
) -> jax.Array:
    """One decode step against a static-length KV cache.

    q: (B, 1, H, D); caches (B, M, Hkv, D); ``idx`` is the position of the
    query token — scalar (whole batch in lockstep, the ``cached_generate``
    path) or (B,) per-row (the serving engine, where each slot decodes at its
    own position) — cache slots > idx are masked out.  The S = 1 case of
    :func:`chunked_cache_attention` (the integer ``idx + 0`` query position
    folds away, so the compiled program is unchanged).
    """
    return chunked_cache_attention(q, k_cache, v_cache, idx)


def paged_gather(pool: jax.Array, page_table: jax.Array) -> jax.Array:
    """Assemble per-row logical KV caches from a shared page pool.

    ``pool`` is (P, T, Hkv, D) — P fixed-size pages of T sequence positions
    each, shared by every decode lane; ``page_table`` is (B, MP) int32
    physical page ids, row ``b`` listing the pages that hold lane ``b``'s
    positions ``[i*T, (i+1)*T)``.  Returns the gathered (B, MP*T, Hkv, D)
    logical cache — a compute-time temporary the attention below consumes;
    the *resident* KV is only ever the pool, which is what lets lanes hold
    pages proportional to their actual length instead of a full-length
    reservation (``serve/kv_pages.py``).

    Table slots a lane has not materialized yet point at the scratch page
    (id 0); whatever bytes they gather sit at positions beyond the lane's
    cache index and are masked to an exact-zero softmax contribution.
    """
    b, mp = page_table.shape
    _, t, hkv, d = pool.shape
    return pool[page_table].reshape(b, mp * t, hkv, d)


def _check_paged_impl(name: str, raw: str) -> str:
    val = raw.strip().lower()
    if val not in ("auto", "kernel", "gather"):
        raise ValueError(f"{name}={raw!r}: expected auto, kernel or gather")
    return val


def _check_positive_int(name: str, raw) -> int:
    try:
        val = int(raw)
    except (TypeError, ValueError):
        raise ValueError(f"{name}={raw!r}: not an integer") from None
    if val <= 0:
        raise ValueError(f"{name}={val}: must be positive")
    return val


def paged_attention_impl(
    q: jax.Array, k_pool: jax.Array, v_pool: jax.Array, page_table: jax.Array
) -> str:
    """Resolve the paged-attention path for this call: ``kernel`` or
    ``gather``.

    ``FTC_PAGED_ATTN`` ∈ {``auto`` (default), ``kernel``, ``gather``} —
    ``auto`` picks the Pallas kernel on TPU when the shapes are eligible
    (matching storage dtypes — the kernel's bit-identity contract needs
    storage-dtype matmul inputs — and the per-lane gathered cache fits the
    ``FTC_PAGED_VMEM_MB`` scratch budget, default 64), the gather oracle
    otherwise.  Explicit ``kernel`` is the operator override and the CI
    bit-identity hook: it forces the kernel everywhere, including
    interpret mode on CPU.
    """
    import os

    impl = _check_paged_impl(
        "FTC_PAGED_ATTN", os.environ.get("FTC_PAGED_ATTN") or "auto"
    )
    if impl != "auto":
        return impl
    if jax.default_backend() != "tpu":
        return "gather"
    if q.dtype != k_pool.dtype or q.dtype != v_pool.dtype:
        return "gather"
    from .pallas.paged_attention import paged_attention_vmem_bytes

    budget_mb = _check_positive_int(
        "FTC_PAGED_VMEM_MB", os.environ.get("FTC_PAGED_VMEM_MB") or 64
    )
    need = paged_attention_vmem_bytes(
        q.shape,
        page_table.shape[1],
        k_pool.shape[1],
        k_pool.shape[2],
        k_pool.dtype.itemsize,
    )
    return "kernel" if need <= budget_mb << 20 else "gather"


def paged_cache_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    page_table: jax.Array,
    idx: jax.Array,
) -> jax.Array:
    """:func:`chunked_cache_attention` reading through a page table.

    Two implementations behind one seam, dispatched by
    :func:`paged_attention_impl` (``FTC_PAGED_ATTN``):

    * ``gather`` — the reference oracle: per-lane logical caches are
      gathered from the shared pools and the exact
      :func:`chunked_cache_attention` numerics run over them, so a paged
      decode/suffix-prefill is bit-identical to the unpaged one whenever
      the gathered length equals the contiguous cache length (the engine
      sizes ``MP*T == cache_len`` when the page size divides it; otherwise
      the tail positions are masked exact-zeros like any other
      beyond-index slot).
    * ``kernel`` — ``ops.pallas.paged_attention``: walks the page table in
      the BlockSpec index map so each KV page is read from HBM once and
      the gathered copy only ever exists in VMEM scratch.  Bit-identical
      to the gather path by construction (CI proves it in interpret mode).

    S = 1 is the decode step, S > 1 a (bucket-padded) prefill or suffix
    prefill.
    """
    if paged_attention_impl(q, k_pool, v_pool, page_table) == "kernel":
        from .pallas.paged_attention import paged_attention

        return paged_attention(q, k_pool, v_pool, page_table, idx)
    return chunked_cache_attention(
        q,
        paged_gather(k_pool, page_table),
        paged_gather(v_pool, page_table),
        idx,
    )


def _check_block(name: str, raw) -> int:
    try:
        val = int(raw)
    except (TypeError, ValueError):
        raise ValueError(f"{name}={raw!r}: not an integer") from None
    if val < 128 or val % 128:
        raise ValueError(f"{name}={val}: must be a positive multiple of 128")
    return val


def _check_exp_dtype(name: str, raw: str) -> str:
    if raw not in ("float32", "bfloat16"):
        raise ValueError(f"{name}={raw!r}: expected float32 or bfloat16")
    return raw


def flash_tuning_kwargs(tuning: dict | None = None) -> dict:
    """Validated flash-kernel overrides — shared by every flash call site
    (the plain dispatch and the ring inner), so a tuning sweep
    (``scripts/tpu_session.py``) moves all of them together.

    Two sources, env over spec: the job's typed config
    (``LlamaConfig.kernel_tuning()`` — how API-submitted jobs carry the
    measured winners) seeds the values, and the ``FTC_FLASH_BLOCK_Q``/``K``
    (positive multiples of 128) / ``FTC_FLASH_EXP_DTYPE``
    (``float32``/``bfloat16``) env vars remain the operator override
    (``docs/performance.md``).
    """
    import os

    kwargs: dict = {}
    tuning = tuning or {}
    for kw in ("block_q", "block_k"):
        if tuning.get(kw):
            kwargs[kw] = _check_block(f"kernel_tuning.{kw}", tuning[kw])
    if tuning.get("exp_dtype"):
        kwargs["exp_dtype"] = _check_exp_dtype(
            "kernel_tuning.exp_dtype", tuning["exp_dtype"]
        )
    for env_name, kw in (("FTC_FLASH_BLOCK_Q", "block_q"),
                         ("FTC_FLASH_BLOCK_K", "block_k")):
        raw = os.environ.get(env_name)
        if raw:
            kwargs[kw] = _check_block(env_name, raw)
    raw = os.environ.get("FTC_FLASH_EXP_DTYPE")
    if raw:
        kwargs["exp_dtype"] = _check_exp_dtype("FTC_FLASH_EXP_DTYPE", raw)
    return kwargs


def causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    impl: str = "xla",
    segment_ids: jax.Array | None = None,
    tuning: dict | None = None,
) -> jax.Array:
    """``tuning`` is the job's typed kernel config
    (``LlamaConfig.kernel_tuning()``); env vars override it per knob."""
    import os

    tuning = tuning or {}
    if impl == "auto":
        # measured dispatch gate (ops/kernel_bench.py): Pallas flash on TPU
        # at long sequence, XLA otherwise
        from .kernel_bench import preferred_impl

        impl = preferred_impl(q.shape[1])
    if impl == "xla":
        return xla_causal_attention(q, k, v, segment_ids=segment_ids)
    if impl == "pallas":
        try:
            from .pallas.flash_attention import flash_attention
        except ImportError as e:
            raise NotImplementedError(
                "attention impl='pallas' requires ops.pallas.flash_attention "
                "(not built in this installation); use impl='xla'"
            ) from e
        return flash_attention(
            q, k, v, segment_ids=segment_ids, **flash_tuning_kwargs(tuning)
        )
    if impl in ("ring", "ulysses"):
        from ..parallel.ring import get_ring_mesh, ring_attention_sharded

        mesh = get_ring_mesh()
        if mesh is None or mesh.shape.get("sp", 1) == 1:
            # no sp axis active: plain attention is both correct and faster
            return xla_causal_attention(q, k, v, segment_ids=segment_ids)
        if impl == "ring":
            return ring_attention_sharded(
                q, k, v, segment_ids=segment_ids, mesh=mesh, tuning=tuning
            )
        from ..parallel.ulysses import ulysses_attention_sharded

        inner = (
            os.environ.get("FTC_ULYSSES_INNER", "").strip().lower()
            or tuning.get("ulysses_inner")
            or "xla"
        )
        return ulysses_attention_sharded(
            q, k, v, segment_ids=segment_ids, mesh=mesh, impl=inner,
            tuning=tuning,
        )
    raise ValueError(f"unknown attention impl: {impl!r}")
