"""FlashAttention-2-style causal GQA attention as a Pallas TPU kernel.

Why hand-write this (the reference delegates all kernels to the user's CUDA
image — SURVEY.md §2.2): the XLA path materialises the (S, S) score matrix in
HBM per head; this kernel streams K/V blocks through VMEM with an online
softmax, so activation memory is O(S · D) instead of O(S²) and the matmuls
stay on the MXU at (block_q × head_dim) × (head_dim × block_k) tiles.

Layout: grid = (batch, q_heads, S / block_q); each instance holds one query
block in VMEM and loops over that head's K/V blocks up to the causal
frontier. GQA is handled in the index map (q head h reads kv head
h // group_size), so no K/V duplication ever happens.

Differentiation: the backward pass recomputes attention with the XLA
reference implementation under ``jax.custom_vjp`` — forward gets the fused
kernel + O(S·D) residuals; a fused Pallas backward is a later optimisation.

Runs in interpreter mode off-TPU so CPU CI exercises the same kernel logic
(SURVEY.md §4 test strategy).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = float(jnp.finfo(jnp.float32).min)


def _flash_kernel(
    q_ref,      # (1, 1, bq, d)
    k_ref,      # (1, 1, S, d)   — this q-head's kv head
    v_ref,      # (1, 1, S, d)
    qseg_ref,   # (1, bq)
    kseg_ref,   # (1, S)
    o_ref,      # (1, 1, bq, d)
    *,
    block_k: int,
    seq_len: int,
    scale: float,
):
    iq = pl.program_id(2)
    bq = q_ref.shape[2]
    d = q_ref.shape[3]

    q = q_ref[0, 0].astype(jnp.float32) * scale          # (bq, d)
    qseg = qseg_ref[0]                                   # (bq,)
    q_pos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    num_kv = pl.cdiv(seq_len, block_k)
    # causal frontier: kv block j is needed iff j*block_k <= last q position
    last_q = (iq + 1) * bq - 1
    needed = last_q // block_k + 1

    def body(j, carry):
        acc, m, l = carry
        start = j * block_k
        k = k_ref[0, 0, pl.ds(start, block_k), :].astype(jnp.float32)  # (bk, d)
        v = v_ref[0, 0, pl.ds(start, block_k), :].astype(jnp.float32)
        kseg = kseg_ref[0, pl.ds(start, block_k)]                      # (bk,)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (bq, bk)
        k_pos = start + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
        mask = q_pos >= k_pos
        mask &= k_pos < seq_len  # tail block: beyond-S lanes are padding
        mask &= qseg[:, None] == kseg[None, :]
        s = jnp.where(mask, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))     # (bq, 1)
        p = jnp.exp(s - m_new)                                          # (bq, bk)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, jnp.minimum(needed, num_kv), body, (acc0, m0, l0))

    # fully-masked rows (padding segments) have l == 0: emit zeros, not NaN
    out = acc / jnp.maximum(l, 1e-30)
    o_ref[0, 0] = out.astype(o_ref.dtype)


def _flash_forward(
    q: jax.Array,           # (B, S, H, D)
    k: jax.Array,           # (B, S, Hkv, D)
    v: jax.Array,
    segment_ids: jax.Array,  # (B, S) int32
    *,
    block_q: int,
    block_k: int,
    interpret: bool,
) -> jax.Array:
    import math

    b, s, h, d = q.shape
    hkv = k.shape[2]
    group = h // hkv
    scale = d ** -0.5

    bq = min(block_q, s)
    bk = min(block_k, s)
    # pad S to a common block multiple: pl.ds/dynamic_slice CLAMP
    # out-of-bounds starts, which would silently read the wrong K rows on a
    # ragged tail block. Padded keys are masked via k_pos >= seq_len; padded
    # query rows are sliced away below.
    s_pad = math.lcm(bq, bk) * pl.cdiv(s, math.lcm(bq, bk))
    if s_pad != s:
        pad = [(0, 0), (0, s_pad - s), (0, 0), (0, 0)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
        segment_ids = jnp.pad(segment_ids, [(0, 0), (0, s_pad - s)])

    # (B, H, S, D) — heads on the grid, sequence contiguous for tiling
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    grid = (b, h, pl.cdiv(s_pad, bq))

    out = pl.pallas_call(
        functools.partial(
            _flash_kernel, block_k=bk, seq_len=s, scale=scale
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, s_pad, d), lambda ib, ih, iq: (ib, ih // group, 0, 0)),
            pl.BlockSpec((1, 1, s_pad, d), lambda ib, ih, iq: (ib, ih // group, 0, 0)),
            pl.BlockSpec((1, bq), lambda ib, ih, iq: (ib, iq)),
            pl.BlockSpec((1, s_pad), lambda ib, ih, iq: (ib, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq: (ib, ih, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, s_pad, d), q.dtype),
        interpret=interpret,
    )(qt, kt, vt, segment_ids, segment_ids)

    return out.transpose(0, 2, 1, 3)[:, :s]  # back to (B, S, H, D), unpadded


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_attention(q, k, v, segment_ids, block_q, block_k, interpret):
    return _flash_forward(
        q, k, v, segment_ids,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )


def _flash_fwd(q, k, v, segment_ids, block_q, block_k, interpret):
    out = _flash_attention(q, k, v, segment_ids, block_q, block_k, interpret)
    return out, (q, k, v, segment_ids)


def _flash_bwd(block_q, block_k, interpret, residuals, g):
    # rematerialised backward through the XLA reference path — activation
    # memory during bwd is per-layer transient, forward residuals stay O(S·D)
    from ..attention import xla_causal_attention

    q, k, v, segment_ids = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: xla_causal_attention(q_, k_, v_, segment_ids=segment_ids),
        q, k, v,
    )
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


_flash_attention.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    segment_ids: jax.Array | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jax.Array:
    """Causal GQA flash attention. Shapes as ``ops.attention.causal_attention``."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, s, _, _ = q.shape
    if segment_ids is None:
        segment_ids = jnp.zeros((b, s), jnp.int32)
    return _flash_attention(
        q, k, v, segment_ids.astype(jnp.int32), block_q, block_k, interpret
    )
