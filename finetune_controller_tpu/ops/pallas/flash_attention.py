"""FlashAttention-2-style causal GQA attention as Pallas TPU kernels.

Why hand-write this (the reference delegates all kernels to the user's CUDA
image — SURVEY.md §2.2): the XLA path materialises the (S, S) score matrix in
HBM per head; these kernels stream K/V blocks through VMEM with an online
softmax, so activation memory is O(S · D) instead of O(S²) and the matmuls
stay on the MXU at (block_q × head_dim) × (head_dim × block_k) tiles.

Kernel structure (the canonical Mosaic pipeline shape): grid =
(batch, q_heads, outer_blocks, inner_blocks) with the inner dimension
iterated sequentially per core — online-softmax state lives in VMEM scratch
across inner iterations and Mosaic double-buffers the inner operand's block
DMAs behind the MXU work. GQA is handled in the index map (q head h reads kv
head h // group_size), so no K/V duplication ever happens. Causally-skipped
blocks still DMA (static grid) but skip all compute via ``pl.when``.

Differentiation is a full Pallas path under ``jax.custom_vjp``:

* forward saves O(S) residuals — the output and the per-row logsumexp — never
  the (S, S) probabilities;
* backward runs two kernels in the FlashAttention-2 style: a dQ kernel
  (inner loop over K/V blocks) and a dK/dV kernel (inner loop over Q blocks),
  both recomputing p = exp(s − lse) on the fly.

Masked-row semantics: every p is explicitly zeroed under the mask (NOT just
the scores set to −inf), so fully-masked rows — padding segments, padded
tails — genuinely accumulate l == 0 and emit zeros with zero gradients.

Runs in interpreter mode off-TPU so CPU CI exercises the same kernel logic
(SURVEY.md §4 test strategy). Dispatch between this kernel and the XLA path
is measured, not assumed — see ``ops/kernel_bench.py``.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(jnp.finfo(jnp.float32).min)

# Default block size, measured on v5e (tpu_session.jsonl, 2026-07-31): the
# seq-8192 grad-path A/B ran 64.5 ms at block 512 vs 49.1 ms at block 1024
# with f32 exp held fixed (−24% — fewer grid steps, same VMEM residency).
# The full TinyLlama seq-2048 train step gained +8% end-to-end from block
# 1024 and bf16 exp TOGETHER (no block-only train-step measurement exists).
# Capped to the sequence length at call time, so short-sequence callers are
# unaffected.
DEFAULT_BLOCK = 1024


def _resolve_tuning(
    q, block_q: int | None, block_k: int | None, exp_dtype: str | None
) -> tuple[int, int, str]:
    """Fill unset tuning knobs with the measured TPU defaults.

    ``exp_dtype=None`` follows the input dtype: bf16 Q/K/V get the bf16 exp
    path — p is about to be rounded to bf16 for the MXU anyway
    (``p.astype(v.dtype)``), so computing exp in bf16 after the f32
    max-subtract adds <0.4% relative error to an already-bf16-rounded
    quantity and measured −10% on the seq-8192 grad path (tpu_session.jsonl
    kernel A/B: bf16-b1024 44.3 ms vs f32-b1024 49.1 ms). Full-precision
    inputs keep the f32 exp — the numerics oracle is untouched.
    """
    if block_q is None:
        block_q = DEFAULT_BLOCK
    if block_k is None:
        block_k = DEFAULT_BLOCK
    if exp_dtype is None:
        exp_dtype = "bfloat16" if q.dtype == jnp.bfloat16 else "float32"
    return block_q, block_k, exp_dtype


def _dimension_semantics(*sem):
    # modern jax renamed TPUCompilerParams -> CompilerParams; support both so
    # the container's baked-in 0.4.x toolchain runs these kernels unmodified
    params_cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return params_cls(dimension_semantics=sem)


def _segment_mask(qseg_ref, kseg_ref):
    """(bq, bk) same-segment mask from the (1, 1, b*) segment-id refs."""
    return qseg_ref[0, 0][:, None] == kseg_ref[0, 0][None, :]


def _block_positions(iq, ik, bq, bk):
    """Absolute (q_pos, k_pos) iotas for a (bq, bk) score block — the masked
    (non-interior) kernel paths compare these; which bound each kernel also
    applies against seq_len differs (fwd/dq mask padded KEYS, dkv masks
    padded QUERIES), so the comparisons stay at the call sites."""
    q_pos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return q_pos, k_pos


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    q_ref,      # (1, 1, bq, d)
    k_ref,      # (1, 1, bk, d)
    v_ref,      # (1, 1, bk, d)
    qseg_ref,   # (1, 1, bq)
    kseg_ref,   # (1, 1, bk)
    o_ref,      # (1, 1, bq, d)
    lse_ref,    # (1, 1, bq, 1)
    acc_ref,    # VMEM scratch (bq, d) f32
    m_ref,      # VMEM scratch (bq, 1) f32
    l_ref,      # VMEM scratch (bq, 1) f32
    *,
    seq_len: int,
    scale: float,
    use_segments: bool,
    exp_dtype: str = "float32",
    causal: bool = True,
):
    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]
    edt = jnp.dtype(exp_dtype)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    if causal:
        # causal frontier: this k block is live iff its first key position is
        # <= the q block's last query position
        needed = ik * bk <= (iq + 1) * bq - 1
        # interior = every (q, k) pair in the block is causally valid AND
        # inside the real sequence: the iota/compare/where mask passes can be
        # skipped. The attention kernel is VPU-bound (S^2 elementwise vs
        # 2dS^2 MXU flops at small head dims), so dropping mask passes on the
        # ~N^2/2 interior blocks is a direct win at long sequence.
        interior = ((ik + 1) * bk - 1 <= iq * bq) & ((ik + 1) * bk <= seq_len)
    else:
        # full (non-causal) attention — the ring-attention off-diagonal
        # steps, where every key is in the query's global past
        needed = ik * bk < seq_len
        interior = (ik + 1) * bk <= seq_len

    def _online_update(s, mask):
        """Shared online-softmax update; ``mask`` None = fully valid block."""
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # zero p under the mask explicitly: for a fully-masked row m_new is
        # still NEG_INF and exp(s - m_new) would be exp(0) = 1 per lane,
        # accumulating l = block count instead of 0.
        # exp_dtype="bfloat16" computes the S²-elementwise exp — the VPU-bound
        # hot loop at small head dims — in bf16 after the f32 max-subtract
        # (safe: arguments are <= 0, so the bf16 range is never stressed;
        # precision is ~3 decimal digits on a probability-like quantity).
        # f32 stays the default until the chip A/B proves a win.
        diff = s - m_new
        p = jnp.exp(diff if edt == jnp.float32 else diff.astype(edt))
        if mask is not None:
            p = jnp.where(mask, p, jnp.zeros((), p.dtype))
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(
            p, axis=-1, keepdims=True, dtype=jnp.float32
        )
        # p rounds to the value dtype for the MXU (the FlashAttention-2
        # recipe); accumulation stays f32 in VMEM scratch
        v = v_ref[0, 0]
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = m_new

    def _scores():
        # matmul inputs stay in their storage dtype (bf16 in production) with
        # f32 MXU accumulation; the scale folds in AFTER the dot, in f32
        return jax.lax.dot_general(
            q_ref[0, 0], k_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (bq, bk) f32

    @pl.when(needed & ~interior)
    def _compute_masked():
        s = _scores()
        q_pos, k_pos = _block_positions(iq, ik, bq, bk)
        mask = k_pos < seq_len  # tail block: beyond-S lanes are padding
        if causal:
            mask &= q_pos >= k_pos
        if use_segments:
            mask &= _segment_mask(qseg_ref, kseg_ref)
        _online_update(s, mask)

    @pl.when(needed & interior)
    def _compute_interior():
        _online_update(
            _scores(),
            _segment_mask(qseg_ref, kseg_ref) if use_segments else None,
        )

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_ref[...]
        # fully-masked rows (padding segments) have l == 0: emit zeros, not NaN
        o_ref[0, 0] = (acc_ref[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        # logsumexp residual for the backward; empty rows stay deeply negative
        # so the backward's exp(s - lse) is masked there anyway
        lse_ref[0, 0] = m_ref[...] + jnp.log(jnp.maximum(l, 1e-30))


def _pad_inputs(q, k, v, segment_ids, bq, bk, kv_segment_ids=None):
    """Pad S to a common block multiple: pl.ds/dynamic_slice CLAMP
    out-of-bounds starts, which would silently read the wrong K rows on a
    ragged tail block. Padded keys are masked via k_pos >= seq_len; padded
    query rows are sliced away by the callers."""
    s = q.shape[1]
    if kv_segment_ids is None:
        kv_segment_ids = segment_ids
    s_pad = math.lcm(bq, bk) * pl.cdiv(s, math.lcm(bq, bk))
    if s_pad != s:
        pad = [(0, 0), (0, s_pad - s), (0, 0), (0, 0)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
        segment_ids = jnp.pad(segment_ids, [(0, 0), (0, s_pad - s)])
        kv_segment_ids = jnp.pad(kv_segment_ids, [(0, 0), (0, s_pad - s)])
    return q, k, v, segment_ids, kv_segment_ids, s_pad


def _flash_forward(
    q: jax.Array,           # (B, S, H, D)
    k: jax.Array,           # (B, S, Hkv, D)
    v: jax.Array,
    segment_ids: jax.Array,  # (B, S) int32
    *,
    block_q: int,
    block_k: int,
    interpret: bool,
    use_segments: bool = True,
    exp_dtype: str = "float32",
    causal: bool = True,
    kv_segment_ids: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Returns (out (B, S, H, D), lse (B, H, S_pad, 1) f32)."""
    b, s, h, d = q.shape
    hkv = k.shape[2]
    group = h // hkv
    scale = d ** -0.5

    bq = min(block_q, s)
    bk = min(block_k, s)
    q, k, v, segment_ids, kv_segment_ids, s_pad = _pad_inputs(
        q, k, v, segment_ids, bq, bk, kv_segment_ids)

    # (B, H, S, D) — heads on the grid, sequence contiguous for tiling
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    # segments ride as (B, 1, S): TPU block shapes must keep their last two
    # dims (8, 128)-aligned or equal to the array dims — a (1, bq) block of a
    # (B, S) array satisfies neither
    seg3 = segment_ids[:, None, :]
    kseg3 = kv_segment_ids[:, None, :]

    nq = pl.cdiv(s_pad, bq)
    nk = pl.cdiv(s_pad, bk)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, seq_len=s, scale=scale,
                          use_segments=use_segments, exp_dtype=exp_dtype,
                          causal=causal),
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda ib, ih, iq, ik: (ib, ih // group, ik, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda ib, ih, iq, ik: (ib, ih // group, ik, 0)),
            pl.BlockSpec((1, 1, bq), lambda ib, ih, iq, ik: (ib, 0, iq)),
            pl.BlockSpec((1, 1, bk), lambda ib, ih, iq, ik: (ib, 0, ik)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s_pad, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s_pad, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        compiler_params=_dimension_semantics(
            "parallel", "parallel", "parallel", "arbitrary"
        ),
        interpret=interpret,
    )(qt, kt, vt, seg3, kseg3)

    return out.transpose(0, 2, 1, 3)[:, :s], lse


# ---------------------------------------------------------------------------
# backward — FlashAttention-2 split: dQ kernel + dK/dV kernel
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref,      # (1, 1, bq, d)
    k_ref,      # (1, 1, bk, d)
    v_ref,      # (1, 1, bk, d)
    do_ref,     # (1, 1, bq, d)
    lse_ref,    # (1, 1, bq, 1)
    delta_ref,  # (1, 1, bq, 1)
    qseg_ref,   # (1, 1, bq)
    kseg_ref,   # (1, 1, bk)
    dq_ref,     # (1, 1, bq, d)
    dq_acc,     # VMEM scratch (bq, d) f32
    *,
    seq_len: int,
    scale: float,
    use_segments: bool,
    exp_dtype: str = "float32",
    causal: bool = True,
):
    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]
    edt = jnp.dtype(exp_dtype)

    @pl.when(ik == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    if causal:
        needed = ik * bk <= (iq + 1) * bq - 1
        # all (q, k) pairs valid (see forward kernel): skip the mask passes
        interior = ((ik + 1) * bk - 1 <= iq * bq) & ((ik + 1) * bk <= seq_len)
    else:
        needed = ik * bk < seq_len
        interior = (ik + 1) * bk <= seq_len

    def _update(mask):
        # storage-dtype (bf16) matmul inputs + f32 accumulation — see the
        # forward kernel's note; the scale folds in after the s dot
        q = q_ref[0, 0]                                        # (bq, d)
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]                                    # (bq, 1)
        delta = delta_ref[0, 0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        diff = s - lse
        p = jnp.exp(diff if edt == jnp.float32 else diff.astype(edt))
        if mask is not None:
            p = jnp.where(mask, p, jnp.zeros((), p.dtype))

        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(needed & ~interior)
    def _compute_masked():
        q_pos, k_pos = _block_positions(iq, ik, bq, bk)
        mask = k_pos < seq_len
        if causal:
            mask &= q_pos >= k_pos
        if use_segments:
            mask &= _segment_mask(qseg_ref, kseg_ref)
        _update(mask)

    @pl.when(needed & interior)
    def _compute_interior():
        _update(_segment_mask(qseg_ref, kseg_ref) if use_segments else None)

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0, 0] = (dq_acc[...] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    k_ref,      # (1, 1, bk, d)
    v_ref,      # (1, 1, bk, d)
    q_ref,      # (1, 1, bq, d)  — q head = ihkv*group + j // nq
    do_ref,     # (1, 1, bq, d)
    lse_ref,    # (1, 1, bq, 1)
    delta_ref,  # (1, 1, bq, 1)
    kseg_ref,   # (1, 1, bk)
    qseg_ref,   # (1, 1, bq)
    dk_ref,     # (1, 1, bk, d)  — one accumulator per KV head (GQA group
    dv_ref,     # (1, 1, bk, d)     reduced IN kernel, no per-q-head partials)
    dk_acc,     # VMEM scratch (bk, d) f32
    dv_acc,     # VMEM scratch (bk, d) f32
    *,
    n_q_blocks: int,
    seq_len: int,
    scale: float,
    use_segments: bool,
    exp_dtype: str = "float32",
    causal: bool = True,
):
    ik, j = pl.program_id(2), pl.program_id(3)
    n_inner = pl.num_programs(3)   # = group * n_q_blocks
    iq = j % n_q_blocks            # q block within the current group member
    bk = k_ref.shape[2]
    bq = q_ref.shape[2]
    edt = jnp.dtype(exp_dtype)

    @pl.when(j == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    if causal:
        # this q block contributes iff its last query can see the block's
        # first key
        needed = (iq + 1) * bq - 1 >= ik * bk
        # all pairs causally valid AND no padded q rows: mask passes skippable
        interior = ((ik + 1) * bk - 1 <= iq * bq) & ((iq + 1) * bq <= seq_len)
    else:
        needed = iq * bq < seq_len
        interior = (iq + 1) * bq <= seq_len

    def _update(mask):
        # storage-dtype (bf16) matmul inputs + f32 accumulation — see the
        # forward kernel's note; the scale folds in after the s dot and at
        # the dK finalize (it used to ride on a pre-scaled f32 q)
        k = k_ref[0, 0]                                        # (bk, d)
        v = v_ref[0, 0]
        q = q_ref[0, 0]                                        # (bq, d)
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]                                    # (bq, 1)
        delta = delta_ref[0, 0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                                              # (bq, bk)
        diff = s - lse
        p = jnp.exp(diff if edt == jnp.float32 else diff.astype(edt))
        if mask is not None:
            p = jnp.where(mask, p, jnp.zeros((), p.dtype))

        # dV += pᵀ · dO
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        # dK += scale · dsᵀ · q (scale applied once, at finalize)
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(needed & ~interior)
    def _compute_masked():
        q_pos, k_pos = _block_positions(iq, ik, bq, bk)
        mask = q_pos < seq_len
        if causal:
            mask &= q_pos >= k_pos
        if use_segments:
            mask &= _segment_mask(qseg_ref, kseg_ref)
        _update(mask)

    @pl.when(needed & interior)
    def _compute_interior():
        _update(_segment_mask(qseg_ref, kseg_ref) if use_segments else None)

    @pl.when(j == n_inner - 1)
    def _finalize():
        dk_ref[0, 0] = (dk_acc[...] * scale).astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_backward(
    q, k, v, segment_ids, out, lse, g,
    *, block_q: int, block_k: int, interpret: bool, use_segments: bool = True,
    exp_dtype: str = "float32", causal: bool = True, dlse=None,
    kv_segment_ids=None,
):
    b, s, h, d = q.shape
    hkv = k.shape[2]
    group = h // hkv
    scale = d ** -0.5

    bq = min(block_q, s)
    bk = min(block_k, s)
    q_p, k_p, v_p, seg_p, kseg_p, s_pad = _pad_inputs(
        q, k, v, segment_ids, bq, bk, kv_segment_ids)
    g_p = jnp.pad(g, [(0, 0), (0, s_pad - s), (0, 0), (0, 0)]) if s_pad != s else g
    out_p = (
        jnp.pad(out, [(0, 0), (0, s_pad - s), (0, 0), (0, 0)])
        if s_pad != s else out
    )

    qt = q_p.transpose(0, 2, 1, 3)      # (B, H, S, D)
    kt = k_p.transpose(0, 2, 1, 3)      # (B, Hkv, S, D)
    vt = v_p.transpose(0, 2, 1, 3)
    dot = g_p.transpose(0, 2, 1, 3)     # (B, H, S, D)
    outt = out_p.transpose(0, 2, 1, 3)

    # delta_i = Σ_d dO_i · O_i — O(S·D) precompute, plain XLA
    delta = jnp.sum(
        dot.astype(jnp.float32) * outt.astype(jnp.float32), axis=-1, keepdims=True
    )  # (B, H, S_pad, 1)
    if dlse is not None:
        # lse cotangent: ∂lse_i/∂s_ij = p_ij, so ds_ij gains dlse_i·p_ij —
        # which is exactly ds = p·(dp − (delta − dlse)). Folding it into
        # delta means the backward kernels need no change at all.
        delta = delta - dlse

    seg3 = seg_p[:, None, :]  # (B, 1, S_pad) — see _flash_forward
    kseg3 = kseg_p[:, None, :]

    nq = pl.cdiv(s_pad, bq)
    nk = pl.cdiv(s_pad, bk)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, seq_len=s, scale=scale,
                          use_segments=use_segments, exp_dtype=exp_dtype,
                          causal=causal),
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda ib, ih, iq, ik: (ib, ih // group, ik, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda ib, ih, iq, ik: (ib, ih // group, ik, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, bq), lambda ib, ih, iq, ik: (ib, 0, iq)),
            pl.BlockSpec((1, 1, bk), lambda ib, ih, iq, ik: (ib, 0, ik)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, s_pad, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_dimension_semantics(
            "parallel", "parallel", "parallel", "arbitrary"
        ),
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta, seg3, kseg3)

    # dK/dV: grid over KV heads; each instance owns one key block and the
    # inner dimension sweeps (group member, q block), so the GQA group sum
    # accumulates in VMEM scratch — no per-q-head f32 partials in HBM.
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, n_q_blocks=nq, seq_len=s, scale=scale,
            use_segments=use_segments, exp_dtype=exp_dtype, causal=causal,
        ),
        grid=(b, hkv, nk, group * nq),
        in_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda ib, ih, ik, j: (ib, ih, ik, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda ib, ih, ik, j: (ib, ih, ik, 0)),
            pl.BlockSpec(
                (1, 1, bq, d),
                lambda ib, ih, ik, j: (ib, ih * group + j // nq, j % nq, 0),
            ),
            pl.BlockSpec(
                (1, 1, bq, d),
                lambda ib, ih, ik, j: (ib, ih * group + j // nq, j % nq, 0),
            ),
            pl.BlockSpec(
                (1, 1, bq, 1),
                lambda ib, ih, ik, j: (ib, ih * group + j // nq, j % nq, 0),
            ),
            pl.BlockSpec(
                (1, 1, bq, 1),
                lambda ib, ih, ik, j: (ib, ih * group + j // nq, j % nq, 0),
            ),
            pl.BlockSpec((1, 1, bk), lambda ib, ih, ik, j: (ib, 0, ik)),
            pl.BlockSpec((1, 1, bq), lambda ib, ih, ik, j: (ib, 0, j % nq)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda ib, ih, ik, j: (ib, ih, ik, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda ib, ih, ik, j: (ib, ih, ik, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hkv, s_pad, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hkv, s_pad, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=_dimension_semantics(
            "parallel", "parallel", "parallel", "arbitrary"
        ),
        interpret=interpret,
    )(kt, vt, qt, dot, lse, delta, kseg3, seg3)

    dq = dq.transpose(0, 2, 1, 3)[:, :s]
    dk = dk.transpose(0, 2, 1, 3)[:, :s].astype(k.dtype)
    dv = dv.transpose(0, 2, 1, 3)[:, :s].astype(v.dtype)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp plumbing
# ---------------------------------------------------------------------------


# --- the single custom_vjp: (out, lse) ---------------------------------------
#
# One vjp serves both public surfaces: the plain out-only path (a dropped
# lse output gets a zero cotangent, and dlse=0 leaves the backward's delta
# untouched — identical gradients) and the ring-attention inner, which
# merges per-step partials across hops via their per-row logsumexp and
# needs lse differentiable. The lse cotangent folds into the backward's
# delta (see _flash_backward), keeping one backward implementation.


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash_attention_lse(q, k, v, segment_ids, kv_segment_ids, block_q,
                         block_k, interpret, use_segments, exp_dtype, causal):
    out, lse = _flash_forward(
        q, k, v, segment_ids, block_q=block_q, block_k=block_k,
        interpret=interpret, use_segments=use_segments, exp_dtype=exp_dtype,
        causal=causal, kv_segment_ids=kv_segment_ids,
    )
    return out, lse[:, :, : q.shape[1]]


def _flash_lse_fwd(q, k, v, segment_ids, kv_segment_ids, block_q, block_k,
                   interpret, use_segments, exp_dtype, causal):
    out, lse = _flash_forward(
        q, k, v, segment_ids, block_q=block_q, block_k=block_k,
        interpret=interpret, use_segments=use_segments, exp_dtype=exp_dtype,
        causal=causal, kv_segment_ids=kv_segment_ids,
    )
    # Named so a remat policy (models/llama.py remat_policy_fn, e.g.
    # "mlp_flash") can SAVE these residuals: under plain per-layer remat the
    # backward re-runs this whole forward kernel just to rebuild out/lse —
    # ~125 ms/step of the TinyLlama bench profile. checkpoint_name inside a
    # custom_vjp fwd is honored by save_only_these_names (verified by jaxpr:
    # the named values move to the primal pass and the remat region consumes
    # them as constants).
    res_out = checkpoint_name(out, "flash_out")
    res_lse = checkpoint_name(lse, "flash_lse")
    return (out, lse[:, :, : q.shape[1]]), (
        q, k, v, segment_ids, kv_segment_ids, res_out, res_lse,
    )


def _flash_lse_bwd(block_q, block_k, interpret, use_segments, exp_dtype,
                   causal, residuals, g):
    g_out, g_lse = g
    q, k, v, segment_ids, kv_segment_ids, out, lse = residuals
    s_pad = lse.shape[2]
    dlse = g_lse.astype(jnp.float32)
    if dlse.shape[2] != s_pad:
        dlse = jnp.pad(
            dlse, [(0, 0), (0, 0), (0, s_pad - dlse.shape[2]), (0, 0)]
        )
    dq, dk, dv = _flash_backward(
        q, k, v, segment_ids, out, lse, g_out,
        block_q=block_q, block_k=block_k, interpret=interpret,
        use_segments=use_segments, exp_dtype=exp_dtype, causal=causal,
        dlse=dlse, kv_segment_ids=kv_segment_ids,
    )
    return dq, dk, dv, None, None


_flash_attention_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    segment_ids: jax.Array | None = None,
    kv_segment_ids: jax.Array | None = None,
    causal: bool = True,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    exp_dtype: str | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Flash attention returning ``(out, lse)`` with ``lse`` (B, H, S, 1) f32.

    ``causal=False`` computes full (bidirectional) attention — the ring
    off-diagonal steps, where every resident key is in the query's global
    past. ``kv_segment_ids`` (default: same as ``segment_ids``) supports the
    ring case where the resident K/V shard carries segments from another
    sequence shard. Both outputs are differentiable.

    Unset ``block_q``/``block_k``/``exp_dtype`` resolve to the measured TPU
    defaults (see :func:`_resolve_tuning`).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_q, block_k, exp_dtype = _resolve_tuning(q, block_q, block_k, exp_dtype)
    b, s, _, _ = q.shape
    use_segments = segment_ids is not None or kv_segment_ids is not None
    if segment_ids is None:
        segment_ids = jnp.zeros((b, s), jnp.int32)
    if kv_segment_ids is None:
        kv_segment_ids = segment_ids
    return _flash_attention_lse(
        q, k, v, segment_ids.astype(jnp.int32),
        kv_segment_ids.astype(jnp.int32), block_q, block_k, interpret,
        use_segments, exp_dtype, causal,
    )


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    segment_ids: jax.Array | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    exp_dtype: str | None = None,
) -> jax.Array:
    """Causal GQA flash attention. Shapes as ``ops.attention.causal_attention``.

    Unset tuning knobs resolve to the measured v5e winners (1024-token
    blocks; exp dtype follows the input dtype — ``_resolve_tuning``). The
    earlier 512 default came from a kernel-only sweep where 512→1024 was
    flat at seq 2048; the 2026-07-31 session measured block 1024 −24% on
    the seq-8192 grad path (f32 exp held fixed) and the combined winner
    (block 1024 + bf16 exp) +8% on the full seq-2048 train step, so these
    are the defaults (blocks are capped to S at call time)."""
    out, _ = flash_attention_with_lse(
        q, k, v, segment_ids=segment_ids, block_q=block_q, block_k=block_k,
        interpret=interpret, exp_dtype=exp_dtype,
    )
    return out
