"""Block-sparse paged-attention decode kernel (Pallas TPU).

Why hand-write this: the gather-based ``paged_cache_attention``
(``ops/attention.py``) materialises every lane's full logical cache
``(B, MP*T, Hkv, D)`` from the page pool in HBM on **every** decode step —
a pure memory-bandwidth tax that scales with the pool's page count, not
with the tokens actually attended.  This kernel walks each lane's page
list directly through the BlockSpec index map: grid = (lane, page-slot),
and the scalar-prefetched page table routes page-slot ``ip`` of lane
``ib`` to physical pool page ``table[ib, ip]`` — each KV page is DMA'd
from HBM into VMEM exactly once and the gathered copy never exists
outside VMEM scratch.

Numerics are the point, not a compromise: CI proves the kernel
bit-identical to the gather oracle (interpret mode off-TPU), so the
per-page loop is a pure copy phase and the finalize step replays
``chunked_cache_attention``'s exact op sequence — same storage-dtype
matmul inputs with no ``preferred_element_type`` (the einsum's bf16
intermediate), same f32 cast, same f32-min mask fill (exp underflows to
an exact 0.0 for out-of-range slots, which is what makes scratch-page
garbage invisible), same ``jax.nn.softmax``, same probs-to-V-dtype cast.
An online-softmax accumulator would re-order the floating-point
reductions and break that contract; the VMEM-stream shape keeps the perf
property (one HBM read per page, zero HBM gather) while staying inside
the oracle's rounding.

Table slots beyond a lane's length point at the scratch page (id 0) —
they stream in like any other page and mask to exact zeros, identical to
the gather path's semantics.

Runs in interpreter mode off-TPU so CPU CI exercises the same kernel
logic (the ``flash_attention.py`` convention).  Dispatch between this
kernel and the gather path is ``FTC_PAGED_ATTN`` (``ops/attention.py``);
regressions show up next to the flash numbers in ``ops/kernel_bench.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import NEG_INF, _dimension_semantics


def paged_attention_vmem_bytes(
    q_shape: tuple, pages_per_lane: int, page_tokens: int, hkv: int, itemsize: int
) -> int:
    """Worst-case VMEM residency of one grid step: the two gathered-cache
    scratch buffers plus the Q/K/V/O blocks.  The dispatch layer
    (``ops/attention.py``) compares this against ``FTC_PAGED_VMEM_MB`` so
    a long-context pool quietly falls back to the gather path instead of
    failing to fit."""
    _, s, h, d = q_shape
    m = pages_per_lane * page_tokens
    scratch = 2 * m * hkv * d * itemsize
    kv_blocks = 2 * 2 * page_tokens * hkv * d * itemsize  # double-buffered
    q_out = 2 * s * h * d * itemsize
    return scratch + kv_blocks + q_out


def _paged_kernel(
    # scalar-prefetch refs (PrefetchScalarGridSpec, num_scalar_prefetch=2)
    table_ref,  # (B, MP) int32 physical page ids
    idx_ref,  # (B,) int32 per-lane first-query position
    # tensor refs
    q_ref,  # (1, S, H, D)
    k_ref,  # (1, T, Hkv, D) — page table[ib, ip]
    v_ref,  # (1, T, Hkv, D)
    o_ref,  # (1, S, H, D)
    # VMEM scratch — the gathered logical cache, never materialised in HBM
    k_acc,  # (MP*T, Hkv, D)
    v_acc,  # (MP*T, Hkv, D)
):
    t = k_ref.shape[1]
    ib = pl.program_id(0)  # read outside pl.when: interpret lowers the
    ip = pl.program_id(1)  # when-body via lax.cond, no pallas context there
    mp = pl.num_programs(1)

    # Copy phase: stream page ``ip`` into its logical slot.  Pure copies —
    # bitwise-neutral by construction.
    k_acc[pl.ds(ip * t, t)] = k_ref[0]
    v_acc[pl.ds(ip * t, t)] = v_ref[0]

    @pl.when(ip == mp - 1)
    def _finalize():
        _, s, h, d = q_ref.shape
        m, hkv, _ = k_acc.shape
        g = h // hkv
        lane_pos = idx_ref[ib]

        # The oracle's LITERAL op sequence at batch 1 — same einsum specs,
        # same 5D shapes, same mask/softmax/cast chain.  Re-expressing the
        # math (per-head 2D dots, head-batched dots) measurably changes
        # XLA CPU's fused reduction order by 1 ulp on some shapes; issuing
        # the identical ops is what makes interpret-mode bit-identity
        # hold robustly (``chunked_cache_attention`` is itself
        # batch-size-independent, which the kernel tests re-prove).
        qh = (q_ref[0][None] * d ** -0.5).reshape(1, s, hkv, g, d)
        scores = jnp.einsum("bskgd,btkd->bkgst", qh, k_acc[...][None])
        scores = scores.astype(jnp.float32)
        qpos = lane_pos.reshape(1, 1, 1, 1, 1) + jnp.arange(s).reshape(1, 1, 1, s, 1)
        valid = jnp.arange(m).reshape(1, 1, 1, 1, -1) <= qpos
        scores = jnp.where(valid, scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1).astype(v_acc.dtype)
        out = jnp.einsum("bkgst,btkd->bskgd", probs, v_acc[...][None])
        o_ref[0] = out.reshape(s, h, d).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _paged_attention(q, k_pool, v_pool, page_table, idx, *, interpret: bool):
    b, s, h, d = q.shape
    _, t, hkv, _ = k_pool.shape
    mp = page_table.shape[1]

    grid = (b, mp)
    kv_spec = pl.BlockSpec(
        (1, t, hkv, d), lambda ib, ip, table, idx: (table[ib, ip], 0, 0, 0)
    )
    q_spec = pl.BlockSpec((1, s, h, d), lambda ib, ip, table, idx: (ib, 0, 0, 0))
    return pl.pallas_call(
        _paged_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[q_spec, kv_spec, kv_spec],
            out_specs=q_spec,
            scratch_shapes=[
                pltpu.VMEM((mp * t, hkv, d), k_pool.dtype),
                pltpu.VMEM((mp * t, hkv, d), v_pool.dtype),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, s, h, d), v_pool.dtype),
        # page slots accumulate into VMEM scratch sequentially per lane
        compiler_params=_dimension_semantics("parallel", "arbitrary"),
        interpret=interpret,
    )(page_table, idx, q, k_pool, v_pool)


def paged_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    page_table: jax.Array,
    idx: jax.Array,
    *,
    interpret: bool | None = None,
) -> jax.Array:
    """Paged-cache attention reading the pool through the page table.

    Shapes match :func:`ops.attention.paged_cache_attention`: ``q``
    (B, S, H, D); pools (P, T, Hkv, D); ``page_table`` (B, MP) int32;
    ``idx`` scalar or (B,) — the absolute position of the chunk's first
    query token.  Returns (B, S, H, D) in the pool dtype, bit-identical
    to the gather path.
    """
    if q.dtype != k_pool.dtype or q.dtype != v_pool.dtype:
        raise ValueError(
            f"paged_attention: q/k/v dtypes must match for bit-identical "
            f"storage-dtype matmuls (got {q.dtype}, {k_pool.dtype}, "
            f"{v_pool.dtype}); use the gather path for mixed dtypes"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b = q.shape[0]
    idx = jnp.broadcast_to(jnp.asarray(idx, jnp.int32).reshape(-1), (b,))
    page_table = page_table.astype(jnp.int32)
    return _paged_attention(q, k_pool, v_pool, page_table, idx, interpret=interpret)
