"""Hand-written TPU Pallas kernels for the ops where XLA's defaults lose.

Benchmark-first policy (SURVEY.md §7: 'benchmark first, hand-write second'):
each kernel here exists because it beats (or bounds the memory of) the XLA
path at the BASELINE.md shapes. Everything runs in interpreter mode on CPU so
the test suite exercises kernel logic without TPU hardware.
"""
