"""Attention kernel micro-benchmark: XLA einsum-softmax vs Pallas flash.

SURVEY.md §7 discipline — "benchmark first, hand-write second": the Pallas
kernel is only used where it measurably beats XLA's fused default. This
module provides the measurement (fwd and fwd+bwd wall time per call at a
given shape) and the dispatch gate (:func:`preferred_impl`) the model config
consults when ``attention_impl="auto"``.

Run on hardware:
    python -m finetune_controller_tpu.ops.kernel_bench [--seq 2048 ...]
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp


def _time_chained(fn, q, k, v, chain, iters: int, warmup: int = 2) -> float:
    """Average per-call seconds with a host-level data-dependency chain:
    each call's output becomes the next call's query input (``chain`` maps the
    output to a q-shaped array). Independent repeated calls through an async
    runtime (or a caching remote-TPU tunnel) can appear nearly free even
    under ``block_until_ready`` — the same failure mode the round-1 training
    bench had (VERDICT r1); a chain forces every execution onto the critical
    path, exactly like a training loop's donated state does."""
    def force(x):
        # a host fetch of a dependent scalar is the only sync that some
        # remote runtimes honour; block_until_ready alone can return with
        # the computation still pending
        return float(jnp.sum(x.astype(jnp.float32)))

    for _ in range(warmup):
        out = fn(q, k, v)
        q = chain(out, q)
    force(q)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(q, k, v)
        q = chain(out, q)
    force(q)
    return (time.perf_counter() - t0) / iters


def _make_qkv(batch, seq, heads, kv_heads, head_dim, dtype):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    return (
        jax.random.normal(kq, (batch, seq, heads, head_dim), dtype),
        jax.random.normal(kk, (batch, seq, kv_heads, head_dim), dtype),
        jax.random.normal(kv, (batch, seq, kv_heads, head_dim), dtype),
    )


def _chain_grad(grads, q_prev):
    """Fold dQ back into the next call's q, keeping magnitudes bounded so
    the chain can run indefinitely without overflowing."""
    return q_prev + grads[0].astype(q_prev.dtype) * 1e-3


def bench_attention(
    batch: int = 8,
    seq: int = 2048,
    heads: int = 32,
    kv_heads: int = 4,
    head_dim: int = 64,
    dtype=jnp.bfloat16,
    iters: int = 10,
) -> dict[str, float]:
    """Per-call seconds for each impl, forward and grad (fwd+bwd)."""
    from .attention import xla_causal_attention
    from .pallas.flash_attention import flash_attention

    q, k, v = _make_qkv(batch, seq, heads, kv_heads, head_dim, dtype)

    def loss(attn, q, k, v):
        return (attn(q, k, v).astype(jnp.float32) ** 2).mean()

    def chain_fwd(out, q_prev):
        return out

    results: dict[str, float] = {}
    for name, attn in (("xla", xla_causal_attention), ("pallas", flash_attention)):
        # ftc: ignore[recompile-jit-in-loop] -- one compile per impl IS the benchmark; each (impl, shape) runs once per process
        fwd = jax.jit(functools.partial(attn))
        # ftc: ignore[recompile-jit-in-loop] -- same: the grad path compiles once per benched impl by design
        grad = jax.jit(jax.grad(functools.partial(loss, attn), argnums=(0, 1, 2)))
        results[f"{name}_fwd_s"] = _time_chained(fwd, q, k, v, chain_fwd, iters)
        results[f"{name}_grad_s"] = _time_chained(grad, q, k, v, _chain_grad, iters)
    return results


def bench_flash_variants(
    batch: int = 2,
    seq: int = 8192,
    heads: int = 32,
    kv_heads: int = 4,
    head_dim: int = 64,
    dtype=jnp.bfloat16,
    iters: int = 8,
    exp_dtypes: tuple[str, ...] = ("float32", "bfloat16"),
    blocks: tuple[int, ...] = (512, 1024),
) -> dict[str, float]:
    """Grad-path seconds per (exp_dtype, block) flash-kernel variant.

    The long-context tuning sweep (``docs/performance.md`` knob table):
    at head-dim 64 the kernels are VPU-bound on the S² exp, so the exp
    dtype and block size are the two dials worth measuring. Keys are
    ``"{exp_dtype}-b{block}"``; ``scripts/tpu_session.py`` records this on
    real hardware and applies the winner via the ``FTC_FLASH_*`` env knobs.
    """
    from .pallas.flash_attention import flash_attention

    q, k, v = _make_qkv(batch, seq, heads, kv_heads, head_dim, dtype)

    results: dict[str, float] = {}
    for edt in exp_dtypes:
        for blk in blocks:
            def loss(q, k, v, edt=edt, blk=blk):
                o = flash_attention(
                    q, k, v, block_q=blk, block_k=blk, exp_dtype=edt)
                return (o.astype(jnp.float32) ** 2).mean()

            # ftc: ignore[recompile-jit-in-loop] -- the sweep measures one compile per (exp_dtype, block) variant on purpose
            grad = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            results[f"{edt}-b{blk}"] = _time_chained(
                grad, q, k, v, _chain_grad, iters)
    return results


def bench_paged_variants(
    batch: int = 8,
    heads: int = 32,
    kv_heads: int = 4,
    head_dim: int = 64,
    page_tokens: int = 16,
    pages_per_lane: tuple[int, ...] = (16, 64, 256),
    dtype=jnp.bfloat16,
    iters: int = 20,
) -> dict[str, float]:
    """Decode-step seconds for the paged-attention impls, gather vs kernel,
    swept over pages-per-lane (i.e. context length at fixed page size).

    The gather tax this measures: the gather path materialises a
    ``(B, MP*T, Hkv, D)`` logical cache from HBM every step, so its cost
    scales with MP even when most pages are beyond the lane's live length;
    the Pallas kernel (``ops/pallas/paged_attention.py``) reads each page
    once into VMEM scratch.  Keys are ``"{impl}-p{pages}"``; run on real
    hardware to pick ``FTC_PAGED_ATTN`` (``docs/performance.md``).
    """
    from .attention import chunked_cache_attention, paged_gather
    from .pallas.paged_attention import paged_attention

    results: dict[str, float] = {}
    for mp in pages_per_lane:
        pool_pages = batch * mp + 1
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(1), 3)
        q = jax.random.normal(
            kq, (batch, 1, heads, head_dim), dtype)
        k_pool = jax.random.normal(
            kk, (pool_pages, page_tokens, kv_heads, head_dim), dtype)
        v_pool = jax.random.normal(
            kv, (pool_pages, page_tokens, kv_heads, head_dim), dtype)
        # each lane owns a disjoint page run, like a fragmented real pool
        table = (1 + jnp.arange(batch * mp, dtype=jnp.int32)
                 ).reshape(batch, mp)
        idx = jnp.full((batch,), mp * page_tokens - 1, jnp.int32)

        def gather_step(q, k, v, table=table, idx=idx):
            return chunked_cache_attention(
                q, paged_gather(k, table), paged_gather(v, table), idx)

        def kernel_step(q, k, v, table=table, idx=idx):
            return paged_attention(q, k, v, table, idx)

        def chain(out, q_prev):
            return q_prev + out.astype(q_prev.dtype) * 1e-3

        for name, step in (("gather", gather_step), ("kernel", kernel_step)):
            # ftc: ignore[recompile-jit-in-loop] -- the sweep measures one compile per (impl, pages) variant on purpose
            fn = jax.jit(step)
            results[f"{name}-p{mp}"] = _time_chained(
                fn, q, k_pool, v_pool, chain, iters)
    return results


#: measured crossover (v5e, 2026-07-31 run of this module at the bench shape
#: b8 h32/4 d64, with the r3 kernel defaults — block 1024, bf16 exp):
#: seq 512 XLA wins the grad path (8.7 ms vs 11.4); seq 1024 Pallas wins
#: (11.1 ms vs 15.1) and the S² HBM gap only widens with length (seq 2048:
#: 21.8 ms vs 37.2). The faster r3 defaults moved the crossover down from
#: the 2026-07 block-512 measurement (then 2048). The gate stays at the
#: shortest length with direct evidence of a Pallas win.
PALLAS_MIN_SEQ = 1024


def preferred_impl(seq_len: int, backend: str | None = None) -> str:
    """Dispatch gate for ``attention_impl="auto"``."""
    backend = backend or jax.default_backend()
    if backend == "tpu" and seq_len >= PALLAS_MIN_SEQ:
        return "pallas"
    return "xla"


def main() -> None:
    import argparse
    import json

    from ..platform import assert_platform_env

    # honor JAX_PLATFORMS even where a site plugin overrides it at startup
    # (the axon-tunnel gotcha — .claude/skills/verify/SKILL.md)
    assert_platform_env()

    p = argparse.ArgumentParser(prog="ftc-kernel-bench")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, nargs="*", default=[512, 1024, 2048, 4096])
    p.add_argument("--heads", type=int, default=32)
    p.add_argument("--kv-heads", type=int, default=4)
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--flash-variants", action="store_true",
                   help="sweep the flash kernel's exp-dtype x block-size "
                        "grid instead of the impl comparison")
    p.add_argument("--paged-variants", action="store_true",
                   help="decode-step sweep of the paged-attention impls "
                        "(gather vs Pallas kernel) over pages-per-lane")
    p.add_argument("--page-tokens", type=int, default=16)
    p.add_argument("--pages-per-lane", type=int, nargs="*",
                   default=[16, 64, 256])
    args = p.parse_args()

    if args.paged_variants:
        r = bench_paged_variants(
            batch=args.batch, heads=args.heads, kv_heads=args.kv_heads,
            head_dim=args.head_dim, page_tokens=args.page_tokens,
            pages_per_lane=tuple(args.pages_per_lane), iters=args.iters,
        )
        r_ms = {k: round(v * 1e3, 3) for k, v in r.items()}
        print(json.dumps({
            "shape": f"b{args.batch} h{args.heads}/{args.kv_heads} "
                     f"d{args.head_dim} t{args.page_tokens}",
            "unit": "ms/decode-step",
            **r_ms,
        }))
        return

    if args.flash_variants:
        for seq in args.seq:
            r = bench_flash_variants(
                batch=args.batch, seq=seq, heads=args.heads,
                kv_heads=args.kv_heads, head_dim=args.head_dim,
                iters=args.iters,
            )
            r_ms = {k: round(v * 1e3, 3) for k, v in r.items()}
            print(json.dumps({
                "shape": f"b{args.batch} s{seq} h{args.heads}/"
                         f"{args.kv_heads} d{args.head_dim}",
                "unit": "ms/call (grad)",
                **r_ms,
                "winner": min(r_ms, key=r_ms.get),
            }))
        return

    for seq in args.seq:
        r = bench_attention(
            batch=args.batch, seq=seq, heads=args.heads,
            kv_heads=args.kv_heads, head_dim=args.head_dim, iters=args.iters,
        )
        r = {k: round(v * 1e3, 3) for k, v in r.items()}  # ms
        print(json.dumps({
            "shape": f"b{args.batch} s{seq} h{args.heads}/{args.kv_heads} d{args.head_dim}",
            "unit": "ms/call",
            **r,
            "winner_grad": "pallas" if r["pallas_grad_s"] < r["xla_grad_s"] else "xla",
        }))


if __name__ == "__main__":
    raise SystemExit(main())
