"""Attention kernel micro-benchmark: XLA einsum-softmax vs Pallas flash.

SURVEY.md §7 discipline — "benchmark first, hand-write second": the Pallas
kernel is only used where it measurably beats XLA's fused default. This
module provides the measurement (fwd and fwd+bwd wall time per call at a
given shape) and the dispatch gate (:func:`preferred_impl`) the model config
consults when ``attention_impl="auto"``.

Run on hardware:
    python -m finetune_controller_tpu.ops.kernel_bench [--seq 2048 ...]
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp


def _time_chained(fn, q, k, v, chain, iters: int, warmup: int = 2) -> float:
    """Average per-call seconds with a host-level data-dependency chain:
    each call's output becomes the next call's query input (``chain`` maps the
    output to a q-shaped array). Independent repeated calls through an async
    runtime (or a caching remote-TPU tunnel) can appear nearly free even
    under ``block_until_ready`` — the same failure mode the round-1 training
    bench had (VERDICT r1); a chain forces every execution onto the critical
    path, exactly like a training loop's donated state does."""
    def force(x):
        # a host fetch of a dependent scalar is the only sync that some
        # remote runtimes honour; block_until_ready alone can return with
        # the computation still pending
        return float(jnp.sum(x.astype(jnp.float32)))

    for _ in range(warmup):
        out = fn(q, k, v)
        q = chain(out, q)
    force(q)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(q, k, v)
        q = chain(out, q)
    force(q)
    return (time.perf_counter() - t0) / iters


def _make_qkv(batch, seq, heads, kv_heads, head_dim, dtype):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    return (
        jax.random.normal(kq, (batch, seq, heads, head_dim), dtype),
        jax.random.normal(kk, (batch, seq, kv_heads, head_dim), dtype),
        jax.random.normal(kv, (batch, seq, kv_heads, head_dim), dtype),
    )


def _chain_grad(grads, q_prev):
    """Fold dQ back into the next call's q, keeping magnitudes bounded so
    the chain can run indefinitely without overflowing."""
    return q_prev + grads[0].astype(q_prev.dtype) * 1e-3


def bench_attention(
    batch: int = 8,
    seq: int = 2048,
    heads: int = 32,
    kv_heads: int = 4,
    head_dim: int = 64,
    dtype=jnp.bfloat16,
    iters: int = 10,
) -> dict[str, float]:
    """Per-call seconds for each impl, forward and grad (fwd+bwd)."""
    from .attention import xla_causal_attention
    from .pallas.flash_attention import flash_attention

    q, k, v = _make_qkv(batch, seq, heads, kv_heads, head_dim, dtype)

    def loss(attn, q, k, v):
        return (attn(q, k, v).astype(jnp.float32) ** 2).mean()

    def chain_fwd(out, q_prev):
        return out

    results: dict[str, float] = {}
    for name, attn in (("xla", xla_causal_attention), ("pallas", flash_attention)):
        # ftc: ignore[recompile-jit-in-loop] -- one compile per impl IS the benchmark; each (impl, shape) runs once per process
        fwd = jax.jit(functools.partial(attn))
        # ftc: ignore[recompile-jit-in-loop] -- same: the grad path compiles once per benched impl by design
        grad = jax.jit(jax.grad(functools.partial(loss, attn), argnums=(0, 1, 2)))
        results[f"{name}_fwd_s"] = _time_chained(fwd, q, k, v, chain_fwd, iters)
        results[f"{name}_grad_s"] = _time_chained(grad, q, k, v, _chain_grad, iters)
    return results


def bench_flash_variants(
    batch: int = 2,
    seq: int = 8192,
    heads: int = 32,
    kv_heads: int = 4,
    head_dim: int = 64,
    dtype=jnp.bfloat16,
    iters: int = 8,
    exp_dtypes: tuple[str, ...] = ("float32", "bfloat16"),
    blocks: tuple[int, ...] = (512, 1024),
) -> dict[str, float]:
    """Grad-path seconds per (exp_dtype, block) flash-kernel variant.

    The long-context tuning sweep (``docs/performance.md`` knob table):
    at head-dim 64 the kernels are VPU-bound on the S² exp, so the exp
    dtype and block size are the two dials worth measuring. Keys are
    ``"{exp_dtype}-b{block}"``; ``scripts/tpu_session.py`` records this on
    real hardware and applies the winner via the ``FTC_FLASH_*`` env knobs.
    """
    from .pallas.flash_attention import flash_attention

    q, k, v = _make_qkv(batch, seq, heads, kv_heads, head_dim, dtype)

    results: dict[str, float] = {}
    for edt in exp_dtypes:
        for blk in blocks:
            def loss(q, k, v, edt=edt, blk=blk):
                o = flash_attention(
                    q, k, v, block_q=blk, block_k=blk, exp_dtype=edt)
                return (o.astype(jnp.float32) ** 2).mean()

            # ftc: ignore[recompile-jit-in-loop] -- the sweep measures one compile per (exp_dtype, block) variant on purpose
            grad = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            results[f"{edt}-b{blk}"] = _time_chained(
                grad, q, k, v, _chain_grad, iters)
    return results


#: measured crossover (v5e, 2026-07-31 run of this module at the bench shape
#: b8 h32/4 d64, with the r3 kernel defaults — block 1024, bf16 exp):
#: seq 512 XLA wins the grad path (8.7 ms vs 11.4); seq 1024 Pallas wins
#: (11.1 ms vs 15.1) and the S² HBM gap only widens with length (seq 2048:
#: 21.8 ms vs 37.2). The faster r3 defaults moved the crossover down from
#: the 2026-07 block-512 measurement (then 2048). The gate stays at the
#: shortest length with direct evidence of a Pallas win.
PALLAS_MIN_SEQ = 1024


def preferred_impl(seq_len: int, backend: str | None = None) -> str:
    """Dispatch gate for ``attention_impl="auto"``."""
    backend = backend or jax.default_backend()
    if backend == "tpu" and seq_len >= PALLAS_MIN_SEQ:
        return "pallas"
    return "xla"


def main() -> None:
    import argparse
    import json

    from ..platform import assert_platform_env

    # honor JAX_PLATFORMS even where a site plugin overrides it at startup
    # (the axon-tunnel gotcha — .claude/skills/verify/SKILL.md)
    assert_platform_env()

    p = argparse.ArgumentParser(prog="ftc-kernel-bench")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, nargs="*", default=[512, 1024, 2048, 4096])
    p.add_argument("--heads", type=int, default=32)
    p.add_argument("--kv-heads", type=int, default=4)
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--flash-variants", action="store_true",
                   help="sweep the flash kernel's exp-dtype x block-size "
                        "grid instead of the impl comparison")
    args = p.parse_args()

    if args.flash_variants:
        for seq in args.seq:
            r = bench_flash_variants(
                batch=args.batch, seq=seq, heads=args.heads,
                kv_heads=args.kv_heads, head_dim=args.head_dim,
                iters=args.iters,
            )
            r_ms = {k: round(v * 1e3, 3) for k, v in r.items()}
            print(json.dumps({
                "shape": f"b{args.batch} s{seq} h{args.heads}/"
                         f"{args.kv_heads} d{args.head_dim}",
                "unit": "ms/call (grad)",
                **r_ms,
                "winner": min(r_ms, key=r_ms.get),
            }))
        return

    for seq in args.seq:
        r = bench_attention(
            batch=args.batch, seq=seq, heads=args.heads,
            kv_heads=args.kv_heads, head_dim=args.head_dim, iters=args.iters,
        )
        r = {k: round(v * 1e3, 3) for k, v in r.items()}  # ms
        print(json.dumps({
            "shape": f"b{args.batch} s{seq} h{args.heads}/{args.kv_heads} d{args.head_dim}",
            "unit": "ms/call",
            **r,
            "winner_grad": "pallas" if r["pallas_grad_s"] < r["xla_grad_s"] else "xla",
        }))


if __name__ == "__main__":
    raise SystemExit(main())
