"""Cross-process serve transport (docs/serving.md §Cross-process transport).

The distribution half of ROADMAP item 2: every serve replica moves into its
OWN worker process — one engine+batcher per process, its own JAX runtime —
behind a length-prefixed msgpack/JSON RPC protocol on a local socket, so the
fleet's replicas stop sharing cores with each other and with the control
plane.  The fleet/router layer (``serve/fleet.py``/``serve/router.py``) is
transport-agnostic: a :class:`~finetune_controller_tpu.transport.client.
RemoteReplica` implements the same surface the in-process ``Batcher``
exposes, so failover, exactly-once request ids, drain, rollover, DRR tenancy
and autoscale work unchanged in either mode (``serve_transport=inproc`` |
``process``).

Module map:

* ``wire``     — framing + codec (u32 length prefix, msgpack when available,
  JSON with base64 bytes otherwise) and the byte counters ``/metrics`` reads;
* ``worker``   — the worker process entrypoint
  (``python -m finetune_controller_tpu.transport.worker --spec …``);
* ``client``   — ``RemoteReplica``: async-socket RPC client with cached
  health snapshots, heartbeat lease checks and process teardown;
* ``process``  — ``ProcessTransport``: spawn/kill of worker sandboxes on the
  local host (the k8s backend renders one pod per replica instead);
* ``builders`` — how a worker process reconstructs its serving payload
  (a staged deploy dir, or the deterministic tiny test model).

Podracer-shape rollout actors (ROADMAP item 4) and MPMD pipeline stages
(item 5) are the next consumers of this same point-to-point transport.
"""

from __future__ import annotations

from typing import Any

#: process-wide transport counters (rendered as ``ftc_serve_transport_*`` by
#: the server's /metrics handler, docs/observability.md) — one flat dict like
#: the obs hub's process counters, shared by client, wire and fleet layers
METRICS: dict[str, int] = {
    "rpcs_total": 0,
    "rpc_errors_total": 0,
    "worker_respawns_total": 0,
    "workers_spawned_total": 0,
    "bytes_sent_total": 0,
    "bytes_received_total": 0,
}


def incr(name: str, n: int = 1) -> None:
    METRICS[name] = METRICS.get(name, 0) + n


def metrics_snapshot() -> dict[str, int]:
    snap = dict(METRICS)
    snap["bytes_total"] = (
        snap.get("bytes_sent_total", 0) + snap.get("bytes_received_total", 0)
    )
    return snap


class TransportError(RuntimeError):
    """The worker process or its socket failed — retryable from the fleet's
    point of view (the router never sees this type: ``RemoteReplica`` maps it
    to :class:`~finetune_controller_tpu.serve.batcher.ReplicaUnavailable` so
    the failover path is byte-for-byte the in-process one)."""


class RemoteError(RuntimeError):
    """An exception raised INSIDE the worker, re-raised here with its remote
    type preserved in the message (``SomeError: detail``) so
    ``resilience.policy.classify_failure`` has the same text a local raise
    would produce."""

    def __init__(self, remote_type: str, message: str):
        super().__init__(f"{remote_type}: {message}")
        self.remote_type = remote_type


def transport_stats() -> dict[str, Any]:
    """Back-compat alias used by admin surfaces."""
    return metrics_snapshot()
