"""``RemoteReplica``: the fleet-side client for one serve worker process.

Implements the surface :class:`~finetune_controller_tpu.serve.fleet.
ReplicaFleet` and :class:`~finetune_controller_tpu.serve.router.
ReplicaRouter` consume from an in-process ``Batcher`` — submit with absolute
deadline, drain, close, health probe, per-tenant counts, stats — over one
multiplexed async-socket connection (``transport/wire.py``), so the
fleet/router layer cannot tell a worker process from an in-process replica
(docs/serving.md §Cross-process transport).

The three liveness layers, cheapest first:

1. **process exit**: a reaped worker (``poll()`` returns) fails the probe
   immediately with its exit code — a SIGKILL is ``-9`` the same tick;
2. **heartbeat lease**: the worker beats ``heartbeat.json`` into its sandbox
   (``resilience/heartbeat.py``); a process that is alive but wedged (stuck
   event loop, hung runtime) goes stale past ``3×`` the beat cadence and
   fails the probe — the fleet then KILLS it, the LeaseChecker pattern;
3. **probe RPC**: the decode-progress snapshot that feeds the fleet's
   stalled-decode check — a worker whose loop answers but whose engine stops
   stepping while holding lanes is caught exactly like an in-process stall.

Any transport failure on the generate path surfaces as
:class:`~finetune_controller_tpu.serve.batcher.ReplicaUnavailable` — the
router's failover re-enqueues on a survivor, and exactly-once holds because
a dead worker never delivered a result for the request (and the worker-side
completed-LRU replays, never re-decodes, if the same id lands on it again).

All waits are a real async socket or ``asyncio.to_thread`` (ftc-lint's
blocking-io-in-async rule gates this file); sync properties the router reads
between awaits come from the last probe snapshot.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import signal
import subprocess
import time
from typing import Any

from ..resilience.heartbeat import read_heartbeat_file
from ..serve.adapters import AdapterError, UnknownAdapter
from ..serve.batcher import DeadlineExceeded, QueueFull, ReplicaUnavailable
from ..serve.engine import GenRequest, GenResult, PromptTooLong
from . import RemoteError, TransportError, incr
from .wire import FrameError, read_msg, write_msg

logger = logging.getLogger(__name__)

#: remote exception types re-raised as their local counterparts (everything
#: else becomes :class:`RemoteError` with the remote type in the message)
_ERROR_TYPES: dict[str, type[BaseException]] = {
    "QueueFull": QueueFull,
    "DeadlineExceeded": DeadlineExceeded,
    "ReplicaUnavailable": ReplicaUnavailable,
    "PromptTooLong": PromptTooLong,
    "UnknownAdapter": UnknownAdapter,
    "AdapterError": AdapterError,
    "ValueError": ValueError,
}


def _raise_remote(error: dict[str, Any]) -> None:
    etype = str(error.get("type", "RuntimeError"))
    message = str(error.get("message", ""))
    cls = _ERROR_TYPES.get(etype)
    if cls is QueueFull:
        raise QueueFull(message, retry_after_s=error.get("retry_after_s"))
    if cls is not None:
        raise cls(message)
    raise RemoteError(etype, message)


class _Connection:
    """One multiplexed request/response connection to a worker."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self._reader = reader
        self._writer = writer
        self._ids = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._write_lock = asyncio.Lock()
        self._dead: BaseException | None = None
        self._reader_task = asyncio.get_running_loop().create_task(
            self._read_loop()
        )

    @classmethod
    async def open(cls, host: str, port: int,
                   timeout_s: float = 10.0) -> "_Connection":
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), timeout_s
        )
        return cls(reader, writer)

    @property
    def alive(self) -> bool:
        return self._dead is None

    async def _read_loop(self) -> None:
        exc: BaseException
        try:
            while True:
                msg = await read_msg(self._reader)
                future = self._pending.pop(msg.get("id"), None)
                if future is None or future.done():
                    continue
                if msg.get("ok"):
                    future.set_result(msg.get("payload"))
                else:
                    try:
                        _raise_remote(msg.get("error") or {})
                    # ftc: ignore[silent-except] -- not swallowed: delivered to the awaiting RPC caller
                    except BaseException as e:
                        future.set_exception(e)
        except (asyncio.IncompleteReadError, ConnectionError, FrameError,
                asyncio.CancelledError) as e:
            exc = e if not isinstance(e, asyncio.CancelledError) \
                else TransportError("connection closed")
        # ftc: ignore[silent-except] -- converted below: every pending caller receives the failure
        except Exception as e:
            exc = e
        else:  # pragma: no cover - while True only leaves via exception
            exc = TransportError("connection closed")
        self.fail_pending(TransportError(f"worker connection lost: {exc!r}"))

    def fail_pending(self, exc: BaseException) -> None:
        if self._dead is None:
            self._dead = exc
        for future in list(self._pending.values()):
            if not future.done():
                future.set_exception(exc)
        self._pending.clear()

    async def call(self, op: str, payload: dict[str, Any],
                   timeout_s: float | None = None) -> Any:
        if self._dead is not None:
            incr("rpc_errors_total")
            raise TransportError(f"connection is down: {self._dead}")
        msg_id = next(self._ids)
        future = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = future
        incr("rpcs_total")
        try:
            async with self._write_lock:
                await write_msg(
                    self._writer, {"op": op, "id": msg_id, "payload": payload}
                )
            if timeout_s is None:
                return await future
            return await asyncio.wait_for(asyncio.shield(future), timeout_s)
        except asyncio.TimeoutError:
            incr("rpc_errors_total")
            self._pending.pop(msg_id, None)
            raise TransportError(
                f"rpc {op!r} timed out after {timeout_s:.1f}s"
            ) from None
        except (ConnectionError, TransportError, FrameError) as e:
            incr("rpc_errors_total")
            self._pending.pop(msg_id, None)
            raise TransportError(f"rpc {op!r} failed: {e}") from e
        finally:
            self._pending.pop(msg_id, None)

    async def close(self) -> None:
        self._reader_task.cancel()
        try:
            await self._reader_task
        except asyncio.CancelledError:
            pass
        self._writer.close()
        try:
            await self._writer.wait_closed()
        # ftc: ignore[silent-except] -- best-effort close of an already-dead socket
        except Exception:
            pass


class _RemoteEngineView:
    """The tiny engine-shaped slice the router/fleet read between awaits —
    decode progress and paged-pool slack from the last probe, admission page
    math recomputed locally from the worker's hello config."""

    def __init__(self, replica: "RemoteReplica"):
        self._replica = replica

    @property
    def steps_total(self) -> int:
        return int(self._replica.probe_snapshot.get("steps_total", 0))

    def kv_slack_pages(self) -> int | None:
        return self._replica.probe_snapshot.get("kv_slack_pages")

    def admission_pages(self, req: GenRequest) -> int:
        page_tokens = int(self._replica.engine_info.get("page_tokens") or 0)
        if page_tokens <= 0:
            return 0
        span = len(req.tokens) + max(0, req.max_new_tokens - 1)
        return -(-span // page_tokens)


class RemoteReplica:
    """Batcher-shaped client for one worker process."""

    def __init__(
        self,
        replica_id: str,
        conn: _Connection,
        hello: dict[str, Any],
        *,
        proc: subprocess.Popen | None = None,
        sandbox: str | None = None,
        heartbeat_interval_s: float = 2.0,
        probe_timeout_s: float = 10.0,
        log_path: str | None = None,
    ):
        self.replica_id = replica_id
        self._conn = conn
        self._proc = proc
        self.sandbox = sandbox
        self.log_path = log_path
        self.pid = int(hello.get("pid") or (proc.pid if proc else 0))
        self.port: int | None = None
        self.engine_info: dict[str, Any] = dict(hello.get("engine") or {})
        self.heartbeat_interval_s = heartbeat_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.engine = _RemoteEngineView(self)
        self._draining = False
        self._closed = False
        #: last probe snapshot — the sync-property source for the router's
        #: between-awaits reads (load, queue depth, retry-after)
        self.probe_snapshot: dict[str, Any] = {}
        self._stats: dict[str, Any] = {}

    # ---- liveness ----------------------------------------------------------

    def _proc_exit(self) -> int | None:
        if self._proc is None:
            return None
        return self._proc.poll()

    @property
    def lease_s(self) -> float:
        """Heartbeat staleness budget: 3 beats, floored — mirrors the
        trainer-side LeaseChecker floor so one slow write never kills a
        healthy worker."""
        return max(3.0 * self.heartbeat_interval_s, 5.0)

    async def _check_heartbeat(self) -> None:
        if self.sandbox is None:
            return
        hb = await asyncio.to_thread(
            read_heartbeat_file, os.path.join(self.sandbox, "heartbeat.json")
        )
        if hb is None:
            return  # never beat / unreadable: the lease does not bind
        age = time.time() - float(hb["ts"])
        if age > self.lease_s:
            raise TransportError(
                f"worker {self.replica_id} heartbeat is {age:.1f}s stale "
                f"(lease {self.lease_s:.1f}s) — wedged process"
            )

    async def health_probe(self) -> dict[str, Any]:
        """The fleet's liveness + decode-progress check (one per tick)."""
        code = self._proc_exit()
        if code is not None:
            raise TransportError(
                f"worker {self.replica_id} process exited with code {code}"
                + (" (SIGKILL)" if code == -int(signal.SIGKILL) else "")
            )
        await self._check_heartbeat()
        probe = await self._conn.call("probe", {},
                                      timeout_s=self.probe_timeout_s)
        self.probe_snapshot = probe
        self._stats = probe.get("stats") or self._stats
        return probe

    # ---- generate path -----------------------------------------------------

    async def submit(
        self,
        req: GenRequest,
        *,
        timeout_s: float | None = None,
        deadline: float | None = None,
    ) -> GenResult:
        if self._draining:
            raise ReplicaUnavailable(
                f"worker {self.replica_id} is draining"
            )
        if self._closed or not self._conn.alive:
            raise ReplicaUnavailable(
                f"worker {self.replica_id} connection is down"
            )
        payload: dict[str, Any] = {
            "request_id": req.request_id,
            "tokens": [int(t) for t in req.tokens],
            "max_new_tokens": req.max_new_tokens,
            "temperature": req.temperature,
            "top_k": req.top_k,
            "eos_id": req.eos_id,
            "seed": req.seed,
            "adapter_id": req.adapter_id,
            "timeout_s": timeout_s,
        }
        rpc_timeout = None
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise DeadlineExceeded(
                    f"request {req.request_id} arrived past its deadline"
                )
            # ship the REMAINING budget: monotonic clocks are per-process
            payload["deadline_in_s"] = remaining
            # the worker enforces the deadline itself; the rpc timeout is a
            # backstop for a worker that dies without dropping the socket
            rpc_timeout = remaining + 30.0
        try:
            doc = await self._conn.call("generate", payload,
                                        timeout_s=rpc_timeout)
        except TransportError as e:
            # the worker died (or the socket did) with the request on it: it
            # never delivered a result, so the router may re-enqueue safely
            raise ReplicaUnavailable(
                f"worker {self.replica_id} lost mid-request: {e}"
            ) from e
        return GenResult(
            request_id=doc["request_id"],
            prompt_tokens=list(doc["prompt_tokens"]),
            generated=list(doc["generated"]),
            finish_reason=doc["finish_reason"],
            steps=int(doc["steps"]),
            admitted_at=float(doc.get("admitted_at", 0.0)),
            finished_at=float(doc.get("finished_at", 0.0)),
            replica_id=self.replica_id,
        )

    # ---- drain / close -----------------------------------------------------

    async def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful removal: the worker bounces queued requests, finishes
        in-flight lanes, exits 0; then the process is reaped (killed if it
        lingers)."""
        self._draining = True
        clean = False
        try:
            out = await self._conn.call(
                "drain", {"timeout_s": timeout_s}, timeout_s=timeout_s + 30.0
            )
            clean = bool(out.get("clean"))
            # the worker's FINAL totals: everything completed since the
            # last probe (the drain window included) must survive into the
            # fleet's retired-counter fold
            self._stats = out.get("stats") or self._stats
        except TransportError as e:
            logger.warning("drain rpc to worker %s failed: %s",
                           self.replica_id, e)
        proc = self._proc
        if proc is not None:
            # a cleanly drained worker exits 0 by itself right after the
            # reply; wait for that before close() escalates to SIGTERM
            def wait_exit() -> None:
                try:
                    proc.wait(timeout=10.0)
                except (subprocess.TimeoutExpired, OSError):
                    logger.debug("worker %s lingered past drain",
                                 self.replica_id)

            await asyncio.to_thread(wait_exit)
        await self.close(ReplicaUnavailable(
            f"worker {self.replica_id} drained away"
        ), grace_s=5.0)
        return clean

    async def _reap(self, grace_s: float) -> None:
        proc = self._proc
        if proc is None or proc.poll() is not None:
            return

        def stop() -> None:
            try:
                proc.terminate()
                try:
                    proc.wait(timeout=grace_s)
                    return
                except subprocess.TimeoutExpired:
                    pass
                proc.kill()
                proc.wait(timeout=5.0)
            except (ProcessLookupError, subprocess.TimeoutExpired, OSError):
                logger.debug("worker %s reap raced its exit",
                             self.replica_id, exc_info=True)

        await asyncio.to_thread(stop)

    async def close(self, exc: BaseException | None = None,
                    *, grace_s: float = 2.0) -> None:
        """Tear down: outstanding RPCs fail with ``exc`` (fleet teardown
        passes :class:`ReplicaUnavailable` so the router fails them over),
        the connection closes, the process is terminated and reaped."""
        if self._closed:
            return
        self._closed = True
        self._conn.fail_pending(
            exc if exc is not None
            else ReplicaUnavailable(f"worker {self.replica_id} closed")
        )
        await self._conn.close()
        await self._reap(grace_s)

    # ---- adapter sync (registry-sync RPCs) ---------------------------------

    async def adapter_register(self, entry_wire: dict[str, Any],
                               *, refresh: bool = False) -> int:
        out = await self._conn.call(
            "adapter_register", {**entry_wire, "refresh": refresh},
            timeout_s=120.0,
        )
        return int(out["slot"])

    async def adapter_unregister(self, adapter_id: str) -> None:
        await self._conn.call(
            "adapter_unregister", {"adapter_id": adapter_id}, timeout_s=60.0
        )

    async def stack_sync(self, entries: list[dict[str, Any]]) -> None:
        if not entries:
            return
        await self._conn.call(
            "stack_sync", {"entries": entries}, timeout_s=300.0
        )

    async def tenant_busy(self, adapter_id: str) -> int:
        out = await self._conn.call(
            "tenant_busy", {"adapter_id": adapter_id},
            timeout_s=self.probe_timeout_s,
        )
        return int(out.get("busy", 0))

    # ---- rollout streaming (docs/preference.md §Disaggregated rollouts) ----
    # The canonical call sites for the rollout/reward op family: every other
    # caller (prefs/rollout_plane.py) routes through these methods, keeping
    # the rpc-conformance lint's client table inside transport/.

    async def rollout_start(self, pairs_per_round: int) -> dict[str, Any]:
        """Start (idempotently) the worker's pair-producer loop."""
        return await self._conn.call(
            "rollout_start", {"pairs_per_round": int(pairs_per_round)},
            timeout_s=120.0,
        )

    async def rollout_pull(self, after_seq: int,
                           max_rounds: int = 8) -> dict[str, Any]:
        """Rounds with ``seq > after_seq`` — idempotent cursor read."""
        return await self._conn.call(
            "rollout_pull",
            {"after_seq": int(after_seq), "max_rounds": int(max_rounds)},
            timeout_s=self.probe_timeout_s + 60.0,
        )

    async def rollout_ack(self, up_to_seq: int) -> dict[str, Any]:
        """Trim the worker's outbox through ``up_to_seq``."""
        return await self._conn.call(
            "rollout_ack", {"up_to_seq": int(up_to_seq)}, timeout_s=60.0
        )

    async def rollout_policy_version(self, version: int,
                                     tree_blob: bytes | None) -> dict[str, Any]:
        """Ship an adapter delta (flax-msgpack blob) as the new policy —
        the fleet-rollover push; idempotent and monotonic worker-side."""
        return await self._conn.call(
            "rollout_policy_version",
            {"version": int(version), "tree": tree_blob},
            timeout_s=300.0,
        )

    async def reward_score(
        self, items: list[dict[str, Any]]
    ) -> list[float]:
        """Batched scalar scoring of (prompt, completion) items."""
        out = await self._conn.call(
            "reward_score", {"items": items}, timeout_s=300.0
        )
        return [float(s) for s in out.get("scores") or []]

    # ---- batcher-shaped sync surface (last-probe snapshots) ----------------

    @property
    def queue_depth(self) -> int:
        return int(self.probe_snapshot.get("queue_depth", 0))

    @property
    def slots_busy(self) -> int:
        return int(self.probe_snapshot.get("slots_busy", 0))

    @property
    def step_errors_total(self) -> int:
        return int(self.probe_snapshot.get("step_errors_total", 0))

    @property
    def last_step_error(self) -> str | None:
        return self.probe_snapshot.get("last_step_error")

    @property
    def draining(self) -> bool:
        return self._draining

    def retry_after_s(self, extra_requests: int = 1) -> float:
        return float(self.probe_snapshot.get("retry_after_s", 1.0))

    def queue_depth_by_tenant(self) -> dict[str, int]:
        return dict(self._stats.get("queue_depth_by_tenant") or {})

    def inflight_by_tenant(self) -> dict[str, int]:
        return dict(self.probe_snapshot.get("inflight_by_tenant") or {})

    def stats(self) -> dict[str, Any]:
        out = dict(self._stats)
        out.setdefault("queue_depth", self.queue_depth)
        out.setdefault("slots_busy", self.slots_busy)
        out["transport"] = "process"
        out["pid"] = self.pid
        return out


class RewardClient:
    """Synchronous facade over the ``reward_score`` RPC for callers that live
    on a plain thread — the rollout worker's producer loop scores each round
    from inside its (non-async) generate path.  Owns a private event loop on
    a daemon thread plus one :class:`_Connection`; every :meth:`score` is a
    thread-safe round trip onto that loop."""

    def __init__(self, host: str, port: int, *, timeout_s: float = 300.0):
        import threading

        self._host = host
        self._port = int(port)
        self._timeout_s = timeout_s
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, name="ftc-reward-client",
            daemon=True,
        )
        self._thread.start()
        self._conn: _Connection = self._run(
            _Connection.open(self._host, self._port)
        )

    def _run(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self._loop).result(
            self._timeout_s + 30.0
        )

    def score(self, items: list[dict[str, Any]]) -> list[float]:
        """Score a batch of ``{"prompt": [...], "completion": [...]}`` items."""

        async def _call() -> list[float]:
            out = await self._conn.call(
                "reward_score", {"items": items}, timeout_s=self._timeout_s
            )
            return [float(s) for s in out.get("scores") or []]

        return self._run(_call())

    def batch_reward_fn(self):
        """Adapter for :class:`~..prefs.actor.RolloutActor`'s
        ``batch_reward_fn`` signature (list of (prompt, completion) tuples)."""

        def fn(pairs: list[tuple[list[int], list[int]]]) -> list[float]:
            return self.score([
                {"prompt": [int(t) for t in p],
                 "completion": [int(t) for t in c]}
                for p, c in pairs
            ])

        return fn

    def close(self) -> None:
        try:
            self._run(self._conn.close())
        # ftc: ignore[silent-except] -- best-effort teardown of a dead socket
        except Exception:
            pass
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5.0)
