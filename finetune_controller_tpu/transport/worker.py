"""Serve worker process: one engine + batcher, its own JAX runtime, one
socket (docs/serving.md §Cross-process transport).

Entrypoint::

    python -m finetune_controller_tpu.transport.worker --spec <spec.json>

The spec (written by :class:`~finetune_controller_tpu.transport.process.
ProcessTransport` into the worker's sandbox) names the payload builder, the
engine/batcher/adapter configuration and the socket to bind.  Startup order
matters and is part of the contract:

1. build the payload (``transport/builders.py``) and a WARM engine — every
   prefill-bucket + decode compile paid before traffic, exactly the
   in-process fleet's warm-start (``serve/engine.py::warm_engine``);
2. arm the seeded chaos hand (``FTC_FAULT_SERVE_*`` forwarded into the spawn
   env) with ``hard_kill=True``: a ``kill``-mode fault SIGKILLs the real
   process, a ``stall`` wedges the real decode loop — the fleet's detection
   paths are exercised against genuine process death, not a stand-in;
3. bind ``127.0.0.1:<port>`` (port 0 = ephemeral) and atomically write
   ``transport.json`` (bound port + pid) — the parent's spawn handshake
   polls for this file;
4. start the heartbeat: ``resilience/heartbeat.py::HeartbeatWriter`` beats
   ``engine.steps_total`` into the sandbox on a cadence — a SIGKILLed or
   event-loop-wedged worker stops beating, and the client's lease check
   catches it even when the socket half-lives.

RPCs (one length-prefixed frame per message, concurrent requests multiplexed
by id over one connection):

``hello``, ``probe`` (health/decode-progress + full stats snapshot),
``generate`` (absolute-deadline + idempotent request id: duplicates attach
in flight and replay from a bounded LRU after), ``drain`` (graceful: bounce
queued, finish in-flight, then exit 0), ``tenant_busy``,
``adapter_register`` / ``adapter_unregister`` / ``stack_sync`` (the
registry-sync RPCs — flax-msgpack adapter deltas, megabytes, never base
weights; a re-register with ``refresh`` drops the tenant's prefix
namespace worker-side, so no separate drop op exists).  A rollout tenant
(``spec.rollout``) adds the idempotent streaming ops ``rollout_start`` /
``rollout_pull`` / ``rollout_ack`` / ``rollout_policy_version``, and a
reward tenant (``spec.reward``) adds the batched ``reward_score``
(docs/preference.md §Disaggregated rollouts).  The op table is verified
against the client's call sites by ftc-lint's ``rpc-conformance`` rule —
it deleted two dead ops (``shutdown``, ``drop_namespace``) on landing,
and a handler/client rename turns the lint red (mutation-tested).

Engine work (prefill/step/adapter installs) always runs in worker threads so
the RPC loop stays responsive — probes answer mid-compile.
"""

from __future__ import annotations

import argparse
import asyncio
import collections
import dataclasses
import json
import logging
import os
import sys
import time
from typing import Any

logger = logging.getLogger("ftc.transport.worker")

TRANSPORT_FILENAME = "transport.json"

#: completed-result replay cache (requests already answered on this worker):
#: the wire-level half of the exactly-once contract — a duplicate generate
#: for a completed id replays the result without touching the engine
COMPLETED_CACHE = 512


@dataclasses.dataclass
class WorkerSpec:
    """The parsed ``--spec`` document."""

    job_id: str
    replica_id: str
    sandbox: str
    builder: str
    builder_kwargs: dict[str, Any]
    engine: dict[str, Any]
    batcher: dict[str, Any]
    adapters: dict[str, Any] | None = None
    #: rollout-tenant section: the worker runs a RolloutService (an actor
    #: streaming scored preference pairs) instead of a request batcher
    rollout: dict[str, Any] | None = None
    #: reward-tenant section: attach a RewardScorer over the built payload
    #: (``{"artifacts_dir": ...}`` names the reward job's export)
    reward: dict[str, Any] | None = None
    host: str = "127.0.0.1"
    port: int = 0
    heartbeat_interval_s: float = 2.0
    warm_start: bool = True

    @classmethod
    def load(cls, path: str) -> "WorkerSpec":
        with open(path) as f:
            doc = json.load(f)
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in doc.items() if k in fields})


def _error_doc(exc: BaseException) -> dict[str, Any]:
    doc: dict[str, Any] = {
        "type": type(exc).__name__,
        "message": str(exc),
    }
    retry_after = getattr(exc, "retry_after_s", None)
    if retry_after is not None:
        doc["retry_after_s"] = retry_after
    return doc


def _result_doc(result) -> dict[str, Any]:
    return {
        "request_id": result.request_id,
        "prompt_tokens": [int(t) for t in result.prompt_tokens],
        "generated": [int(t) for t in result.generated],
        "finish_reason": result.finish_reason,
        "steps": int(result.steps),
        "admitted_at": float(result.admitted_at),
        "finished_at": float(result.finished_at),
    }


class WorkerServer:
    """The RPC surface over one ``(engine, batcher)`` pair.

    Built either by :func:`main` (a real worker process) or directly by
    tests, which run it in-process against a loopback socket to exercise the
    protocol without paying a process spawn.
    """

    def __init__(self, spec: WorkerSpec, engine, batcher, registry=None,
                 *, exit_on_drain: bool = True):
        self.spec = spec
        self.engine = engine
        self.batcher = batcher
        self.registry = registry
        #: rollout tenant only (``spec.rollout``): the streaming pair service
        self.rollout = None
        #: reward tenant only (``spec.reward``): the batched pair scorer
        self.reward_scorer = None
        self.exit_on_drain = exit_on_drain
        self._server: asyncio.base_events.Server | None = None
        self.port: int | None = None
        self._exit_requested = asyncio.Event()
        self.exit_code = 0
        #: request_id -> future of the in-flight attempt (duplicates attach)
        self._inflight: dict[str, asyncio.Future] = {}
        #: request_id -> result doc (bounded LRU replay)
        self._completed: collections.OrderedDict[str, dict] = (
            collections.OrderedDict()
        )
        self._hb_task: asyncio.Task | None = None
        self._hb_writer = None
        self.rpcs_total = 0

    # ---- lifecycle ---------------------------------------------------------

    async def start(self) -> int:
        """Bind the socket; returns the bound port."""
        self._server = await asyncio.start_server(
            self._handle_conn, self.spec.host, self.spec.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    def start_heartbeat(self) -> None:
        from ..resilience.heartbeat import HeartbeatWriter

        self._hb_writer = HeartbeatWriter(
            self.spec.sandbox, interval_s=0.0,  # cadence is ours, not the writer's
        )
        self._hb_writer.beat(self.engine.steps_total, force=True)

        async def beat_loop():
            while not self._exit_requested.is_set():
                await asyncio.sleep(max(0.1, self.spec.heartbeat_interval_s))
                await asyncio.to_thread(
                    self._hb_writer.beat, self.engine.steps_total, force=True
                )

        self._hb_task = asyncio.get_running_loop().create_task(beat_loop())

    async def serve_until_exit(self) -> int:
        await self._exit_requested.wait()
        await self.stop()
        return self.exit_code

    async def stop(self) -> None:
        """Tear down socket + heartbeat + batcher (tests drive this directly;
        the worker process goes through :meth:`serve_until_exit`)."""
        if self._hb_task is not None:
            self._hb_task.cancel()
            self._hb_task = None
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        await self.batcher.close()

    def request_exit(self, code: int = 0) -> None:
        self.exit_code = code
        self._exit_requested.set()

    # ---- connection loop ---------------------------------------------------

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        from .wire import FrameError, read_msg, write_msg

        lock = asyncio.Lock()  # one response frame at a time per connection
        tasks: set[asyncio.Task] = set()

        async def respond(doc: dict) -> None:
            async with lock:
                try:
                    await write_msg(writer, doc)
                except (ConnectionError, RuntimeError):
                    logger.debug("response write failed (client gone)")

        async def run_one(msg: dict) -> None:
            msg_id = msg.get("id")
            try:
                payload = await self._dispatch(
                    str(msg.get("op", "")), msg.get("payload") or {}
                )
                await respond({"id": msg_id, "ok": True, "payload": payload})
            # ftc: ignore[silent-except] -- not swallowed: marshalled to the caller as a typed wire error
            except BaseException as exc:
                await respond(
                    {"id": msg_id, "ok": False, "error": _error_doc(exc)}
                )

        try:
            while True:
                try:
                    msg = await read_msg(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                except FrameError:
                    logger.warning("torn frame; dropping connection")
                    break
                task = asyncio.get_running_loop().create_task(run_one(msg))
                tasks.add(task)
                task.add_done_callback(tasks.discard)
        finally:
            for t in tasks:
                t.cancel()
            writer.close()
            try:
                await writer.wait_closed()
            # ftc: ignore[silent-except] -- best-effort socket close on a connection already torn down
            except Exception:
                pass

    # ---- dispatch ----------------------------------------------------------

    async def _dispatch(self, op: str, payload: dict[str, Any]) -> Any:
        self.rpcs_total += 1
        handler = getattr(self, f"_op_{op}", None)
        if handler is None:
            raise ValueError(f"unknown transport op {op!r}")
        return await handler(payload)

    async def _op_hello(self, payload: dict) -> dict:
        cfg = self.engine.config
        return {
            "job_id": self.spec.job_id,
            "replica_id": self.spec.replica_id,
            "pid": os.getpid(),
            "engine": {
                "slots": cfg.slots,
                "prompt_buckets": list(cfg.prompt_buckets),
                "max_new_tokens": cfg.max_new_tokens,
                "page_tokens": cfg.page_tokens,
                "paged": cfg.paged,
            },
            "adapters": (
                [e.adapter_id for e in self.registry.entries()]
                if self.registry is not None else []
            ),
        }

    async def _op_probe(self, payload: dict) -> dict:
        probe = await self.batcher.health_probe()
        probe.update({
            "pid": os.getpid(),
            "retry_after_s": self.batcher.retry_after_s(),
            "kv_slack_pages": self.engine.kv_slack_pages(),
            "rpcs_total": self.rpcs_total,
            "stats": self.batcher.stats(),
            "ts": time.time(),
        })
        return probe

    async def _op_generate(self, payload: dict) -> dict:
        from ..serve.engine import GenRequest

        request_id = str(payload["request_id"])
        done = self._completed.get(request_id)
        if done is not None:
            self._completed.move_to_end(request_id)
            return done  # idempotent replay: never decode an id twice
        racing = self._inflight.get(request_id)
        if racing is not None:
            return await asyncio.shield(racing)  # attach to the live attempt
        req = GenRequest(
            request_id=request_id,
            tokens=[int(t) for t in payload["tokens"]],
            max_new_tokens=int(payload.get("max_new_tokens", 32)),
            temperature=float(payload.get("temperature", 0.0)),
            top_k=int(payload.get("top_k", 0)),
            eos_id=payload.get("eos_id"),
            seed=int(payload.get("seed", 0)),
            adapter_id=str(payload.get("adapter_id") or ""),
        )
        deadline_in = payload.get("deadline_in_s")
        # the parent ships a REMAINING budget, not an absolute instant —
        # monotonic clocks are per-process, so the absolute deadline is
        # re-anchored here and stays original-length across a failover
        deadline = (
            time.monotonic() + float(deadline_in)
            if deadline_in is not None else None
        )
        timeout_s = payload.get("timeout_s")
        future = asyncio.get_running_loop().create_future()
        self._inflight[request_id] = future
        try:
            result = await self.batcher.submit(
                req, deadline=deadline,
                timeout_s=None if timeout_s is None else float(timeout_s),
            )
            doc = _result_doc(result)
            self._completed[doc["request_id"]] = doc
            while len(self._completed) > COMPLETED_CACHE:
                self._completed.popitem(last=False)
            if not future.done():
                future.set_result(doc)
            return doc
        except BaseException as exc:
            if not future.done():
                future.set_exception(exc)
                future.exception()  # attached duplicates or nobody: mark seen
            raise
        finally:
            self._inflight.pop(request_id, None)

    async def _op_drain(self, payload: dict) -> dict:
        clean = await self.batcher.drain(
            float(payload.get("timeout_s", 30.0))
        )
        if self.exit_on_drain:
            # reply first, then leave: the response frame is already queued
            # and the exit path closes the server after the write flushes
            asyncio.get_running_loop().call_later(0.05, self.request_exit, 0)
        # final stats ride the reply: the fleet retires this replica's
        # counters from them — a probe-cadence snapshot would lose every
        # request completed since the last health tick (the whole drain
        # window included)
        return {"clean": clean, "stats": self.batcher.stats()}

    async def _op_tenant_busy(self, payload: dict) -> dict:
        busy = await self.batcher.tenant_busy(
            str(payload.get("adapter_id") or "")
        )
        return {"busy": busy}

    def _require_registry(self):
        if self.registry is None:
            raise ValueError(
                "worker has no adapter registry (serve_max_adapters=0)"
            )
        return self.registry

    async def _op_adapter_register(self, payload: dict) -> dict:
        from ..serve.adapters import entry_from_wire

        registry = self._require_registry()
        adapter_id, tree, alpha, rank, meta = entry_from_wire(payload)
        refresh = bool(payload.get("refresh")) \
            and registry.get(adapter_id) is not None
        entry = registry.register(adapter_id, tree, alpha, rank, meta=meta)
        await asyncio.to_thread(self.engine.install_adapter, adapter_id)
        if refresh:
            # tenant rollover: drop the namespace AFTER the atomic stack
            # swap — same ordering rationale as the in-process fleet
            self.engine.drop_prefix_namespace(adapter_id)
        return {"slot": entry.slot}

    async def _op_adapter_unregister(self, payload: dict) -> dict:
        registry = self._require_registry()
        entry = registry.unregister(str(payload["adapter_id"]))
        await asyncio.to_thread(
            self.engine.remove_adapter, entry.adapter_id, entry.slot
        )
        return {"slot": entry.slot}

    async def _op_stack_sync(self, payload: dict) -> dict:
        """Full registry sync (spawn/rollover): install every entry the
        parent registry holds — arriving workers join mid-churn consistent."""
        installed = []
        for doc in payload.get("entries") or []:
            out = await self._op_adapter_register(doc)
            installed.append({"adapter_id": doc["adapter_id"], **out})
        return {"installed": installed}

    # ---- rollout tenant (docs/preference.md §Disaggregated rollouts) -------

    def _require_rollout(self):
        if self.rollout is None:
            raise ValueError(
                "worker is not a rollout tenant (spec has no rollout section)"
            )
        return self.rollout

    async def _op_rollout_start(self, payload: dict) -> dict:
        """Start (or idempotently re-confirm) the producer loop."""
        svc = self._require_rollout()
        return await asyncio.to_thread(
            svc.start, int(payload["pairs_per_round"])
        )

    async def _op_rollout_pull(self, payload: dict) -> dict:
        """Rounds with ``seq > after_seq`` — an idempotent cursor read: a
        re-delivered pull replays the same rounds with the same pair ids."""
        svc = self._require_rollout()
        return await asyncio.to_thread(
            svc.pull, int(payload["after_seq"]),
            int(payload.get("max_rounds", 8)),
        )

    async def _op_rollout_ack(self, payload: dict) -> dict:
        """Trim the outbox through ``up_to_seq`` (monotonic; stale acks no-op)."""
        svc = self._require_rollout()
        return await asyncio.to_thread(svc.ack, int(payload["up_to_seq"]))

    async def _op_rollout_policy_version(self, payload: dict) -> dict:
        """Install a learner-shipped adapter delta (idempotent, monotonic) —
        the fleet-rollover push: megabytes of LoRA, never a model load."""
        svc = self._require_rollout()
        return await asyncio.to_thread(
            svc.push_policy, int(payload["version"]), payload.get("tree")
        )

    # ---- reward tenant -----------------------------------------------------

    def _require_reward(self):
        if self.reward_scorer is None:
            raise ValueError(
                "worker is not a reward tenant (spec has no reward section)"
            )
        return self.reward_scorer

    async def _op_reward_score(self, payload: dict) -> dict:
        """Batched scalar scoring: one forward for a whole rollout round."""
        scorer = self._require_reward()
        scores = await asyncio.to_thread(scorer.score, payload["items"] or [])
        return {"scores": [float(s) for s in scores]}


def _write_transport_file(spec: WorkerSpec, port: int) -> str:
    path = os.path.join(spec.sandbox, TRANSPORT_FILENAME)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump({"host": spec.host, "port": port, "pid": os.getpid()}, f)
    os.replace(tmp, path)
    return path


def build_worker(spec: WorkerSpec, *, exit_on_drain: bool = True) -> WorkerServer:
    """Construct the (warm) engine + batcher + registry from a spec — the
    heavy half of worker startup, shared with in-process protocol tests."""
    from ..resilience.faults import ServeFaultInjector
    from ..serve.adapters import AdapterRegistry
    from ..serve.batcher import Batcher
    from ..serve.engine import BatchEngine, EngineConfig, warm_engine
    from .builders import resolve_builder

    if spec.rollout:
        # rollout tenant: the actor's engine replaces the request batcher —
        # the whole (service, shim-batcher, server) assembly lives with the
        # rest of the data plane in prefs/rollout_plane.py
        from ..prefs.rollout_plane import build_rollout_worker

        return build_rollout_worker(spec, exit_on_drain=exit_on_drain)

    builder = resolve_builder(spec.builder)
    model, variables = builder(**(spec.builder_kwargs or {}))
    registry = None
    if spec.adapters:
        registry = AdapterRegistry(
            int(spec.adapters["capacity"]), int(spec.adapters["max_rank"])
        )
    engine_cfg = EngineConfig(**{
        **spec.engine, "prompt_buckets": tuple(spec.engine["prompt_buckets"]),
    })
    engine = BatchEngine(model, variables, engine_cfg, adapters=registry)
    if spec.warm_start:
        warm_engine(engine)
    fault = ServeFaultInjector.from_env()
    if fault is not None and fault.arm(spec.replica_id, engine,
                                       hard_kill=True):
        logger.warning("worker %s armed with a serve fault (hard kill)",
                       spec.replica_id)
    batcher = Batcher(engine, **(spec.batcher or {}))
    server = WorkerServer(spec, engine, batcher, registry,
                          exit_on_drain=exit_on_drain)
    if spec.reward:
        # reward tenant: the scorer shares the engine's (merged) weights —
        # the head rides separately in the reward job's export
        from ..prefs.rollout_plane import RewardScorer

        server.reward_scorer = RewardScorer.from_artifacts(
            str(spec.reward["artifacts_dir"]), model, variables
        )
    return server


async def _amain(spec: WorkerSpec) -> int:
    # ftc: ignore[blocking-io-in-async-transitive] -- startup path: build_worker (weights + reward-head reads) runs once, before the loop serves anything
    server = build_worker(spec)
    port = await server.start()
    server.start_heartbeat()
    # off the loop: the parent polls for this file, and a slow sandbox disk
    # must not stall the very RPC loop the handshake is about to probe
    await asyncio.to_thread(_write_transport_file, spec, port)
    logger.info("serve worker %s (job=%s) listening on %s:%d pid=%d",
                spec.replica_id, spec.job_id, spec.host, port, os.getpid())
    return await server.serve_until_exit()


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="finetune-controller serve worker (one replica process)"
    )
    parser.add_argument("--spec", required=True,
                        help="path to the worker spec JSON")
    ns = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s [worker] %(name)s: %(message)s",
    )
    spec = WorkerSpec.load(ns.spec)
    os.makedirs(spec.sandbox, exist_ok=True)
    return asyncio.run(_amain(spec))


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    sys.exit(main())
