"""Length-prefixed msgpack/JSON framing for the serve worker protocol.

One frame per message::

    u32 big-endian payload length | payload bytes

The payload is a single document: msgpack when the interpreter has it (it
ships with the flax toolchain, and carries adapter weight blobs as native
``bytes``), JSON with base64-wrapped bytes otherwise — the codec is
negotiated implicitly because both ends run the same image; a mixed
deployment can pin ``FTC_TRANSPORT_CODEC=json``.

Messages are small dicts::

    request:  {"op": str, "id": int, "payload": {...}}
    response: {"id": int, "ok": bool, "payload": {...}}            # success
              {"id": int, "ok": false,
               "error": {"type": str, "message": str, ...extras}}  # failure

``MAX_FRAME`` bounds a single message (adapter stacks are megabytes; a
gigabyte frame is a bug, not a payload) — an oversized length prefix tears
the connection down instead of allocating it.

Byte counters feed the process-wide ``ftc_serve_transport_bytes_total``
metric (``transport.METRICS``).
"""

from __future__ import annotations

import asyncio
import base64
import json
import os
from typing import Any

from . import incr

try:  # pragma: no cover - availability depends on the image
    import msgpack  # type: ignore
# ftc: ignore[silent-except] -- deliberate degrade: the JSON codec below is the documented fallback
except Exception:  # pragma: no cover
    msgpack = None

#: hard per-frame ceiling: large enough for stacked adapter trees, far below
#: anything a model-weight transfer would need (weights never ride this wire
#: — workers stage checkpoints from disk/object store themselves)
MAX_FRAME = 256 * (1 << 20)

_FORCE_JSON = os.environ.get("FTC_TRANSPORT_CODEC", "").strip().lower() == "json"

_B64_KEY = "__ftc_b64__"


def codec_name() -> str:
    return "msgpack" if (msgpack is not None and not _FORCE_JSON) else "json"


def _json_default(obj: Any) -> Any:
    if isinstance(obj, (bytes, bytearray)):
        return {_B64_KEY: base64.b64encode(bytes(obj)).decode("ascii")}
    raise TypeError(f"unserializable wire object: {type(obj)!r}")


def _json_hook(obj: dict) -> Any:
    if len(obj) == 1 and _B64_KEY in obj:
        return base64.b64decode(obj[_B64_KEY])
    return obj


def dumps(obj: Any) -> bytes:
    if msgpack is not None and not _FORCE_JSON:
        return msgpack.packb(obj, use_bin_type=True)
    return json.dumps(obj, default=_json_default).encode("utf-8")


def loads(data: bytes) -> Any:
    if msgpack is not None and not _FORCE_JSON:
        return msgpack.unpackb(data, raw=False, strict_map_key=False)
    return json.loads(data.decode("utf-8"), object_hook=_json_hook)


def tree_to_blob(tree: Any) -> bytes:
    """flax-msgpack blob of a (device or host) pytree — the adapter-delta
    wire format (``serve/adapters.py::entry_to_wire``) reused for rollout
    policy rollover: megabytes of LoRA deltas, never base weights."""
    import jax
    import numpy as np
    from flax import serialization

    host = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), tree)
    return serialization.msgpack_serialize(host)


def tree_from_blob(blob: bytes) -> Any:
    """Inverse of :func:`tree_to_blob`: host-side numpy pytree."""
    from flax import serialization

    return serialization.msgpack_restore(bytes(blob))


class FrameError(RuntimeError):
    """A torn or oversized frame — the connection is unusable afterwards."""


async def write_msg(writer: asyncio.StreamWriter, obj: Any) -> None:
    data = dumps(obj)
    if len(data) > MAX_FRAME:
        raise FrameError(f"frame of {len(data)} bytes exceeds MAX_FRAME")
    writer.write(len(data).to_bytes(4, "big") + data)
    incr("bytes_sent_total", len(data) + 4)
    await writer.drain()


async def read_msg(reader: asyncio.StreamReader) -> Any:
    """Read one frame; raises :class:`asyncio.IncompleteReadError` (EOF) or
    :class:`FrameError` (oversized/torn)."""
    header = await reader.readexactly(4)
    length = int.from_bytes(header, "big")
    if length > MAX_FRAME:
        raise FrameError(f"frame length {length} exceeds MAX_FRAME")
    data = await reader.readexactly(length)
    incr("bytes_received_total", length + 4)
    return loads(data)
