"""Worker payload builders: how a worker process reconstructs its serving
model WITHOUT the parent shipping weights over the wire.

A worker spec names a builder (``"deploy_dir"``, ``"tiny_test"``, or a fully
qualified ``"package.module:callable"``) plus kwargs; the builder returns
``(model, variables)`` ready for :class:`~finetune_controller_tpu.serve.
engine.BatchEngine`.  The two built-ins cover the real path and the test
path:

* ``deploy_dir`` — rebuild from a staged promoted prefix exactly as the
  in-process loader does (``serve/loader.py::load_serving_model``), so a
  process-mode fleet and an in-process fleet decode bit-identically from the
  same artifacts;
* ``tiny_test`` — the deterministic tiny preset (same seed ⇒ same weights in
  every process), which is what makes the cross-process bit-identity proofs
  in ``tests/test_transport.py`` possible without staging checkpoints.

Builders run INSIDE the worker process (its own JAX runtime); everything
here imports jax lazily so the spec-parsing half stays import-light.
"""

from __future__ import annotations

import importlib
from typing import Any, Callable


def tiny_test(preset: str = "tiny-test", seed: int = 0,
              lora_rank: int = 0) -> tuple[Any, dict]:
    """Deterministic tiny model (tests + transport bench): same ``seed`` ⇒
    bit-identical weights in every process on the same backend."""
    import jax
    import jax.numpy as jnp

    from ..models.llama import PRESETS, LlamaForCausalLM
    from ..models.lora import LoRAConfig

    cfg = PRESETS[preset]
    if lora_rank:
        cfg = cfg.replace(lora=LoRAConfig(rank=lora_rank))
    model = LlamaForCausalLM(cfg)
    variables = model.init(
        {"params": jax.random.PRNGKey(seed)}, jnp.zeros((1, 4), jnp.int32)
    )
    return model, variables


def deploy_dir(dir: str, merge_lora: bool = True,
               multi_tenant: bool = False) -> tuple[Any, dict]:
    """Rebuild serving weights from a staged promoted prefix (the parent's
    ``serve/loader.py::fetch_promoted`` output, shared read-only by every
    worker of the fleet).  ``multi_tenant`` strips the job's own LoRA into
    nothing here — the PARENT registry owns the self-adapter and installs it
    through the stack-sync RPC like any other tenant."""
    from ..serve.loader import load_serving_model, strip_lora_for_multitenant

    model, variables, _meta = load_serving_model(
        dir, merge_lora=merge_lora and not multi_tenant
    )
    if multi_tenant:
        model, variables, _tree, _alpha, _rank = \
            strip_lora_for_multitenant(model, variables)
    return model, variables


def rollout_base(dir: str) -> tuple[Any, dict]:
    """Reconstruct the rlhf learner's FROZEN BASE for a remote rollout actor.

    The learner's checkpoints only hold the trainable adapter; the base the
    actor must decode with is written once by ``prefs/rollout_plane.py::
    write_rollout_base`` into ``<artifacts>/rollout_base/`` (model spec JSON
    + flax-msgpack params) — adapter deltas then arrive over the
    ``rollout_policy_version`` RPC, so base weights never ride the wire and
    the actor's step-0 policy is bit-identical to the learner's."""
    import json
    import os

    from flax import serialization

    from ..models.llama import LlamaForCausalLM
    from ..train.cli import build_model_config

    base = os.path.join(dir, "rollout_base")
    with open(os.path.join(base, "model.json")) as f:
        model_spec = json.load(f)
    cfg = build_model_config({"model": model_spec})
    if cfg.image_size:  # pragma: no cover - MM rlhf unsupported
        raise ValueError("rollout_base only supports text-only policies")
    model = LlamaForCausalLM(cfg)
    with open(os.path.join(base, "params.msgpack"), "rb") as f:
        params = serialization.msgpack_restore(f.read())
    return model, {"params": params}


_BUILTINS: dict[str, Callable[..., tuple[Any, dict]]] = {
    "tiny_test": tiny_test,
    "deploy_dir": deploy_dir,
    "rollout_base": rollout_base,
}


def resolve_builder(name: str) -> Callable[..., tuple[Any, dict]]:
    """Builder lookup: a built-in name or ``module:attr``.  Dotted paths are
    how tests and future consumers (rollout actors, pipeline stages) plug in
    payloads; the spec file is written by this process's own transport layer,
    so this is configuration, not an untrusted-input surface."""
    if name in _BUILTINS:
        return _BUILTINS[name]
    if ":" not in name:
        raise ValueError(
            f"unknown payload builder {name!r} "
            f"(built-ins: {sorted(_BUILTINS)}; or use 'module:callable')"
        )
    mod_name, _, attr = name.partition(":")
    mod = importlib.import_module(mod_name)
    fn = getattr(mod, attr, None)
    if not callable(fn):
        raise ValueError(f"payload builder {name!r} is not callable")
    return fn
