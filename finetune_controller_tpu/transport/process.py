"""``ProcessTransport``: worker-process lifecycle on the local host.

One sandbox per replica under the transport root (the local backend's work
dir when the fleet is wired through a backend): the worker spec, the bound
socket's ``transport.json``, the heartbeat, and the process log live there —
the same sandbox shape the training backend gives trainer attempts, so
operators debug a serve worker exactly like a failed job attempt.

Spawn handshake::

    write spec.json → Popen(python -m …transport.worker --spec …)
        → poll for transport.json (bound port + pid)
        → connect + hello → RemoteReplica

bounded by ``serve_worker_spawn_timeout_s``; a worker that dies or stalls
during the handshake is killed and the log tail rides the raised error.

The spawn env is the parent's env (so ``JAX_PLATFORMS``, compilation-cache
settings and the chaos hand's ``FTC_FAULT_SERVE_*`` all cross the process
boundary — the fault-injection satellite) plus per-worker overrides.  Ports:
``serve_worker_port_base`` > 0 assigns ``base + n`` per spawn; 0 (default)
binds ephemeral ports and reads the bound port back from ``transport.json``
— collision-free on shared CI hosts.

The k8s backend does not use this class: it renders one worker POD per
replica (``controller/backends/k8s.py::render_serve_worker_pod``) with the
same spec/env contract, and the fleet dials the pod IP instead.
"""

from __future__ import annotations

import asyncio
import dataclasses
import itertools
import json
import logging
import os
import subprocess
import sys
import time
from pathlib import Path
from typing import Any

from . import TransportError, incr
from .client import RemoteReplica, _Connection
from .worker import TRANSPORT_FILENAME

logger = logging.getLogger(__name__)


def _jax_cache_env() -> dict[str, str]:
    """Forward the parent's persistent-compilation-cache config into worker
    env: workers recompile the same tiny programs otherwise, and the test
    suite's warm cache (tests/conftest.py) must reach spawned workers too."""
    env: dict[str, str] = {}
    try:
        import jax

        cache_dir = jax.config.jax_compilation_cache_dir
        if cache_dir:
            env["JAX_COMPILATION_CACHE_DIR"] = cache_dir
            env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = str(
                jax.config.jax_persistent_cache_min_compile_time_secs
            )
    except Exception:  # pragma: no cover - jax config surface drift
        logger.debug("jax cache env forwarding skipped", exc_info=True)
    return env


@dataclasses.dataclass
class ProcessTransport:
    """Spawns/kills serve worker sandboxes for one fleet."""

    job_id: str
    root: Path
    #: payload builder the workers reconstruct the model with
    #: (``transport/builders.py``): ``{"builder": name, "kwargs": {...}}``
    payload: dict[str, Any]
    port_base: int = 0
    spawn_timeout_s: float = 120.0
    heartbeat_interval_s: float = 2.0
    probe_timeout_s: float = 10.0
    extra_env: dict[str, str] = dataclasses.field(default_factory=dict)
    mode: str = "process"

    def __post_init__(self) -> None:
        self.root = Path(self.root)
        self._ports = itertools.count(self.port_base) \
            if self.port_base > 0 else None

    def set_payload(self, builder: str, kwargs: dict[str, Any]) -> None:
        """Swap the payload NEW spawns build (the rollover path: stage the
        new checkpoint, point the transport at it, then ``fleet.rollover``
        spins the next generation on it)."""
        self.payload = {"builder": builder, "kwargs": dict(kwargs)}

    def _spawn_env(self) -> dict[str, str]:
        env = dict(os.environ)
        env.update(_jax_cache_env())
        # the worker runs `-m finetune_controller_tpu.transport.worker` from
        # its sandbox cwd: make sure the package resolves even when this
        # process imported it off sys.path (source checkout, test run)
        # rather than a site-packages install
        import finetune_controller_tpu as _pkg

        pkg_root = str(Path(_pkg.__file__).resolve().parent.parent)
        existing = env.get("PYTHONPATH", "")
        if pkg_root not in existing.split(os.pathsep):
            env["PYTHONPATH"] = (
                pkg_root + (os.pathsep + existing if existing else "")
            )
        env.update(self.extra_env)
        return env

    async def spawn(
        self,
        replica_id: str,
        generation: int,
        *,
        engine_config,
        batcher_kwargs: dict[str, Any],
        adapters=None,
        warm_start: bool = True,
        rollout: dict[str, Any] | None = None,
        reward: dict[str, Any] | None = None,
    ) -> RemoteReplica:
        """Spawn one worker sandbox and hand back its connected client."""
        sandbox = self.root / f"{replica_id}-g{generation}"
        spec = {
            "job_id": self.job_id,
            "replica_id": replica_id,
            "sandbox": str(sandbox),
            "builder": self.payload["builder"],
            "builder_kwargs": self.payload.get("kwargs") or {},
            "engine": {
                **dataclasses.asdict(engine_config),
                "prompt_buckets": list(engine_config.prompt_buckets),
            },
            # callables (ttft observers) cannot cross the process boundary;
            # worker-side TTFT shows up through probe stats instead
            "batcher": {k: v for k, v in (batcher_kwargs or {}).items()
                        if not callable(v) and v is not None},
            "adapters": (
                {"capacity": adapters.capacity, "max_rank": adapters.max_rank}
                if adapters is not None else None
            ),
            "host": "127.0.0.1",
            "port": next(self._ports) if self._ports is not None else 0,
            "heartbeat_interval_s": self.heartbeat_interval_s,
            "warm_start": warm_start,
        }
        if rollout:
            spec["rollout"] = dict(rollout)
        if reward:
            spec["reward"] = dict(reward)
        spec_path = sandbox / "worker_spec.json"
        log_path = sandbox / "worker.log"

        def stage() -> subprocess.Popen:
            sandbox.mkdir(parents=True, exist_ok=True)
            # a previous incarnation of this replica id (server restart,
            # same job) leaves its transport.json/heartbeat.json behind —
            # the handshake would read the STALE port and dial a dead
            # listener; scrub before the new worker exists
            for stale in ("transport.json", "heartbeat.json"):
                try:
                    os.unlink(sandbox / stale)
                except OSError:
                    pass
            with open(spec_path, "w") as f:
                json.dump(spec, f, indent=2)
            log_f = open(log_path, "ab")
            try:
                return subprocess.Popen(
                    [sys.executable, "-m",
                     "finetune_controller_tpu.transport.worker",
                     "--spec", str(spec_path)],
                    stdout=log_f, stderr=subprocess.STDOUT,
                    stdin=subprocess.DEVNULL, cwd=str(sandbox),
                    env=self._spawn_env(), start_new_session=True,
                )
            finally:
                log_f.close()

        proc = await asyncio.to_thread(stage)
        incr("workers_spawned_total")
        try:
            replica = await self._handshake(
                replica_id, proc, sandbox, log_path
            )
        except BaseException:
            await asyncio.to_thread(self._kill, proc)
            raise
        logger.info(
            "serve worker %s spawned (job=%s gen=%d pid=%d port=%d)",
            replica_id, self.job_id, generation, replica.pid,
            replica.port,
        )
        return replica

    @staticmethod
    def _kill(proc: subprocess.Popen) -> None:
        try:
            proc.kill()
            proc.wait(timeout=5.0)
        except (ProcessLookupError, subprocess.TimeoutExpired, OSError):
            logger.debug("spawn-failure kill raced", exc_info=True)

    def _log_tail(self, log_path: Path, n: int = 12) -> str:
        try:
            lines = log_path.read_text(errors="replace").splitlines()
        except OSError:
            return ""
        return "\n".join(lines[-n:])

    async def _handshake(
        self, replica_id: str, proc: subprocess.Popen, sandbox: Path,
        log_path: Path,
    ) -> RemoteReplica:
        deadline = time.monotonic() + self.spawn_timeout_s
        doc: dict[str, Any] | None = None
        transport_file = sandbox / TRANSPORT_FILENAME
        while time.monotonic() < deadline:
            code = proc.poll()
            if code is not None:
                tail = await asyncio.to_thread(self._log_tail, log_path)
                raise TransportError(
                    f"serve worker {replica_id} exited with code {code} "
                    f"during spawn; log tail:\n{tail}"
                )
            doc = await asyncio.to_thread(self._read_transport_file,
                                          transport_file)
            # belt over the stage-time scrub: only THIS spawn's pid counts
            # — a stale file from a previous incarnation names a dead port
            if doc is not None and int(doc.get("pid") or -1) == proc.pid:
                break
            doc = None
            await asyncio.sleep(0.1)
        if doc is None:
            tail = await asyncio.to_thread(self._log_tail, log_path)
            raise TransportError(
                f"serve worker {replica_id} did not come up within "
                f"{self.spawn_timeout_s:.0f}s "
                f"(serve_worker_spawn_timeout_s); log tail:\n{tail}"
            )
        conn = await _Connection.open(
            doc.get("host", "127.0.0.1"), int(doc["port"]),
            timeout_s=max(5.0, deadline - time.monotonic()),
        )
        hello = await conn.call("hello", {}, timeout_s=30.0)
        replica = RemoteReplica(
            replica_id, conn, hello,
            proc=proc, sandbox=str(sandbox),
            heartbeat_interval_s=self.heartbeat_interval_s,
            probe_timeout_s=self.probe_timeout_s,
            log_path=str(log_path),
        )
        replica.port = int(doc["port"])
        return replica

    @staticmethod
    def _read_transport_file(path: Path) -> dict[str, Any] | None:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        return doc if isinstance(doc, dict) and "port" in doc else None
