# finetune-controller-tpu — one image, three roles (reference ships two
# images, `Dockerfile:28` API + `Dockerfile.monitor:30` monitor, and delegates
# training to user images; here the trainer is in-repo so the same image also
# runs inside the TPU pods):
#
#   API server (default):  python -m finetune_controller_tpu.controller.server
#   monitor daemon:        see Dockerfile.monitor
#   training pod:          python -m finetune_controller_tpu.train.cli --spec ...
#                          (the command the JobSet deployer renders,
#                          controller/backends/k8s.py)
#
# Build:   docker build -t finetune-controller-tpu:latest .
# TPU pods get real chips via the `google.com/tpu` resource; jax[tpu] pulls
# libtpu from the Google releases index.

FROM python:3.12-slim

WORKDIR /app

# native toolchain for the C++ data packer (native/packer.cc)
RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*

COPY pyproject.toml README.md ./
COPY finetune_controller_tpu ./finetune_controller_tpu

RUN pip install --no-cache-dir \
    "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
    && pip install --no-cache-dir ".[control]" pandas \
    && python -c "from finetune_controller_tpu.native.build import ensure_built; ensure_built(quiet=False)"

ENV PYTHONUNBUFFERED=1 \
    FTC_ENVIRONMENT=production

EXPOSE 8787

CMD ["python", "-m", "finetune_controller_tpu.controller.server", \
     "--host", "0.0.0.0", "--port", "8787"]
